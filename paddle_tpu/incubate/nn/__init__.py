"""Fused layers — reference python/paddle/incubate/nn/layer/fused_transformer.py.
On TPU, "fused" = flash-attention Pallas kernel + XLA-fused FFN; these classes
keep the reference API while routing to those paths."""
import jax.numpy as jnp

from ... import nn
from ...framework.core import Tensor
from ...nn import functional as F

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward", "FusedTransformerEncoderLayer",
           "functional"]


class FusedMultiHeadAttention(nn.Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5,
                 normalize_before=False, qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, **kwargs):
        super().__init__()
        self.normalize_before = normalize_before
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        # single fused QKV projection — one MXU matmul
        self.qkv_proj = nn.Linear(embed_dim, 3 * embed_dim, qkv_weight_attr, qkv_bias_attr)
        self.out_proj = nn.Linear(embed_dim, embed_dim, linear_weight_attr, linear_bias_attr)
        self.norm = nn.LayerNorm(embed_dim, epsilon)
        self.dropout = nn.Dropout(dropout_rate)
        self.attn_dropout_rate = attn_dropout_rate

    def forward(self, x, attn_mask=None, cache=None):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        qkv = self.qkv_proj(x)
        B, L = x.shape[0], x.shape[1]
        from ...tensor.manipulation import reshape, split
        qkv = reshape(qkv, [B, L, 3, self.num_heads, self.head_dim])
        q, k, v = (qkv[:, :, i] for i in range(3))
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             dropout_p=self.attn_dropout_rate if self.training else 0.0,
                                             training=self.training)
        out = reshape(out, [B, L, self.embed_dim])
        out = residual + self.dropout(self.out_proj(out))
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(nn.Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-05,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, **kwargs):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = nn.Linear(d_model, dim_feedforward, linear1_weight_attr, linear1_bias_attr)
        self.linear2 = nn.Linear(dim_feedforward, d_model, linear2_weight_attr, linear2_bias_attr)
        self.norm = nn.LayerNorm(d_model, epsilon)
        self.dropout1 = nn.Dropout(act_dropout_rate if act_dropout_rate is not None else dropout_rate)
        self.dropout2 = nn.Dropout(dropout_rate)
        self.activation = getattr(F, activation)

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        src = self.linear2(self.dropout1(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm(src)
        return src


class FusedTransformerEncoderLayer(nn.Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, **kwargs):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate,
            attn_dropout_rate if attn_dropout_rate is not None else dropout_rate,
            normalize_before)
        self.ffn = FusedFeedForward(d_model, dim_feedforward, dropout_rate,
                                    activation=activation,
                                    act_dropout_rate=act_dropout_rate,
                                    normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class functional:
    """incubate.nn.functional namespace."""

    @staticmethod
    def fused_multi_head_attention(*args, **kwargs):
        return F.scaled_dot_product_attention(*args, **kwargs)

    @staticmethod
    def fused_feedforward(x, linear1_weight, linear1_bias, linear2_weight, linear2_bias,
                          ln1_scale=None, ln1_bias=None, ln2_scale=None, ln2_bias=None,
                          dropout1_rate=0.5, dropout2_rate=0.5, activation="relu",
                          training=True, **kwargs):
        h = F.linear(x, linear1_weight, linear1_bias)
        h = getattr(F, activation)(h)
        h = F.dropout(h, dropout1_rate, training=training)
        h = F.linear(h, linear2_weight, linear2_bias)
        return x + F.dropout(h, dropout2_rate, training=training)
