"""Fused layers — reference python/paddle/incubate/nn/layer/fused_transformer.py.
On TPU, "fused" = flash-attention Pallas kernel + XLA-fused FFN; these classes
keep the reference API while routing to those paths."""
import jax.numpy as jnp

from ... import nn
from ...framework.core import Tensor
from ...nn import functional as F

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward", "FusedTransformerEncoderLayer",
           "FusedMultiTransformer", "functional"]


class FusedMultiHeadAttention(nn.Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5,
                 normalize_before=False, qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, **kwargs):
        super().__init__()
        self.normalize_before = normalize_before
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        # single fused QKV projection — one MXU matmul
        self.qkv_proj = nn.Linear(embed_dim, 3 * embed_dim, qkv_weight_attr, qkv_bias_attr)
        self.out_proj = nn.Linear(embed_dim, embed_dim, linear_weight_attr, linear_bias_attr)
        self.norm = nn.LayerNorm(embed_dim, epsilon)
        self.dropout = nn.Dropout(dropout_rate)
        self.attn_dropout_rate = attn_dropout_rate

    def forward(self, x, attn_mask=None, cache=None):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        qkv = self.qkv_proj(x)
        B, L = x.shape[0], x.shape[1]
        from ...tensor.manipulation import reshape, split
        qkv = reshape(qkv, [B, L, 3, self.num_heads, self.head_dim])
        q, k, v = (qkv[:, :, i] for i in range(3))
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             dropout_p=self.attn_dropout_rate if self.training else 0.0,
                                             training=self.training)
        out = reshape(out, [B, L, self.embed_dim])
        out = residual + self.dropout(self.out_proj(out))
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(nn.Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-05,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, **kwargs):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = nn.Linear(d_model, dim_feedforward, linear1_weight_attr, linear1_bias_attr)
        self.linear2 = nn.Linear(dim_feedforward, d_model, linear2_weight_attr, linear2_bias_attr)
        self.norm = nn.LayerNorm(d_model, epsilon)
        self.dropout1 = nn.Dropout(act_dropout_rate if act_dropout_rate is not None else dropout_rate)
        self.dropout2 = nn.Dropout(dropout_rate)
        self.activation = getattr(F, activation)

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        src = self.linear2(self.dropout1(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm(src)
        return src


class FusedTransformerEncoderLayer(nn.Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, **kwargs):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate,
            attn_dropout_rate if attn_dropout_rate is not None else dropout_rate,
            normalize_before)
        self.ffn = FusedFeedForward(d_model, dim_feedforward, dropout_rate,
                                    activation=activation,
                                    act_dropout_rate=act_dropout_rate,
                                    normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedMultiTransformer(nn.Layer):
    """Whole-stack fused transformer (reference
    python/paddle/incubate/nn/layer/fused_transformer.py:627 — the
    multi-layer inference/decode block behind FasterGPT).  TPU-native:
    per-layer weights live STACKED on a leading [num_layers] axis and the
    forward is one lax.scan over layers — flash attention for the
    self-attention, XLA-fused FFN — so the whole stack compiles into a
    single fused program.  Supports decode `caches` ((k, v) buffers per
    the stacked layout) with `time_step` positioning.

    Per-layer *_attrs are honored (list = per layer, single = shared);
    note the TPU-native weight layout: qkv [h, 3h], linear [h, h],
    ffn1 [h, f], ffn2 [f, h] — transpose reference [3, heads, dim, h]
    checkpoints accordingly when assigning."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, ln_bias_attrs=None,
                 qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None,
                 epsilon=1e-5, num_layers=-1, nranks=1, ring_id=-1,
                 name=None):
        super().__init__()
        assert embed_dim > 0 and num_heads > 0 and dim_feedforward > 0
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) \
                if isinstance(qkv_weight_attrs, (list, tuple)) else 1
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.num_layers = num_layers
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.activation = activation
        self.epsilon = epsilon
        from ...nn.initializer import Constant, XavierUniform
        L, h, f = num_layers, embed_dim, dim_feedforward
        one, zero = Constant(1.0), Constant(0.0)
        xav = XavierUniform()

        def mk(shape, attrs, default_init):
            """Stacked [L, *shape] parameter honoring the reference's
            per-layer attrs convention: a list/tuple gives layer i its
            own initializer; a single attr applies to every layer."""
            if attrs is None:
                return self.create_parameter(
                    [L] + shape, default_initializer=default_init)
            from ...nn.layer_base import ParamAttr
            if isinstance(attrs, (list, tuple)):
                if len(attrs) != L:
                    raise ValueError(
                        f"expected {L} per-layer attrs, got {len(attrs)}")
                per = [ParamAttr._to_attr(a) for a in attrs]
            else:
                per = [ParamAttr._to_attr(attrs)] * L
            slices = [(a.initializer or default_init)(shape, "float32")
                      for a in per]
            stacked = jnp.stack([jnp.asarray(s) for s in slices])
            from ...nn.initializer import Assign
            return self.create_parameter(
                [L] + shape, default_initializer=Assign(stacked))

        self.ln_scale = mk([h], ln_scale_attrs, one)
        self.ln_bias = mk([h], ln_bias_attrs, zero)
        self.qkv_weight = mk([h, 3 * h], qkv_weight_attrs, xav)
        self.qkv_bias = mk([3 * h], qkv_bias_attrs, zero)
        self.linear_weight = mk([h, h], linear_weight_attrs, xav)
        self.linear_bias = mk([h], linear_bias_attrs, zero)
        self.ffn_ln_scale = mk([h], ffn_ln_scale_attrs, one)
        self.ffn_ln_bias = mk([h], ffn_ln_bias_attrs, zero)
        self.ffn1_weight = mk([h, f], ffn1_weight_attrs, xav)
        self.ffn1_bias = mk([f], ffn1_bias_attrs, zero)
        self.ffn2_weight = mk([f, h], ffn2_weight_attrs, xav)
        self.ffn2_bias = mk([h], ffn2_bias_attrs, zero)

    def gen_cache(self, batch_size, max_len):
        """Stacked decode KV buffers: (k, v) each
        [num_layers, B, max_len, num_heads, head_dim]."""
        shape = (self.num_layers, batch_size, max_len, self.num_heads,
                 self.head_dim)
        z = jnp.zeros(shape, jnp.dtype(self.qkv_weight.dtype))
        return Tensor(z), Tensor(z)

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        import numpy as np

        import jax

        from ...framework.core import apply_op

        eps = self.epsilon
        H, D = self.num_heads, self.head_dim
        pre = self.normalize_before
        act = self.activation
        have_mask = attn_mask is not None
        have_cache = caches is not None
        step = None
        if time_step is not None:
            step = time_step._value if isinstance(time_step, Tensor) \
                else jnp.asarray(time_step)
        elif have_cache:
            step = jnp.asarray(0)     # prefill: write the cache from pos 0

        rate = float(self.dropout_rate) if self.training else 0.0
        # per-call seed (same convention/limitation as the flash kernel's
        # _next_seed: varies per eager call, a trace-time constant under jit)
        from ...ops.attention import _next_seed
        seed = jnp.uint32(_next_seed() if rate else 0)

        def ln(x, w, b):
            x32 = x.astype(jnp.float32)
            mu = x32.mean(-1, keepdims=True)
            var = x32.var(-1, keepdims=True)
            return ((x32 - mu) * jax.lax.rsqrt(var + eps) * w + b) \
                .astype(x.dtype)

        def drop(t, salt):
            if not rate:
                return t
            # deterministic counter-hash RNG (the repo's cheap dropout —
            # see ops/attention.py): ~8 int ops/elem, no key plumbing
            from ...ops.attention import _hash32, _rate_thresh
            ids = jax.lax.iota(jnp.uint32, t.size).reshape(t.shape)
            keep = _hash32(ids ^ jnp.uint32(salt) ^ seed) \
                >= _rate_thresh(rate)
            return jnp.where(keep, t / (1.0 - rate), 0).astype(t.dtype)

        def run(xv, *rest):
            i = 0
            mask = rest[0] if have_mask else None
            i += 1 if have_mask else 0
            kc = rest[i] if have_cache else None
            vc = rest[i + 1] if have_cache else None
            i += 2 if have_cache else 0
            (ln_w, ln_b, qkv_w, qkv_b, lin_w, lin_b, fln_w, fln_b,
             f1_w, f1_b, f2_w, f2_b) = rest[i:]
            B, Lq = xv.shape[0], xv.shape[1]

            def layer(x, wl):
                (li, ln_w, ln_b, qkv_w, qkv_b, lin_w, lin_b, fln_w, fln_b,
                 f1_w, f1_b, f2_w, f2_b, kci, vci) = wl
                salt0 = li * jnp.uint32(3)
                res = x
                y = ln(x, ln_w, ln_b) if pre else x
                qkv = (y @ qkv_w.astype(y.dtype)
                       + qkv_b.astype(y.dtype)).reshape(B, Lq, 3, H, D)
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                if have_cache:
                    # decode: append at time_step, attend over the prefix
                    kci = jax.lax.dynamic_update_slice(
                        kci, k.astype(kci.dtype), (0, step, 0, 0))
                    vci = jax.lax.dynamic_update_slice(
                        vci, v.astype(vci.dtype), (0, step, 0, 0))
                    Lmax = kci.shape[1]
                    scale = 1.0 / float(np.sqrt(D))
                    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale
                    kh = jnp.swapaxes(kci, 1, 2).astype(jnp.float32)
                    vh = jnp.swapaxes(vci, 1, 2).astype(jnp.float32)
                    s = qh @ jnp.swapaxes(kh, -1, -2)
                    qpos = step + jax.lax.broadcasted_iota(
                        jnp.int32, (Lq, Lmax), 0)
                    kpos = jax.lax.broadcasted_iota(jnp.int32, (Lq, Lmax), 1)
                    s = jnp.where(kpos <= qpos, s, -1e30)
                    if mask is not None:
                        m = mask
                        while m.ndim < 4:
                            m = m[None]
                        if m.dtype == jnp.bool_:
                            s = jnp.where(m, s, -1e30)
                        else:
                            s = s + m.astype(s.dtype)
                    p = jax.nn.softmax(s, axis=-1)
                    attn = jnp.swapaxes(p @ vh, 1, 2).astype(x.dtype)
                else:
                    from ...ops.attention import mha_reference
                    attn = mha_reference(q, k, v, causal=mask is None,
                                         attn_mask=mask)
                attn = attn.reshape(B, Lq, H * D)
                o = attn @ lin_w.astype(attn.dtype) + lin_b.astype(attn.dtype)
                x = res + drop(o, salt0)
                if not pre:
                    x = ln(x, ln_w, ln_b)
                res = x
                y = ln(x, fln_w, fln_b) if pre else x
                hdn = y @ f1_w.astype(y.dtype) + f1_b.astype(y.dtype)
                hdn = drop(getattr(jax.nn, act)(hdn), salt0 + jnp.uint32(1))
                y = hdn @ f2_w.astype(hdn.dtype) + f2_b.astype(hdn.dtype)
                x = res + drop(y, salt0 + jnp.uint32(2))
                if not pre:
                    x = ln(x, fln_w, fln_b)
                return x, (kci, vci)

            L = ln_w.shape[0]
            kc_xs = kc if have_cache else jnp.zeros((L, 0))
            vc_xs = vc if have_cache else jnp.zeros((L, 0))
            xs = (jnp.arange(L, dtype=jnp.uint32), ln_w, ln_b, qkv_w,
                  qkv_b, lin_w, lin_b, fln_w, fln_b,
                  f1_w, f1_b, f2_w, f2_b, kc_xs, vc_xs)
            out, (nk, nv) = jax.lax.scan(layer, xv, xs)
            return out, nk, nv

        params = (self.ln_scale, self.ln_bias, self.qkv_weight,
                  self.qkv_bias, self.linear_weight, self.linear_bias,
                  self.ffn_ln_scale, self.ffn_ln_bias, self.ffn1_weight,
                  self.ffn1_bias, self.ffn2_weight, self.ffn2_bias)
        args = (src,)
        if have_mask:
            args += (attn_mask,)
        if have_cache:
            args += tuple(caches)
        out, nk, nv = apply_op(run, *args, *params)
        if have_cache:
            return out, (nk, nv)
        return out


class functional:
    """incubate.nn.functional namespace."""

    @staticmethod
    def fused_multi_head_attention(*args, **kwargs):
        return F.scaled_dot_product_attention(*args, **kwargs)

    @staticmethod
    def fused_feedforward(x, linear1_weight, linear1_bias, linear2_weight, linear2_bias,
                          ln1_scale=None, ln1_bias=None, ln2_scale=None, ln2_bias=None,
                          dropout1_rate=0.5, dropout2_rate=0.5, activation="relu",
                          training=True, **kwargs):
        h = F.linear(x, linear1_weight, linear1_bias)
        h = getattr(F, activation)(h)
        h = F.dropout(h, dropout1_rate, training=training)
        h = F.linear(h, linear2_weight, linear2_bias)
        return x + F.dropout(h, dropout2_rate, training=training)
