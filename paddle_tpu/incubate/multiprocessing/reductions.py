"""Tensor IPC over multiprocessing — reference
python/paddle/incubate/multiprocessing/reductions.py:33-196.

The reference registers ForkingPickler reducers so Tensors travel
through multiprocessing Queues/Pipes via shared-memory files (CPU) or
CUDA IPC handles (GPU). The TPU-native equivalent: host-side transport
through multiprocessing.shared_memory — the same segment-passing
protocol the io worker pool uses — with the receiving process copying
out and taking ownership of the segment.

One deliberate semantic difference, documented rather than hidden: jax
arrays are immutable and device memory has no cross-process IPC handle
on PJRT, so a received Tensor is a VALUE COPY of the sender's data, not
a view onto shared mutable storage. Code that relied on the reference's
shared-storage mutation (rare; the docs steer users to Queues) must
send updated tensors explicitly.
"""
import atexit
from collections import OrderedDict
from multiprocessing import shared_memory
from multiprocessing.reduction import ForkingPickler

import numpy as np

__all__ = []

# segments created by this process that were never (yet) consumed, with
# creation time: a dead receiver must not leak /dev/shm forever, but a
# normally-exiting sender must not destroy payloads a live receiver
# hasn't rebuilt yet — at exit only segments past the grace window
# (long-undelivered, ergo orphaned) are reclaimed. Receivers normally
# rebuild within milliseconds of Queue.put, so the window only matters
# for fire-and-forget sends to slow consumers.
_SEGMENT_GRACE_S = 120.0
_created_segments = {}


@atexit.register
def _cleanup_segments():
    import time
    now = time.monotonic()
    for name, born in list(_created_segments.items()):
        if now - born < _SEGMENT_GRACE_S:
            continue
        try:
            seg = shared_memory.SharedMemory(name=name)
            seg.close()
            seg.unlink()
        except Exception:
            pass


class LRUSharedCache(OrderedDict):
    """Rebuilt-tensor cache keyed by segment name (reference
    reductions.py:49): a pickle delivered twice within a process
    rebuilds the same Tensor instead of re-attaching a segment the
    first rebuild already unlinked."""

    def __init__(self, limit=128):
        self.limit = limit
        super().__init__()

    def get(self, key):
        if key in self:
            self.move_to_end(key)
            return self[key]
        return None

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        self.move_to_end(key)
        while len(self) > self.limit:
            self.popitem(last=False)


shared_cache = LRUSharedCache()


def _supported_check():
    import sys
    if sys.platform == "win32":
        import warnings
        warnings.warn("paddle_tpu.incubate.multiprocessing needs POSIX "
                      "shared memory; falling back to default pickling")
        return False
    return True


def _np_dtype(name):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bfloat16 & friends register through ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def rebuild_tensor(cls, shm_name, shape, dtype, stop_gradient):
    cached = shared_cache.get(shm_name)
    if cached is not None:
        return cached
    seg = shared_memory.SharedMemory(name=shm_name)
    try:
        arr = np.array(np.ndarray(shape, _np_dtype(dtype), buffer=seg.buf))
    finally:
        seg.close()
        try:
            seg.unlink()  # receiver takes ownership (io _decode_tree protocol)
        except FileNotFoundError:
            pass
    _created_segments.pop(shm_name, None)
    t = cls(arr)
    t.stop_gradient = stop_gradient
    shared_cache[shm_name] = t
    return t


def rebuild_empty(cls, shape, dtype, stop_gradient):
    t = cls(np.zeros(shape, _np_dtype(dtype)))
    t.stop_gradient = stop_gradient
    return t


def reduce_tensor(tensor):
    arr = np.asarray(tensor.numpy())
    if arr.size == 0:
        return (rebuild_empty, (type(tensor), arr.shape, str(arr.dtype),
                                tensor.stop_gradient))
    seg = shared_memory.SharedMemory(create=True, size=arr.nbytes)
    np.ndarray(arr.shape, arr.dtype, buffer=seg.buf)[...] = arr
    name = seg.name
    seg.close()
    import time
    _created_segments[name] = time.monotonic()
    try:
        # ownership transfers to the receiver, which unlinks after the
        # copy-out; drop this process's tracker registration so neither
        # side double-cleans or warns (same dance as io._encode_tree)
        from multiprocessing import resource_tracker
        resource_tracker.unregister("/" + name, "shared_memory")
    except Exception:
        pass
    return (rebuild_tensor, (type(tensor), name, arr.shape, str(arr.dtype),
                             tensor.stop_gradient))


def init_reductions():
    if not _supported_check():
        return
    from ...framework.core import Parameter, Tensor
    ForkingPickler.register(Tensor, reduce_tensor)
    ForkingPickler.register(Parameter, reduce_tensor)
