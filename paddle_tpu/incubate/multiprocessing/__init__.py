"""Reference python/paddle/incubate/multiprocessing/__init__.py: a
drop-in for the stdlib multiprocessing module with Tensor reducers
installed — `import paddle_tpu.incubate.multiprocessing as mp` then use
mp.Process / mp.Queue and put Tensors on them directly.

Spawned children must inherit the parent's PLATFORM, not rediscover it:
the parent may have forced CPU in-process (tests/conftest.py pops the
axon TPU-tunnel backend factory and calls jax.config.update), which a
fresh child knows nothing about — it would initialize jax against the
(single, shared, possibly dead) real chip and hang the queue. Mirroring
__graft_entry__.py:55-62, `get_context`/`Process` here pin
JAX_PLATFORMS + XLA_FLAGS env vars around child start so the child's
jax resolves to the parent's backend before any plugin loads.
"""
import multiprocessing
import os
import sys

from multiprocessing import *  # noqa: F401,F403

from .reductions import init_reductions

__all__ = []
__all__ += multiprocessing.__all__


def _platform_env():
    """Env entries a child needs to land on the parent's jax backend.
    Computed lazily at Process.start() time; a no-op when jax was never
    initialized in the parent (nothing to inherit) or the user already
    pinned JAX_PLATFORMS."""
    env = {}
    jax = sys.modules.get("jax")
    if jax is None:
        return env
    if not os.environ.get("JAX_PLATFORMS"):
        try:
            env["JAX_PLATFORMS"] = jax.default_backend()
        except Exception:
            return env
    # virtual device counts (tests force 8 CPU devices via XLA_FLAGS in
    # os.environ, which spawn children inherit automatically) need no
    # copy; only the in-process platform choice is invisible to them
    return env


class _EnvInheritingProcess:
    """Mixin: set the platform env right before the interpreter for the
    child is launched, restore the parent's env after. Applies to both
    spawn (env captured at Popen time) and fork (inherited address
    space, env harmless)."""

    def start(self):
        injected = {k: v for k, v in _platform_env().items()
                    if k not in os.environ}
        for k, v in injected.items():
            os.environ[k] = v
        try:
            return super().start()
        finally:
            for k in injected:
                os.environ.pop(k, None)


# spawn pickles the Process object by CLASS REFERENCE, so every wrapped
# class must be a real module-level attribute here, not a per-call type()
_WRAPPED = {}
for _method in multiprocessing.get_all_start_methods():
    _base = multiprocessing.get_context(_method).Process
    _cls = type(_base.__name__, (_EnvInheritingProcess, _base),
                {"__module__": __name__})
    globals()[_base.__name__] = _cls
    _WRAPPED[_method] = _cls


class _EnvInheritingContext:
    """Proxy over a multiprocessing context whose Process class injects
    the platform env (everything else delegates). Pool is built with
    THIS proxy as its context so its workers also ride the wrapped
    Process — otherwise `mp.Pool` would bypass the env injection
    entirely."""

    def __init__(self, ctx):
        self._ctx = ctx
        self.Process = _WRAPPED[ctx.get_start_method()]

    def Pool(self, processes=None, initializer=None, initargs=(),
             maxtasksperchild=None):
        from multiprocessing.pool import Pool as _PoolCls
        return _PoolCls(processes, initializer, initargs,
                        maxtasksperchild, context=self)

    def __getattr__(self, name):
        return getattr(self._ctx, name)


def get_context(method=None):
    return _EnvInheritingContext(multiprocessing.get_context(method))


def Pool(processes=None, initializer=None, initargs=(),
         maxtasksperchild=None):
    return get_context().Pool(processes, initializer, initargs,
                              maxtasksperchild)


class Process(_EnvInheritingProcess, multiprocessing.Process):
    __module__ = __name__

init_reductions()
