"""Reference python/paddle/incubate/multiprocessing/__init__.py: a
drop-in for the stdlib multiprocessing module with Tensor reducers
installed — `import paddle_tpu.incubate.multiprocessing as mp` then use
mp.Process / mp.Queue and put Tensors on them directly."""
import multiprocessing

from multiprocessing import *  # noqa: F401,F403

from .reductions import init_reductions

__all__ = []
__all__ += multiprocessing.__all__

init_reductions()
