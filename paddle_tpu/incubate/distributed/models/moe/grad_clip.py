"""Reference incubate/distributed/models/moe/grad_clip.py — the
MoE-aware global-norm clip lives in nn.clip (shared with incubate.moe)."""
from .....nn.clip import ClipGradForMOEByGlobalNorm  # noqa: F401

__all__ = ["ClipGradForMOEByGlobalNorm"]
