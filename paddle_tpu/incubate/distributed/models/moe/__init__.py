"""Reference incubate/distributed/models/moe/__init__.py."""
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .grad_clip import ClipGradForMOEByGlobalNorm  # noqa: F401
from .moe_layer import MoELayer  # noqa: F401
from . import utils  # noqa: F401
