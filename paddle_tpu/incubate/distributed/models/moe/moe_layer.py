"""MoELayer — reference incubate/distributed/models/moe/moe_layer.py:233
(fastmoe lineage: gate -> scatter -> per-expert forward -> gather ->
weighted combine).

TPU-native dispatch: instead of the reference's dynamic MoEScatter/
MoEGather (variable-length per-expert slices, which XLA cannot compile
— shapes must be static), every expert runs over the full token batch
and each token's outputs are combined with its gate weights, with
non-selected experts masked to zero.  That is shape-static, jittable,
and exactly equal numerically (pruned -1 assignments contribute 0,
like the reference's zero-filled gather).  The cost is num_expert/top_k
redundant expert FLOPs — acceptable for the API-compat layer with its
handful of experts per device; the performance path for large E is
models.moe.MoEMLP, whose stacked-weight einsum dispatch pads to
capacity instead (see docs/distributed.md).

Per-rank concepts (`moe_group`/`mp_group` with nranks > 1) raise with
guidance: single-controller JAX holds the full expert set and shards it
over the 'ep'/'tp' mesh axes via pjit/GSPMD instead of splitting state
by process rank.
"""
from ..... import nn
from .....nn import Layer
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer"]


class MoELayer(Layer):
    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, **kwargs):
        super().__init__()
        recompute_interval = kwargs.get("recompute_interval", 0)
        if gate is None:
            gate = dict()
        assert isinstance(gate, (dict, BaseGate)), \
            "gate config' type must be dict or an instance of BaseGate"
        self.group = moe_group
        self.world_size = 1
        if self.group is not None:
            self.world_size = self.group.nranks
        if self.world_size > 1:
            # per-rank expert hosting is a multi-controller concept: this
            # layer's dense dispatch sees only its local experts, so
            # tokens routed to ids >= len(experts) would silently drop.
            # Single-controller JAX holds the FULL expert set and shards
            # it over the 'ep' mesh axis via pjit/GSPMD instead.
            raise NotImplementedError(
                "moe_group with nranks > 1 hosts experts per rank; in "
                "single-controller JAX construct MoELayer with the full "
                "expert list and moe_group=None, then shard over the 'ep' "
                "mesh axis with pjit (docs/distributed.md) — or use "
                "models.moe.MoEMLP, the einsum-dispatch performance path")
        assert experts is not None
        self.num_expert = len(experts)
        self.recompute_interval = recompute_interval
        self.experts = experts
        if mp_group is not None and mp_group.nranks > 1:
            raise NotImplementedError(
                "mp_group slicing is a per-rank concept; shard the "
                "surrounding module over the 'tp' mesh axis with pjit "
                "instead (docs/distributed.md)")
        self.mp_group = mp_group
        self.d_model = d_model

        if isinstance(gate, dict):
            self.top_k = gate.get("top_k", 2)
            kind = gate.get("type", "gshard")
            if kind == "naive" or kind is None:
                gate = NaiveGate(d_model, num_expert=len(experts),
                                 world_size=self.world_size,
                                 topk=self.top_k)
            elif kind == "gshard":
                gate = GShardGate(d_model, num_expert=len(experts),
                                  world_size=self.world_size,
                                  topk=self.top_k, group=self.group)
            elif kind == "switch":
                self.top_k = 1
                gate = SwitchGate(d_model, num_expert=len(experts),
                                  world_size=self.world_size,
                                  topk=1, group=self.group)
            else:
                raise AssertionError(
                    "We only support naive gate, gshard gate and switch "
                    f"gate, but you choose {kind} gate.")
        elif isinstance(gate, NaiveGate):
            self.top_k = gate.top_k
        else:
            raise TypeError("Unimplemented gate type: ", type(gate))
        self.gate = gate

    def forward(self, inp):
        import paddle_tpu as paddle
        assert len(inp.shape) == 3, "MoELayer input must be [batch, seq, d]"
        origin_shape = inp.shape
        x = inp.reshape([-1, origin_shape[-1]])

        value, gate_idx = self.gate(x)          # [T, k] each

        combined = paddle.zeros_like(x)
        for e, expert in enumerate(self.experts):
            sel = (gate_idx == e).astype(value.dtype)       # [T, k]
            w = (value * sel).sum(-1)                       # [T]
            if self.recompute_interval > 0 and self.training:
                from paddle_tpu.distributed.fleet.utils import recompute
                y = recompute(expert, x)
            else:
                y = expert(x)
            combined = combined + y * w.unsqueeze(-1)

        return combined.reshape(origin_shape)
