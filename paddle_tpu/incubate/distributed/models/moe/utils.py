"""Reference incubate/distributed/models/moe/utils.py (fastmoe
count_by_gate / limit_by_capacity), on top of the vectorized routing
ops in paddle_tpu.distributed.models.moe.utils.

Single-controller semantics: with world_size == 1 (or outside a live
shard_map axis) the local and global counts coincide; inside an 'ep'
axis scope the count exchange rides lax collectives, mirroring how
collective.all_reduce treats replicated arrays."""
import paddle_tpu as paddle
from paddle_tpu.distributed.models.moe.utils import (
    _assign_pos, _limit_by_capacity, _number_count,
    _prune_gate_by_capacity)

__all__ = []


def _exchange_counts(counts, group):
    """fastmoe count exchange: a [world_size * num_expert] vector splits
    into world_size chunks of num_expert and each chunk travels to its
    rank — lax.all_to_all(tiled=True) over the expert-parallel axis is
    exactly that shape.  Outside a live axis (eager single-controller,
    counts already global) it is the identity."""
    import jax

    from paddle_tpu.distributed.mesh import current_axis_context
    from paddle_tpu.framework.core import Tensor, apply_op

    axis = group.axis if group is not None else "ep"
    if axis not in current_axis_context():
        return counts

    def f(v):
        return jax.lax.all_to_all(v, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
    return apply_op(f, counts) if isinstance(counts, Tensor) else f(counts)


def count_by_gate(gate, num_expert, world_size, require_pos=True,
                  group=None):
    total_expert_count = num_expert * world_size
    with paddle.no_grad():
        local_expert_count = _number_count(gate, total_expert_count)
        if world_size > 1:
            global_expert_count = _exchange_counts(local_expert_count, group)
        else:
            global_expert_count = local_expert_count
        if not require_pos:
            pos = None
        else:
            lec_cum = paddle.cumsum(local_expert_count, axis=0)
            pos = _assign_pos(gate, lec_cum)
    return pos, local_expert_count, global_expert_count


def limit_by_capacity(topk_idx, num_expert, world_size, capacity,
                      group=None):
    with paddle.no_grad():
        capacity = paddle.ones(shape=[num_expert], dtype="int32") * capacity
        _, lec, gec = count_by_gate(topk_idx, num_expert, world_size,
                                    require_pos=False, group=group)
        new_gec = _limit_by_capacity(gec, capacity, world_size)
        if world_size > 1:
            new_lec = _exchange_counts(new_gec, group)
        else:
            new_lec = new_gec
        topk_idx = _prune_gate_by_capacity(topk_idx, new_lec, num_expert,
                                           world_size)
    return new_lec, new_gec, topk_idx
