"""Gate networks — reference incubate/distributed/models/moe/gate/
{base_gate,naive_gate,switch_gate,gshard_gate}.py (fastmoe lineage).

Same class surface and constructor signatures; the capacity pruning
runs through paddle_tpu.distributed.models.moe.utils (vectorized jnp)
instead of CUDA ops.
"""
import math

from .....nn import Layer, Linear
from ..... import nn


class BaseGate(Layer):
    """Reference gate/base_gate.py:25."""

    def __init__(self, num_expert, world_size):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def forward(self, x):
        raise NotImplementedError("Base gate cannot be directly used for fwd")

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss


class NaiveGate(BaseGate):
    """Linear router returning the raw top-k (value, index) pairs —
    reference gate/naive_gate.py:29."""

    def __init__(self, d_model, num_expert, world_size, topk=2):
        super().__init__(num_expert, world_size)
        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp, return_all_scores=False):
        import paddle_tpu as paddle
        gate = self.gate(inp)
        gate_top_k_val, gate_top_k_idx = paddle.topk(
            gate, k=self.top_k, axis=-1, largest=True, sorted=False)
        if return_all_scores:
            return gate_top_k_val, gate_top_k_idx, gate
        return gate_top_k_val, gate_top_k_idx


class SwitchGate(NaiveGate):
    """Top-1 routing with training noise and load-balance loss —
    reference gate/switch_gate.py:30."""

    def __init__(self, d_model, num_expert, world_size, topk=1,
                 switch_eps=.1, capacity=(1.2, 2.4), group=None):
        assert topk == 1, "topk should be 1 in switch"
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity = capacity
        self.group = group

    def forward(self, inp):
        import paddle_tpu as paddle
        from .utils import limit_by_capacity

        score = self.gate(inp)
        if self.training:
            noise = paddle.rand(shape=score.shape)
            noise = noise * 2 * self.switch_eps + 1.0 - self.switch_eps
            score = score + noise
        score = nn.functional.softmax(score, axis=-1)
        top1_score, top1_idx = paddle.topk(score, k=1, axis=-1, largest=True)

        cap_rate = self.capacity[0 if self.training else 1]
        capacity = math.ceil(cap_rate * inp.shape[0])
        _, _, top1_idx = limit_by_capacity(
            top1_idx, self.num_expert, self.world_size, capacity,
            group=self.group)

        # load-balance loss over the post-prune assignment (reference
        # switch_gate.py:62-76): fraction of tokens vs mean prob, both
        # normalized by the KEPT token count (valid_idx.numel() there) —
        # under heavy pruning the loss must grow, that's its job.
        # kept.sum() is shape-static, so this stays jittable.
        kept = (top1_idx.reshape([-1]) > -1).astype("float32")
        n_kept = paddle.clip(kept.sum(), min=1.0)
        onehot = nn.functional.one_hot(
            paddle.clip(top1_idx.reshape([-1]), 0, self.tot_expert - 1),
            self.tot_expert) * kept.unsqueeze(-1)
        fraction_expert = onehot.sum(0) / n_kept
        prob_expert = score.sum(0) / n_kept
        loss = (fraction_expert * prob_expert).sum() * self.tot_expert
        self.set_loss(loss)
        return top1_score, top1_idx


class GShardGate(NaiveGate):
    """Top-2 routing with gshard aux loss, capacity pruning, and
    random second-expert drop — reference gate/gshard_gate.py:30."""

    def __init__(self, d_model, num_expert, world_size, topk=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        assert topk == 2, "topk should be 2 in gshard"
        super().__init__(d_model, num_expert, world_size)
        self.capacity = capacity
        self.random_routing = random_routing
        self.group = group

    def forward(self, x):
        import paddle_tpu as paddle
        from .....distributed.models.moe.utils import (
            _random_routing as rr_util)
        from .utils import limit_by_capacity

        topk_val, topk_idx, gate_score = super().forward(
            x, return_all_scores=True)
        s = gate_score.shape[0]
        top1_idx = topk_idx.flatten()
        c_e = nn.functional.one_hot(
            top1_idx, self.tot_expert).astype("float32").sum(0) / s
        m_e = nn.functional.softmax(gate_score, axis=1).mean(0)
        loss = (c_e * m_e).mean() * (self.num_expert ** 2)
        self.set_loss(loss)

        cap_rate = self.capacity[0 if self.training else 1]
        capacity = math.ceil(cap_rate * x.shape[0])
        _, _, topk_idx = limit_by_capacity(
            topk_idx, self.num_expert, self.world_size, capacity,
            group=self.group)

        if self.random_routing:
            rand_routing_prob = paddle.rand(shape=[s], dtype="float32")
            topk_idx = rr_util(topk_idx, topk_val, rand_routing_prob)
        return topk_val, topk_idx
