"""Checkpoint/resume — reference python/paddle/incubate/checkpoint +
fleet_executor checkpointing. Orbax-backed: async, sharded-array aware
(each host writes its shards), with keep-N retention — the TPU equivalent of
the reference's per-rank .pdparams dumps."""
import os

import jax
import numpy as np

__all__ = ["CheckpointManager", "save_checkpoint", "load_checkpoint", "auto_checkpoint"]

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:
    _HAS_ORBAX = False


def _to_arrays(tree):
    from ...framework.core import Tensor
    return jax.tree_util.tree_map(
        lambda x: x._value if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


class CheckpointManager:
    """Async sharded checkpointing with retention.

    usage:
        mgr = CheckpointManager("ckpts", max_to_keep=3)
        mgr.save(step, {"model": model.state_dict(), "opt": opt.state_dict()})
        state = mgr.restore_latest()
    """

    def __init__(self, directory, max_to_keep=3, async_save=True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        if _HAS_ORBAX:
            opts = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                enable_async_checkpointing=async_save)
            self._mgr = ocp.CheckpointManager(self.directory, options=opts)
        else:
            self._mgr = None
            self.max_to_keep = max_to_keep

    def save(self, step, state):
        state = _to_arrays(state)
        if self._mgr is not None:
            self._mgr.save(step, args=ocp.args.StandardSave(state))
            return
        # pickle fallback
        import pickle
        path = os.path.join(self.directory, f"ckpt-{step}.pkl")
        with open(path, "wb") as f:
            pickle.dump(jax.tree_util.tree_map(np.asarray, state), f)
        self._gc()

    def _gc(self):
        import re
        entries = sorted(
            (int(m.group(1)), n) for n in os.listdir(self.directory)
            if (m := re.match(r"ckpt-(\d+)\.pkl", n)))
        for _, name in entries[:-self.max_to_keep]:
            os.remove(os.path.join(self.directory, name))

    def latest_step(self):
        if self._mgr is not None:
            return self._mgr.latest_step()
        import re
        steps = [int(m.group(1)) for n in os.listdir(self.directory)
                 if (m := re.match(r"ckpt-(\d+)\.pkl", n))]
        return max(steps) if steps else None

    def restore(self, step, template=None):
        if self._mgr is not None:
            if template is not None:
                return self._mgr.restore(step, args=ocp.args.StandardRestore(_to_arrays(template)))
            return self._mgr.restore(step)
        import pickle
        with open(os.path.join(self.directory, f"ckpt-{step}.pkl"), "rb") as f:
            return pickle.load(f)

    def restore_latest(self, template=None):
        step = self.latest_step()
        if step is None:
            return None
        return self.restore(step, template)

    def wait_until_finished(self):
        if self._mgr is not None:
            self._mgr.wait_until_finished()


def save_checkpoint(directory, step, state, max_to_keep=3):
    CheckpointManager(directory, max_to_keep).save(step, state)


def load_checkpoint(directory, step=None, template=None):
    mgr = CheckpointManager(directory)
    return mgr.restore(step, template) if step is not None else mgr.restore_latest(template)


def auto_checkpoint(func=None, **kwargs):
    """Decorator parity for reference auto_checkpoint; explicit manager preferred."""
    return func if func is not None else (lambda f: f)
