"""paddle_tpu.incubate — reference python/paddle/incubate (fused ops, MoE,
checkpointing, ASP, segment/graph ops, LookAhead/ModelAverage)."""
from . import asp, autograd, autotune, checkpoint, graph, moe, nn, operators, optimizer, tensor  # noqa: F401
from .graph import graph_khop_sampler, graph_reindex, graph_sample_neighbors  # noqa: F401
from .operators import (  # noqa: F401
    graph_send_recv,
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from .tensor import segment_max, segment_mean, segment_min, segment_sum  # noqa: F401

__all__ = ["nn", "checkpoint", "autotune", "asp", "autograd", "operators", "optimizer",
           "tensor", "segment_sum", "segment_mean", "segment_max",
           "segment_min", "graph_send_recv", "graph_khop_sampler",
           "graph_reindex", "graph_sample_neighbors", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle", "LookAhead", "ModelAverage"]



from . import distributed  # noqa: F401,E402
from . import passes  # noqa: F401,E402
