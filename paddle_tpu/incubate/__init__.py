"""paddle_tpu.incubate — reference python/paddle/incubate (fused ops, MoE,
checkpointing). Fused ops map to the Pallas/XLA kernels in paddle_tpu.ops."""
from . import checkpoint, nn  # noqa: F401

__all__ = ["nn", "checkpoint", "autotune"]


def autotune(config=None):
    """XLA autotunes its own tilings; accepted for API parity."""
    return None
