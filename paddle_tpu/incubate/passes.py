"""Reference python/paddle/incubate/passes/ (ir.py: RegisterPass and
fuse-pattern descriptions).  On TPU the IR is StableHLO and operator
fusion is XLA's job — custom fuse patterns are expressed as Pallas
kernels (ops/) or custom ops (incubate.operators) instead of graph
rewrites, so RegisterPass resolves but explains that mapping."""

__all__ = ["ir"]


class _IRModule:
    @staticmethod
    def RegisterPass(function=None, input_specs=None):
        raise NotImplementedError(
            "IR fuse passes rewrite fluid graphs; on TPU write the fused "
            "computation as a Pallas kernel (paddle_tpu.ops) or a custom "
            "op (incubate.operators) — XLA fuses elementwise chains "
            "automatically")


ir = _IRModule()
