"""Functional quasi-Newton minimizers — reference
python/paddle/incubate/optimizer/functional/bfgs.py (minimize_bfgs) and
lbfgs.py (minimize_lbfgs).

TPU-native shape: the ENTIRE minimization is one jit-compiled
lax.while_loop (outer iterations) with a nested lax.while_loop
strong-Wolfe line search (Nocedal & Wright Alg. 3.5/3.6 with bisection
zoom) — static shapes throughout, no host round-trips per iteration.
L-BFGS keeps its (s, y) history in fixed [m, n] ring buffers and runs
the two-loop recursion with lax.fori_loop.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _as_array(x, dtype):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return v.astype(dtype)


def _strong_wolfe(f_and_grad, x, d, f0, g0, alpha0, max_iters,
                  c1=1e-4, c2=0.9):
    """Strong-Wolfe line search along d from x.

    Returns (alpha, f_new, g_new, n_calls). Bracketing loop then a
    bisection zoom, both as lax.while_loops (Nocedal & Wright 3.5/3.6;
    bisection instead of cubic interpolation keeps the trace tiny and is
    robust under fp32 — same convergence class, a few more f evals).
    """
    dtype = f0.dtype
    dg0 = jnp.dot(g0, d).astype(dtype)

    def phi(a):
        f, g = f_and_grad(x + a * d)
        return f.astype(dtype), g, jnp.dot(g, d).astype(dtype)

    # --- bracketing: expand until the minimum is trapped -------------
    #   carry: (a_prev, f_prev, dg_prev, a_cur, iters, calls,
    #           lo, hi, f_lo, dg_lo, done_interval, done_exact,
    #           a_star, f_star, g_star)
    g_zero = jnp.zeros_like(g0)

    def bracket_cond(c):
        (_, _, _, a_cur, it, _, _, _, _, _, done_i, done_e, *_rest) = c
        return (~done_i) & (~done_e) & (it < max_iters) & (a_cur < 1e10)

    def bracket_body(c):
        (a_prev, f_prev, dg_prev, a_cur, it, calls,
         lo, hi, f_lo, dg_lo, done_i, done_e, a_star, f_star, g_star) = c
        f_cur, g_cur, dg_cur = phi(a_cur)
        calls = calls + 1
        armijo_fail = (f_cur > f0 + c1 * a_cur * dg0) | \
                      ((f_cur >= f_prev) & (it > 0))
        strong = jnp.abs(dg_cur) <= -c2 * dg0
        pos_slope = dg_cur >= 0
        # case 1: minimum bracketed between a_prev and a_cur
        new_done_i = armijo_fail | pos_slope
        new_lo = jnp.where(armijo_fail, a_prev, jnp.where(pos_slope, a_cur, lo))
        new_hi = jnp.where(armijo_fail, a_cur, jnp.where(pos_slope, a_prev, hi))
        new_f_lo = jnp.where(armijo_fail, f_prev, jnp.where(pos_slope, f_cur, f_lo))
        new_dg_lo = jnp.where(armijo_fail, dg_prev, jnp.where(pos_slope, dg_cur, dg_lo))
        # case 2: strong Wolfe satisfied outright
        new_done_e = strong & ~armijo_fail
        a_star = jnp.where(new_done_e, a_cur, a_star)
        f_star = jnp.where(new_done_e, f_cur, f_star)
        g_star = jnp.where(new_done_e, g_cur, g_star)
        # case 3: keep expanding
        a_next = jnp.where(new_done_i | new_done_e, a_cur, 2.0 * a_cur)
        return (a_cur, f_cur, dg_cur, a_next, it + 1, calls,
                new_lo, new_hi, new_f_lo, new_dg_lo,
                done_i | new_done_i, done_e | new_done_e,
                a_star, f_star, g_star)

    init = (jnp.zeros((), dtype), f0, dg0, jnp.asarray(alpha0, dtype),
            jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
            jnp.zeros((), dtype), jnp.asarray(alpha0, dtype), f0, dg0,
            jnp.zeros((), bool), jnp.zeros((), bool),
            jnp.asarray(alpha0, dtype), f0, g0)
    (a_prev, f_prev, dg_prev, a_cur, it, calls,
     lo, hi, f_lo, dg_lo, done_i, done_e,
     a_star, f_star, g_star) = jax.lax.while_loop(bracket_cond, bracket_body, init)

    # --- zoom: bisect [lo, hi] until strong Wolfe holds --------------
    def zoom_cond(c):
        lo, hi, f_lo, dg_lo, it, calls, done, a_s, f_s, g_s = c
        return (~done) & (it < max_iters) & (jnp.abs(hi - lo) > 1e-12)

    def zoom_body(c):
        lo, hi, f_lo, dg_lo, it, calls, done, a_s, f_s, g_s = c
        a_mid = 0.5 * (lo + hi)
        f_mid, g_mid, dg_mid = phi(a_mid)
        calls = calls + 1
        armijo_fail = (f_mid > f0 + c1 * a_mid * dg0) | (f_mid >= f_lo)
        strong = jnp.abs(dg_mid) <= -c2 * dg0
        found = strong & ~armijo_fail
        # shrink toward the side keeping the Armijo point
        hi_new = jnp.where(armijo_fail, a_mid,
                           jnp.where(dg_mid * (hi - lo) >= 0, lo, hi))
        lo_new = jnp.where(armijo_fail, lo, a_mid)
        f_lo_new = jnp.where(armijo_fail, f_lo, f_mid)
        dg_lo_new = jnp.where(armijo_fail, dg_lo, dg_mid)
        a_s = jnp.where(found, a_mid, a_s)
        f_s = jnp.where(found, f_mid, f_s)
        g_s = jnp.where(found, g_mid, g_s)
        # even when not strong-Wolfe yet, remember the best Armijo point
        better = (~armijo_fail) & (f_mid < f_s) & ~found
        a_s = jnp.where(better, a_mid, a_s)
        f_s = jnp.where(better, f_mid, f_s)
        g_s = jnp.where(better, g_mid, g_s)
        return (lo_new, hi_new, f_lo_new, dg_lo_new, it + 1, calls,
                done | found, a_s, f_s, g_s)

    # seed the zoom answer with the Armijo endpoint (never worse than x)
    zoom_init = (lo, hi, f_lo, dg_lo, jnp.zeros((), jnp.int32), calls,
                 done_e, jnp.where(done_e, a_star, lo),
                 jnp.where(done_e, f_star, f_lo),
                 jnp.where(done_e, g_star, g_star))
    lo, hi, f_lo, dg_lo, it2, calls, done, a_s, f_s, g_s = \
        jax.lax.while_loop(zoom_cond, zoom_body, zoom_init)
    # if nothing satisfied strong Wolfe, re-evaluate at the best point so
    # (f, g) are consistent with a_s (g_s can be stale when the zoom
    # exhausts its budget); skipped entirely on the success path
    def fallback(_):
        f_fb, g_fb, _dg = phi(a_s)
        return f_fb, g_fb, calls + 1

    f_s, g_s, calls = jax.lax.cond(
        done, lambda _: (f_s, g_s, calls), fallback, None)
    return a_s, f_s, g_s, calls


def _prep(objective_func, initial_position, dtype):
    x0 = _as_array(initial_position, dtype)

    def f_and_grad(x):
        def scalar_f(v):
            out = objective_func(Tensor(v))
            return (out._value if isinstance(out, Tensor) else out).astype(dtype)
        return jax.value_and_grad(scalar_f)(x)
    return x0, f_and_grad


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe", max_line_search_iters=50,
                  initial_step_length=1.0, dtype="float32", name=None):
    """Reference incubate/optimizer/functional/bfgs.py:minimize_bfgs
    (Nocedal & Wright Alg. 6.1) as ONE compiled lax.while_loop.

    Returns (is_converge, num_func_calls, position, objective_value,
    objective_gradient, inverse_hessian_estimate) — Tensor leaves."""
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError("only strong_wolfe line search")
    dtype = jnp.dtype(dtype)
    x0, f_and_grad = _prep(objective_func, initial_position, dtype)
    n = x0.shape[0]
    H0 = jnp.eye(n, dtype=dtype) if initial_inverse_hessian_estimate is None \
        else _as_array(initial_inverse_hessian_estimate, dtype)

    f0, g0 = f_and_grad(x0)

    def cond(c):
        x, f, g, H, it, calls, converged, stalled = c
        return (~converged) & (~stalled) & (it < max_iters)

    def body(c):
        x, f, g, H, it, calls, converged, stalled = c
        d = -(H @ g)
        # safeguard: if d is not a descent direction, restart from -g
        descent = jnp.dot(d, g) < 0
        d = jnp.where(descent, d, -g)
        H = jnp.where(descent, H, jnp.eye(n, dtype=dtype))
        alpha, f_new, g_new, ls_calls = _strong_wolfe(
            f_and_grad, x, d, f, g, initial_step_length,
            max_line_search_iters)
        s = alpha * d
        x_new = x + s
        y = g_new - g
        sy = jnp.dot(s, y)
        rho = jnp.where(sy > 1e-10, 1.0 / jnp.where(sy == 0, 1.0, sy), 0.0)
        I = jnp.eye(n, dtype=dtype)
        V = I - rho * jnp.outer(s, y)
        H_new = jnp.where(rho > 0, V @ H @ V.T + rho * jnp.outer(s, s), H)
        converged = jnp.max(jnp.abs(g_new)) < tolerance_grad
        stalled = (jnp.abs(f_new - f) < tolerance_change) | \
                  (jnp.max(jnp.abs(s)) < tolerance_change)
        return (x_new, f_new, g_new, H_new, it + 1, calls + ls_calls,
                converged, stalled)

    init = (x0, f0, g0, H0, jnp.zeros((), jnp.int32),
            jnp.ones((), jnp.int32), jnp.max(jnp.abs(g0)) < tolerance_grad,
            jnp.zeros((), bool))
    x, f, g, H, it, calls, converged, stalled = jax.jit(
        lambda c: jax.lax.while_loop(cond, body, c))(init)
    is_converge = converged | (jnp.max(jnp.abs(g)) < tolerance_grad)
    return (Tensor(is_converge), Tensor(calls), Tensor(x), Tensor(f),
            Tensor(g), Tensor(H))


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-8, tolerance_change=1e-8,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", max_line_search_iters=50,
                   initial_step_length=1.0, dtype="float32", name=None):
    """Reference incubate/optimizer/functional/lbfgs.py:minimize_lbfgs:
    two-loop recursion over fixed [m, n] (s, y) ring buffers
    (lax.fori_loop), outer lax.while_loop.

    Returns (is_converge, num_func_calls, position, objective_value,
    objective_gradient) — Tensor leaves (no dense inverse Hessian, the
    whole point of the limited-memory variant)."""
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError("only strong_wolfe line search")
    dtype = jnp.dtype(dtype)
    x0, f_and_grad = _prep(objective_func, initial_position, dtype)
    n = x0.shape[0]
    m = int(history_size)
    f0, g0 = f_and_grad(x0)

    def two_loop(g, S, Y, rhos, count, head):
        """H @ g via the L-BFGS two-loop recursion over the ring buffer.
        Entries are ordered newest-first via index arithmetic."""
        q = g
        alphas = jnp.zeros((m,), dtype)

        def bwd(i, qa):
            q, alphas = qa
            idx = (head - 1 - i) % m        # newest -> oldest
            valid = i < count
            a = rhos[idx] * jnp.dot(S[idx], q)
            a = jnp.where(valid, a, 0.0)
            q = q - a * Y[idx]
            return q, alphas.at[idx].set(a)
        q, alphas = jax.lax.fori_loop(0, m, bwd, (q, alphas))
        # initial scaling gamma = s·y / y·y of the most recent pair
        last = (head - 1) % m
        gamma = jnp.where(
            count > 0,
            jnp.dot(S[last], Y[last]) /
            jnp.maximum(jnp.dot(Y[last], Y[last]), 1e-12),
            1.0)
        r = gamma * q

        def fwd(i, r):
            idx = (head - count + i) % m    # oldest -> newest
            valid = i < count
            b = rhos[idx] * jnp.dot(Y[idx], r)
            upd = (alphas[idx] - b) * S[idx]
            return r + jnp.where(valid, 1.0, 0.0) * upd
        return jax.lax.fori_loop(0, m, fwd, r)

    def cond(c):
        x, f, g, S, Y, rhos, count, head, it, calls, converged, stalled = c
        return (~converged) & (~stalled) & (it < max_iters)

    def body(c):
        x, f, g, S, Y, rhos, count, head, it, calls, converged, stalled = c
        d = -two_loop(g, S, Y, rhos, count, head)
        descent = jnp.dot(d, g) < 0
        d = jnp.where(descent, d, -g)
        alpha, f_new, g_new, ls_calls = _strong_wolfe(
            f_and_grad, x, d, f, g, initial_step_length,
            max_line_search_iters)
        s = alpha * d
        y = g_new - g
        sy = jnp.dot(s, y)
        keep = sy > 1e-10
        S = jnp.where(keep, S.at[head % m].set(s), S)
        Y = jnp.where(keep, Y.at[head % m].set(y), Y)
        rhos = jnp.where(
            keep, rhos.at[head % m].set(1.0 / jnp.where(sy == 0, 1.0, sy)),
            rhos)
        head = jnp.where(keep, (head + 1) % m, head)
        count = jnp.where(keep, jnp.minimum(count + 1, m), count)
        x_new = x + s
        converged = jnp.max(jnp.abs(g_new)) < tolerance_grad
        stalled = (jnp.abs(f_new - f) < tolerance_change) | \
                  (jnp.max(jnp.abs(s)) < tolerance_change)
        return (x_new, f_new, g_new, S, Y, rhos, count, head, it + 1,
                calls + ls_calls, converged, stalled)

    init = (x0, f0, g0,
            jnp.zeros((m, n), dtype), jnp.zeros((m, n), dtype),
            jnp.zeros((m,), dtype), jnp.zeros((), jnp.int32),
            jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
            jnp.ones((), jnp.int32), jnp.max(jnp.abs(g0)) < tolerance_grad,
            jnp.zeros((), bool))
    (x, f, g, S, Y, rhos, count, head, it, calls, converged,
     stalled) = jax.jit(lambda c: jax.lax.while_loop(cond, body, c))(init)
    is_converge = converged | (jnp.max(jnp.abs(g)) < tolerance_grad)
    return (Tensor(is_converge), Tensor(calls), Tensor(x), Tensor(f),
            Tensor(g))
