"""Segment reductions — reference python/paddle/incubate/tensor/math.py.

TPU-native: jax.ops.segment_* lowers to one XLA scatter-reduce (the
reference dispatches a CUDA segment kernel per op). num_segments is taken
from the ids tensor so results match the reference's dynamic sizing.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min"]


def _num_segments(segment_ids):
    ids = segment_ids._value if isinstance(segment_ids, Tensor) else segment_ids
    return int(np.asarray(jax.device_get(ids)).max()) + 1 if ids.shape[0] else 0


def segment_sum(data, segment_ids, name=None):
    n = _num_segments(segment_ids)
    return apply_op(lambda d, i: jax.ops.segment_sum(d, i, num_segments=n),
                    data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    n = _num_segments(segment_ids)

    def f(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(i, d.dtype), i, num_segments=n)
        cnt = cnt.reshape((-1,) + (1,) * (d.ndim - 1))
        return s / jnp.maximum(cnt, 1)
    return apply_op(f, data, segment_ids)


def segment_max(data, segment_ids, name=None):
    n = _num_segments(segment_ids)
    return apply_op(lambda d, i: jax.ops.segment_max(d, i, num_segments=n),
                    data, segment_ids)


def segment_min(data, segment_ids, name=None):
    n = _num_segments(segment_ids)
    return apply_op(lambda d, i: jax.ops.segment_min(d, i, num_segments=n),
                    data, segment_ids)
