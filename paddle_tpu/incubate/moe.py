"""paddle_tpu.incubate.moe — reference
python/paddle/incubate/distributed/models/moe (MoELayer, gate classes,
grad clip). Flat namespace here; the implementations live in
models/moe.py (dispatch), models/moe_gate.py (gate policies) and
nn/clip.py (MoE-aware global-norm clip)."""
from ..models.moe import GPTMoE, MoEConfig, MoEMLP  # noqa: F401
from ..models.moe_gate import (  # noqa: F401
    GShardGate, NaiveTopKGate, SwitchGate, make_gate)
from ..nn.clip import ClipGradForMOEByGlobalNorm  # noqa: F401

__all__ = ["MoEConfig", "MoEMLP", "GPTMoE", "NaiveTopKGate", "SwitchGate",
           "GShardGate", "make_gate", "ClipGradForMOEByGlobalNorm"]
