"""Graph-learning sampling ops — reference python/paddle/incubate/operators/
graph_sample_neighbors.py, graph_reindex.py, graph_khop_sampler.py.

These are host-side data-preparation ops (dynamic output shapes — not XLA
territory): numpy implementations feeding device compute, mirroring the
reference's CPU kernels.
"""
import numpy as np

from ..framework.core import Tensor

__all__ = ["graph_sample_neighbors", "graph_reindex", "graph_khop_sampler"]


def _np(x):
    return np.asarray(x._value if isinstance(x, Tensor) else x)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None, perm_buffer=None,
                           sample_size=-1, return_eids=False,
                           flag_perm_buffer=False, name=None):
    """Sample up to sample_size neighbors per input node from a CSC graph."""
    rownp = _np(row).reshape(-1)
    colnp = _np(colptr).reshape(-1)
    nodes = _np(input_nodes).reshape(-1)
    eidnp = _np(eids).reshape(-1) if eids is not None else None
    out_n, out_c, out_e = [], [], []
    for n in nodes:
        beg, end = int(colnp[n]), int(colnp[n + 1])
        neigh = rownp[beg:end]
        eid = eidnp[beg:end] if eidnp is not None else None
        if sample_size != -1 and len(neigh) > sample_size:
            pick = np.random.choice(len(neigh), sample_size, replace=False)
            neigh = neigh[pick]
            eid = eid[pick] if eid is not None else None
        out_n.append(neigh)
        out_c.append(len(neigh))
        if eid is not None:
            out_e.append(eid)
    out_neighbors = Tensor(np.concatenate(out_n) if out_n else np.zeros(0, rownp.dtype))
    out_count = Tensor(np.asarray(out_c, dtype=np.int32))
    if return_eids:
        return out_neighbors, out_count, Tensor(
            np.concatenate(out_e) if out_e else np.zeros(0, np.int64))
    return out_neighbors, out_count


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Renumber (x + neighbors) to contiguous ids with x first."""
    xs = _np(x).reshape(-1)
    ns = _np(neighbors).reshape(-1)
    cnt = _np(count).reshape(-1)
    mapping = {}
    order = []
    for v in xs:
        v = int(v)
        if v not in mapping:
            mapping[v] = len(order)
            order.append(v)
    for v in ns:
        v = int(v)
        if v not in mapping:
            mapping[v] = len(order)
            order.append(v)
    reindex_src = np.asarray([mapping[int(v)] for v in ns], np.int64)
    reindex_dst = np.repeat(
        np.asarray([mapping[int(v)] for v in xs], np.int64), cnt)
    out_nodes = np.asarray(order, xs.dtype)
    return Tensor(reindex_src), Tensor(reindex_dst), Tensor(out_nodes)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes, sorted_eids=None,
                       return_eids=False, name=None):
    """Multi-hop neighbor sampling + subgraph reindex."""
    frontier = _np(input_nodes).reshape(-1)
    all_neigh, all_cnt, all_dst, all_eids = [], [], [], []
    for size in sample_sizes:
        if return_eids:
            neigh, cnt, eid = graph_sample_neighbors(
                row, colptr, Tensor(frontier), eids=sorted_eids,
                sample_size=size, return_eids=True)
            all_eids.append(_np(eid))
        else:
            neigh, cnt = graph_sample_neighbors(
                row, colptr, Tensor(frontier), sample_size=size)
        all_neigh.append(_np(neigh))
        all_cnt.append(_np(cnt))
        all_dst.append(frontier)
        frontier = np.unique(np.concatenate([frontier, _np(neigh)]))
    neighbors = np.concatenate(all_neigh) if all_neigh else np.zeros(0, np.int64)
    counts = np.concatenate(all_cnt) if all_cnt else np.zeros(0, np.int32)
    dsts = np.concatenate(all_dst) if all_dst else np.zeros(0, np.int64)
    src, dst, out_nodes = graph_reindex(Tensor(dsts), Tensor(neighbors), Tensor(counts))
    xs = _np(input_nodes).reshape(-1)
    pos = {int(v): i for i, v in enumerate(_np(out_nodes))}
    reindex_x = Tensor(np.asarray([pos[int(v)] for v in xs], np.int64))
    if return_eids:
        return src, dst, out_nodes, reindex_x, Tensor(np.concatenate(all_eids))
    return src, dst, out_nodes, reindex_x
