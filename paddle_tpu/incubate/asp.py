"""Automatic SParsity (2:4 structured sparsity) — reference
python/paddle/fluid/contrib/sparsity + incubate ASP API.

The reference targets Ampere sparse tensor cores; TPU MXUs have no 2:4
hardware path, so here ASP is a *pruning* workflow with identical masks and
semantics: magnitude-based n:m masks computed per row-block, re-applied
after each optimizer step so pruned weights stay zero. The masked matmul
itself runs dense on the MXU (dense bf16 is the fast path on TPU).
"""
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor

__all__ = ["prune_model", "decorate", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density",
           "create_mask", "check_mask_1d", "check_mask_2d"]

_EXCLUDED = set()
_MASKS = {}  # id(param) -> mask jnp array


def set_excluded_layers(main_program=None, param_names=None):
    for n in param_names or []:
        _EXCLUDED.add(n)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def calculate_density(x):
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    return float((arr != 0).sum() / arr.size) if arr.size else 0.0


def create_mask(tensor, func_name="mask_1d", n=2, m=4):
    """n:m magnitude mask along the last axis (keep n largest of every m)."""
    arr = np.asarray(tensor.numpy() if isinstance(tensor, Tensor) else tensor)
    flat = arr.reshape(-1, arr.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    groups = flat.reshape(flat.shape[0], -1, m)
    order = np.argsort(np.abs(groups), axis=-1)
    mask = np.ones_like(groups, dtype=bool)
    np.put_along_axis(mask, order[..., :m - n], False, axis=-1)
    mask = mask.reshape(flat.shape[0], -1)[:, :cols].reshape(arr.shape)
    return mask


def check_mask_1d(mask, n=2, m=4):
    arr = np.asarray(mask).reshape(-1, np.asarray(mask).shape[-1])
    cols = arr.shape[1]
    pad = (-cols) % m
    if pad:
        arr = np.pad(arr, ((0, 0), (0, pad)), constant_values=0)
    groups = arr.reshape(arr.shape[0], -1, m)
    return bool(((groups != 0).sum(-1) <= n).all())


def check_mask_2d(mask, n=2, m=4):
    return check_mask_1d(mask, n, m) and check_mask_1d(np.asarray(mask).T, n, m)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every prunable weight (>=2D, not excluded)."""
    pruned = {}
    for name, p in model.named_parameters():
        if p.stop_gradient or len(p.shape) < 2 or name in _EXCLUDED:
            continue
        mask = create_mask(p, mask_algo, n, m)
        jmask = jnp.asarray(mask, p._value.dtype)
        p._value = p._value * jmask
        _MASKS[id(p)] = jmask
        pruned[name] = float(mask.mean())
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step so masks are re-applied after every update —
    the reference's OptimizerWithSparsityGuarantee."""
    inner_step = optimizer.step

    def step():
        inner_step()
        for p in optimizer._parameter_list or []:
            mask = _MASKS.get(id(p))
            if mask is not None:
                p._value = p._value * mask

    optimizer.step = step
    return optimizer
