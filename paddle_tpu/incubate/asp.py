"""Automatic SParsity (2:4 structured sparsity) — reference
python/paddle/fluid/contrib/sparsity + incubate ASP API.

The reference targets Ampere sparse tensor cores; TPU MXUs have no 2:4
hardware path, so here ASP is a *pruning* workflow with identical masks and
semantics: magnitude-based n:m masks computed per row-block, re-applied
after each optimizer step so pruned weights stay zero. The masked matmul
itself runs dense on the MXU (dense bf16 is the fast path on TPU).
"""
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor

__all__ = ["prune_model", "decorate", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density",
           "create_mask", "check_mask_1d", "check_mask_2d"]

_EXCLUDED = set()
# id(param) -> (weakref(param), mask). The weakref guards against id
# RECYCLING: CPython reuses a freed parameter's id, so a bare id-keyed
# dict could hand a brand-new parameter a stale (wrong-shaped) mask —
# observed as a test-order-dependent broadcast ValueError.
_MASKS = {}


def _mask_for(p):
    entry = _MASKS.get(id(p))
    if entry is None:
        return None
    ref, mask = entry
    if ref() is not p:      # id recycled by a dead parameter
        del _MASKS[id(p)]
        return None
    return mask


def set_excluded_layers(main_program=None, param_names=None):
    """Exclude layers/params from pruning (reference
    asp/asp.py set_excluded_layers). Accepts either full parameter
    names ('fc.weight') or layer prefixes ('fc', 'backbone.conv1') —
    the reference takes layer names and derives their params."""
    if param_names is None and main_program is not None and \
            not hasattr(main_program, "global_block"):
        # dygraph call style: set_excluded_layers(["fc1", ...])
        param_names, main_program = main_program, None
    for n in param_names or []:
        _EXCLUDED.add(n)


def _is_excluded(param_name):
    if param_name in _EXCLUDED:
        return True
    parts = param_name.split(".")
    return any(".".join(parts[:k]) in _EXCLUDED
               for k in range(1, len(parts)))


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def calculate_density(x):
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    return float((arr != 0).sum() / arr.size) if arr.size else 0.0


def create_mask(tensor, func_name="mask_1d", n=2, m=4):
    """n:m magnitude mask (reference sparsity/utils.py create_mask):
    mask_1d keeps the n largest of every m along the last axis;
    mask_2d_greedy/mask_2d_best keep at most n per row AND per column
    of every m x m block (greedy by magnitude)."""
    arr = np.asarray(tensor.numpy() if isinstance(tensor, Tensor) else tensor)
    if func_name in ("mask_2d_greedy", "get_mask_2d_greedy"):
        return _mask_2d_greedy(arr, n, m)
    if func_name in ("mask_2d_best", "get_mask_2d_best"):
        return _mask_2d_best(arr, n, m)
    if func_name not in ("mask_1d", "get_mask_1d"):
        raise ValueError(
            f"unknown mask algorithm {func_name!r}; expected mask_1d, "
            "mask_2d_greedy or mask_2d_best")
    flat = arr.reshape(-1, arr.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    groups = flat.reshape(flat.shape[0], -1, m)
    order = np.argsort(np.abs(groups), axis=-1)
    mask = np.ones_like(groups, dtype=bool)
    np.put_along_axis(mask, order[..., :m - n], False, axis=-1)
    mask = mask.reshape(flat.shape[0], -1)[:, :cols].reshape(arr.shape)
    return mask


def _mask_2d_greedy(arr, n, m):
    """Per m x m block, admit entries in descending |magnitude| while
    row- and column-budgets (n each) allow — the reference
    get_mask_2d_greedy algorithm."""
    mat = arr.reshape(-1, arr.shape[-1]) if arr.ndim != 2 else arr
    rows, cols = mat.shape
    pr, pc = (-rows) % m, (-cols) % m
    padded = np.pad(mat, ((0, pr), (0, pc)))
    mask = np.zeros_like(padded, dtype=bool)
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            block = np.abs(padded[bi:bi + m, bj:bj + m])
            order = np.dstack(np.unravel_index(
                np.argsort(-block, axis=None), block.shape))[0]
            rbud = np.full(m, n)
            cbud = np.full(m, n)
            for r, c in order:
                if rbud[r] and cbud[c]:
                    mask[bi + r, bj + c] = True
                    rbud[r] -= 1
                    cbud[c] -= 1
    mask = mask[:rows, :cols]
    return mask.reshape(arr.shape)


_BEST_PATTERNS = {}  # (n, m) -> [m x m bool candidates], lazily built


def _mask_2d_best(arr, n, m):
    """Exhaustive per-block search (reference get_mask_2d_best): among
    all masks with exactly n kept per row and per column of the m x m
    block, pick the one maximizing kept |magnitude|.  The candidate set
    is enumerated once per (n, m) — 90 patterns for 2:4."""
    import itertools
    if (n, m) not in _BEST_PATTERNS:
        row_choices = list(itertools.combinations(range(m), n))
        cands = []
        for rows_sel in itertools.product(row_choices, repeat=m):
            colcount = [0] * m
            for sel in rows_sel:
                for c in sel:
                    colcount[c] += 1
            if all(c == n for c in colcount):
                pat = np.zeros((m, m), bool)
                for r, sel in enumerate(rows_sel):
                    pat[r, list(sel)] = True
                cands.append(pat)
        _BEST_PATTERNS[(n, m)] = np.stack(cands)
    cands = _BEST_PATTERNS[(n, m)]

    mat = arr.reshape(-1, arr.shape[-1]) if arr.ndim != 2 else arr
    rows, cols = mat.shape
    pr, pc = (-rows) % m, (-cols) % m
    padded = np.pad(np.abs(mat), ((0, pr), (0, pc)))
    mask = np.zeros_like(padded, dtype=bool)
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            block = padded[bi:bi + m, bj:bj + m]
            scores = (cands * block[None]).sum(axis=(1, 2))
            mask[bi:bi + m, bj:bj + m] = cands[int(scores.argmax())]
    mask = mask[:rows, :cols]
    return mask.reshape(arr.shape)


def check_mask_1d(mask, n=2, m=4):
    arr = np.asarray(mask).reshape(-1, np.asarray(mask).shape[-1])
    cols = arr.shape[1]
    pad = (-cols) % m
    if pad:
        arr = np.pad(arr, ((0, 0), (0, pad)), constant_values=0)
    groups = arr.reshape(arr.shape[0], -1, m)
    return bool(((groups != 0).sum(-1) <= n).all())


def check_mask_2d(mask, n=2, m=4):
    return check_mask_1d(mask, n, m) and check_mask_1d(np.asarray(mask).T, n, m)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every prunable weight (>=2D, not excluded)."""
    pruned = {}
    for name, p in model.named_parameters():
        if p.stop_gradient or len(p.shape) < 2 or _is_excluded(name):
            continue
        mask = create_mask(p, mask_algo, n, m)
        jmask = jnp.asarray(mask, p._value.dtype)
        p._value = p._value * jmask
        import weakref
        _MASKS[id(p)] = (weakref.ref(p), jmask)
        pruned[name] = float(mask.mean())
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step so masks are re-applied after every update —
    the reference's OptimizerWithSparsityGuarantee."""
    inner_step = optimizer.step

    def step():
        inner_step()
        for p in optimizer._parameter_list or []:
            mask = _mask_for(p)
            if mask is not None:
                p._value = p._value * mask

    optimizer.step = step
    return optimizer
