"""Incubate optimizers — reference python/paddle/incubate/optimizer/
{lookahead,modelaverage}.py.

Both wrap an inner optimizer and keep auxiliary parameter copies; updates
are pure jnp expressions so a jitted train step folds them in.
"""
import jax.numpy as jnp

from ..framework.core import Tensor
from ..optimizer.optimizer import Optimizer

from . import functional_optimizer as functional  # noqa: F401
from .functional_optimizer import minimize_bfgs, minimize_lbfgs  # noqa: F401

__all__ = ["LookAhead", "ModelAverage", "functional", "minimize_bfgs",
           "minimize_lbfgs"]


class LookAhead(Optimizer):
    """k steps forward, 1 step back (Zhang et al. 2019).

    slow += alpha * (fast - slow) every k inner steps; fast := slow.
    """

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._k_count = 0
        self._slow = {}
        self._parameter_list = inner_optimizer._parameter_list
        # base-class plumbing expected by inherited helpers
        self._learning_rate = inner_optimizer._learning_rate
        self._accumulators = {}
        self._step_count = 0
        self._slot_names = ()
        self._multi_precision = False
        self._grad_clip = None

    def step(self):
        self.inner_optimizer.step()
        self._k_count += 1
        if self._k_count % self.k:
            return
        for p in self._parameter_list or []:
            if p.stop_gradient:
                continue
            slow = self._slow.get(id(p))
            if slow is None:
                slow = p._value
            slow = slow + self.alpha * (p._value - slow)
            self._slow[id(p)] = slow
            p._value = slow

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def set_lr(self, lr):
        return self.inner_optimizer.set_lr(lr)

    def state_dict(self):
        return {"inner": self.inner_optimizer.state_dict(),
                "k_count": self._k_count}

    def set_state_dict(self, state):
        self.inner_optimizer.set_state_dict(state["inner"])
        self._k_count = state.get("k_count", 0)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, []


class ModelAverage(Optimizer):
    """Maintain a running average of parameters for evaluation (reference
    incubate/optimizer/modelaverage.py). apply()/restore() swap the
    averaged weights in and out."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000, name=None):
        self._parameter_list = list(parameters) if parameters is not None else []
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._sum = {}
        self._cnt = 0
        self._backup = None
        # base-class plumbing expected by inherited helpers
        self._learning_rate = 0.0
        self._accumulators = {}
        self._step_count = 0
        self._slot_names = ()
        self._multi_precision = False
        self._grad_clip = None

    def step(self):
        self._cnt += 1
        for p in self._parameter_list:
            if p.stop_gradient:
                continue
            acc = self._sum.get(id(p))
            f32 = p._value.astype(jnp.float32)
            self._sum[id(p)] = f32 if acc is None else acc + f32
        # bound the window: restart accumulation when it outgrows max_w
        if self._cnt > self.max_w:
            for p in self._parameter_list:
                if id(p) in self._sum:
                    self._sum[id(p)] = p._value.astype(jnp.float32)
            self._cnt = 1

    def apply(self, executor=None, need_restore=True):
        if need_restore:
            self._backup = {id(p): p._value for p in self._parameter_list}
        for p in self._parameter_list:
            acc = self._sum.get(id(p))
            if acc is not None and self._cnt:
                p._value = (acc / self._cnt).astype(p.dtype)
        return self

    def restore(self, executor=None):
        if self._backup:
            for p in self._parameter_list:
                if id(p) in self._backup:
                    p._value = self._backup[id(p)]
        self._backup = None

    def __enter__(self):
        self.apply(need_restore=True)
        return self

    def __exit__(self, *exc):
        self.restore()
        return False

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad
