"""Cost model — reference python/paddle/cost_model/cost_model.py.

The reference profiles a static Program op-by-op against a benchmark JSON.
TPU-native: XLA's compiled cost analysis gives per-program FLOPs/bytes
analytically, and profile_measure times the real jitted program.

This module also hosts the OFFLINE half of config selection:

  * `ChipSpec` / `chip_spec` — the per-generation peak FLOP/s, HBM
    bandwidth/size and interconnect numbers bench.py uses for MFU and
    roofline framing, in one queryable table;
  * `eqn_flops` / `jaxpr_flops` — analytic FLOPs of a traced jaxpr
    (dot/conv priced exactly from shapes, elementwise at 1 flop/elem,
    scan multiplied by trip count) — the compute numerator no chip is
    needed for;
  * `roofline_step_time` — price one training step as
    max(compute-bound, HBM-bound, wire-bound) time (the T3-style
    compute/collective split, arxiv 2401.16677; static per-program cost
    modeling after TPU-MLIR, arxiv 2210.15016). analysis/autotune.py
    ranks (microbatch, remat) candidates with it before anything
    compiles;
  * `collective_wire_bytes` / `collective_wire_split` — ring-model
    bytes-on-the-wire per collective, with DCN-spanning hops priced
    separately from ICI when the mesh axis crosses hosts.
"""
import time
from dataclasses import dataclass

import numpy as np

__all__ = ["CostModel", "collective_wire_bytes", "collective_wire_split",
           "axis_host_count", "ChipSpec", "chip_spec", "CHIP_SPECS",
           "eqn_flops", "jaxpr_flops", "RooflineTime",
           "roofline_step_time", "OverlapRooflineTime",
           "roofline_step_time_overlap", "decode_tick_roofline_s",
           "ragged_tick_legs", "ragged_tick_roofline_s",
           "ragged_chunk_tokens", "decode_horizon", "train_horizon",
           "measured_host_sync_s", "prefill_ttft_s", "kv_restore_s",
           "SLO_SYNC_FRAC", "slo_horizon", "slo_p99_target_s"]


# ------------------------------------------------------------------ chips
#
# Per-chip peak numbers (bf16 MXU FLOP/s, HBM bytes/s and capacity,
# aggregate one-direction ICI bytes/s, per-chip share of the host DCN
# NIC). The flops/HBM columns are the same table bench.py has always
# used for MFU; ICI/DCN are approximate public figures — they feed
# RELATIVE ranking and the wire-bound roofline leg, not accounting.

@dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_flops: float      # bf16 FLOP/s
    hbm_bw: float          # HBM bytes/s
    hbm_bytes: int         # HBM capacity per chip
    ici_bw: float          # aggregate ICI bytes/s per chip (one dir)
    dcn_bw: float          # per-chip share of host DCN bytes/s
    # host<->chip wire (PCIe DMA) bytes/s per chip — the H2D leg the
    # tiered-KV restore pricing (`kv_restore_s`) divides by: a page
    # spilled to pinned host RAM re-mounts at this bandwidth, vs
    # recomputing its span at the MXU roofline. Approximate public
    # figures (PCIe gen3/gen4-class hosts); they feed the RELATIVE
    # restore-vs-recompute decision, not accounting.
    host_bw: float = 1.6e10
    # host-tier READ bytes/s — the extra leg a CROSS-PROCESS shared
    # host tier (serving.fleet.SharedHostKVTier) pays BEFORE the PCIe
    # DMA: the payload lives in an shm-/file-backed store another
    # replica wrote, so a restore first copies it host-RAM -> host-RAM
    # (page-cache read + memcpy, roughly DRAM-copy bandwidth) and only
    # then crosses the wire. Distinct from `host_bw` so
    # `restore_beats_recompute(shared=True)` stays honest for the
    # fleet: the shared read never makes restore cheaper, only
    # costlier, and pricing it at PCIe alone would overclaim the wire.
    host_read_bw: float = 6.4e10


CHIP_SPECS = {
    "v4": ChipSpec("v4", 275e12, 1228e9, 32 << 30, 300e9, 3.1e9,
                   host_bw=1.6e10, host_read_bw=6.4e10),
    "v5e": ChipSpec("v5e", 197e12, 819e9, 16 << 30, 200e9, 3.1e9,
                    host_bw=1.6e10, host_read_bw=6.4e10),
    "v5p": ChipSpec("v5p", 459e12, 2765e9, 95 << 30, 600e9, 3.1e9,
                    host_bw=3.2e10, host_read_bw=1.2e11),
    "v6e": ChipSpec("v6e", 918e12, 1640e9, 32 << 30, 448e9, 3.1e9,
                    host_bw=3.2e10, host_read_bw=1.2e11),
}


def chip_spec(kind=None):
    """Resolve a ChipSpec from an explicit name ("v5e") or a jax
    device_kind string ("TPU v5 lite"). With kind=None, asks the live
    backend; a CPU/no-device environment resolves to v5e (the paper's
    reference chip), so static analysis off-chip prices for the chip
    the campaign targets. Branch order matters: 'v6 lite' must check
    before the generic 'lite' clause or it reads as v5e."""
    if kind is None:
        try:
            import jax
            d = jax.devices()[0]
            if d.platform != "cpu":
                kind = d.device_kind
        except Exception:
            kind = None
    if not kind:
        return CHIP_SPECS["v5e"]
    k = str(kind).lower()
    if k in CHIP_SPECS:
        return CHIP_SPECS[k]
    if "v6" in k:
        return CHIP_SPECS["v6e"]
    if "v5 lite" in k or "v5e" in k or "lite" in k:
        return CHIP_SPECS["v5e"]
    if "v5p" in k or "v5" in k:
        return CHIP_SPECS["v5p"]
    if "v4" in k:
        return CHIP_SPECS["v4"]
    return CHIP_SPECS["v5e"]


# ------------------------------------------------------------ jaxpr flops

def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def eqn_flops(eqn):
    """Analytic executed FLOPs of one jaxpr eqn. dot_general and
    conv_general_dilated are priced exactly from shapes (2*M*N*K per
    contraction); eqns carrying sub-jaxprs recurse (scan multiplied by
    its trip count, cond priced at its most expensive branch);
    everything else is 1 flop per output element — elementwise ops are
    bandwidth-bound on TPU, so their flop count only needs the right
    order of magnitude."""
    name = eqn.primitive.name
    try:
        if name == "dot_general":
            (lc, _rc), (lb, _rb) = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval
            rhs = eqn.invars[1].aval
            batch = _prod(lhs.shape[i] for i in lb)
            k = _prod(lhs.shape[i] for i in lc)
            m = _prod(d for i, d in enumerate(lhs.shape)
                      if i not in set(lc) | set(lb))
            n = _prod(rhs.shape) // max(batch * k, 1)
            return 2 * batch * m * n * k
        if name == "conv_general_dilated":
            out = eqn.outvars[0].aval
            rhs = eqn.invars[1].aval
            dn = eqn.params["dimension_numbers"]
            out_ch = rhs.shape[dn.rhs_spec[0]]
            # per output element: one MAC per (kernel spatial x in-ch)
            return 2 * _prod(out.shape) * (_prod(rhs.shape) // max(out_ch, 1))
        subs = _eqn_sub_jaxprs(eqn)
        if subs:
            inner = [jaxpr_flops(sj) for sj in subs]
            if name == "scan":
                return int(eqn.params.get("length", 1)) * sum(inner)
            if name == "cond":
                return max(inner)
            return sum(inner)
        return _prod(getattr(eqn.outvars[0].aval, "shape", ()))
    except Exception:
        return 0


def _eqn_sub_jaxprs(eqn):
    found = []
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for x in vs:
            tn = type(x).__name__
            if tn == "ClosedJaxpr":
                found.append(x.jaxpr)
            elif tn == "Jaxpr":
                found.append(x)
    return found


def jaxpr_flops(jx):
    """Total analytic FLOPs of a (closed) jaxpr, sub-jaxprs included."""
    jx = jx.jaxpr if hasattr(jx, "jaxpr") else jx
    return sum(eqn_flops(eqn) for eqn in jx.eqns)


# -------------------------------------------------------------- roofline

@dataclass
class RooflineTime:
    """One candidate's step-time breakdown: the step takes at least as
    long as its slowest resource (compute, HBM, interconnect) — XLA
    overlaps the three, so the max is the analytic floor."""
    compute_s: float
    hbm_s: float
    wire_s: float

    @property
    def step_s(self):
        return max(self.compute_s, self.hbm_s, self.wire_s)

    @property
    def bound(self):
        return max((self.compute_s, "compute"), (self.hbm_s, "hbm"),
                   (self.wire_s, "wire"))[1]


def roofline_step_time(flops, hbm_bytes, ici_bytes=0, dcn_bytes=0,
                       chip=None, mxu_efficiency=0.65):
    """Analytic step time: max(compute, HBM, wire) seconds.

    `mxu_efficiency` derates peak FLOP/s for the achievable fraction on
    real schedules (the campaign's best measured MFU on compute-bound
    GPT configs is ~0.64 — rankings are insensitive to the constant,
    absolute tok/s predictions are honest with it). DCN hops are priced
    at DCN bandwidth on top of the ICI time: a multi-host ring's wire
    time is gated by its slowest link."""
    chip = chip if isinstance(chip, ChipSpec) else chip_spec(chip)
    compute = flops / (chip.peak_flops * mxu_efficiency)
    hbm = hbm_bytes / chip.hbm_bw
    wire = ici_bytes / chip.ici_bw + dcn_bytes / chip.dcn_bw
    return RooflineTime(compute_s=compute, hbm_s=hbm, wire_s=wire)


@dataclass
class OverlapRooflineTime:
    """Overlap-AWARE step-time breakdown: the chip streams (compute,
    HBM) still overlap into max(compute, hbm), but only
    ``overlap_frac`` of the wire time hides under them — the rest is
    EXPOSED and adds serially (the two-stream schedule model of
    analysis/schedule.py, after T3's compute/collective split, arxiv
    2401.16677).  ``overlap_frac=1`` collapses to `RooflineTime`'s
    max(); ``overlap_frac=0`` is the fully serialized
    max(compute, hbm) + wire.  step_s is bracketed by construction:
    max(compute, hbm, wire) <= step_s <= max(compute, hbm) + wire."""
    compute_s: float
    hbm_s: float
    wire_s: float
    overlap_frac: float = 1.0

    @property
    def chip_s(self):
        return max(self.compute_s, self.hbm_s)

    @property
    def exposed_wire_s(self):
        return (1.0 - self.overlap_frac) * self.wire_s

    @property
    def step_s(self):
        hidden = self.overlap_frac * self.wire_s
        return max(self.chip_s, hidden) + self.exposed_wire_s

    @property
    def bound(self):
        floor = max((self.compute_s, "compute"), (self.hbm_s, "hbm"),
                    (self.wire_s, "wire"))
        if self.step_s > floor[0] * (1 + 1e-12) and \
                self.exposed_wire_s > 0:
            return "wire-serialized"
        return floor[1]


def roofline_step_time_overlap(flops, hbm_bytes, ici_bytes=0,
                               dcn_bytes=0, overlap_frac=1.0,
                               chip=None, mxu_efficiency=0.65):
    """Overlap-aware analytic step time: the same three legs as
    `roofline_step_time`, with the wire leg only ``overlap_frac``
    hidden behind the chip streams.  `analysis/schedule.py`'s
    two-stream list schedule supplies the fraction from the real
    dependency DAG (`ScheduleEstimate.overlap_frac`); with no
    collectives (or frac 1.0) this is EXACTLY `roofline_step_time` —
    which is why re-pricing single-device candidates through it leaves
    the autotuner's ranking untouched."""
    chip = chip if isinstance(chip, ChipSpec) else chip_spec(chip)
    frac = min(max(float(overlap_frac), 0.0), 1.0)
    return OverlapRooflineTime(
        compute_s=flops / (chip.peak_flops * mxu_efficiency),
        hbm_s=hbm_bytes / chip.hbm_bw,
        wire_s=ici_bytes / chip.ici_bw + dcn_bytes / chip.dcn_bw,
        overlap_frac=frac)


# ------------------------------------------------- chunked-overlap leg

# per-chunk dispatch floor: issuing one more async collective-permute +
# matmul tile costs a scalar-core/launch slot even when the payload is
# tiny — the reason n_chunks cannot grow without bound. Order of
# magnitude of one async op issue; rankings are insensitive to the
# constant, the knee location is honest with it.
CHUNK_LAUNCH_OVERHEAD_S = 1e-6


@dataclass
class ChunkedOverlapTime:
    """Step time of ONE overlapped site decomposed into n_chunks tiles
    (ops/overlap.py): chunk t's transfer rides the wire while chunk
    t+1's matmul runs, so the n-1 interior pairs cost max(compute,
    wire) per chunk — but the FIRST chunk's compute and the LAST
    chunk's transfer have nothing to hide behind (the exposed tails),
    and every chunk pays the launch-overhead floor.  n_chunks=1 is the
    bulk serial sum; n_chunks→inf approaches max(compute, wire) with
    the overhead term eventually winning the argmin back down."""
    compute_s: float
    wire_s: float
    n_chunks: int = 1
    launch_overhead_s: float = CHUNK_LAUNCH_OVERHEAD_S

    @property
    def step_s(self):
        n = max(1, int(self.n_chunks))
        c = self.compute_s / n
        w = self.wire_s / n
        return c + (n - 1) * max(c, w) + w + n * self.launch_overhead_s

    @property
    def serial_s(self):
        """The bulk twin: whole matmul, then the whole collective."""
        return self.compute_s + self.wire_s + self.launch_overhead_s

    @property
    def overlap_frac(self):
        """Fraction of the wire this decomposition hides (the same
        quantity the Schedule Doctor reads off the real DAG)."""
        if self.wire_s <= 0.0:
            return 1.0
        hidden = self.serial_s - self.step_s
        return min(max(hidden / self.wire_s, 0.0), 1.0)


def chunked_overlap_time(compute_s, wire_s, n_chunks=1,
                         launch_overhead_s=CHUNK_LAUNCH_OVERHEAD_S):
    """Price one matmul+collective site at a given chunk count."""
    return ChunkedOverlapTime(compute_s=float(compute_s),
                              wire_s=float(wire_s),
                              n_chunks=max(1, int(n_chunks)),
                              launch_overhead_s=launch_overhead_s)


def best_n_chunks(compute_s, wire_s, max_chunks=64,
                  launch_overhead_s=CHUNK_LAUNCH_OVERHEAD_S):
    """Feasible-fastest chunk count for one overlapped site — the same
    argmin the autotuner runs for microbatch, applied to the n_chunks
    knob: walk 1..max_chunks, keep the step-time minimizer (ties break
    LOW — fewer launches, same time).  Returns (n, ChunkedOverlapTime).
    """
    best = chunked_overlap_time(compute_s, wire_s, 1, launch_overhead_s)
    best_n = 1
    for n in range(2, max(1, int(max_chunks)) + 1):
        t = chunked_overlap_time(compute_s, wire_s, n, launch_overhead_s)
        if t.step_s < best.step_s - 1e-15:
            best, best_n = t, n
    return best_n, best


# ------------------------------------------------------- decode horizon

# Fallback python-dispatch + device->host-fetch cost of one decode sync
# when no measurement is available (order of magnitude of a CPython
# jit-call + np.asarray round-trip on a dev host). The engine's horizon
# only needs the right magnitude: K is capped and bucketed anyway.
DEFAULT_DECODE_SYNC_S = 4e-4

_MEASURED_SYNC = {}


def measured_host_sync_s(force=False):
    """Measure (once per process) the host cost one decode sync pays:
    dispatch a trivial jitted program and fetch its result. This is the
    overhead `decode_horizon` amortizes over K device-resident ticks —
    the 'measured host overhead per sync' leg of the K pricing."""
    if _MEASURED_SYNC and not force:
        return _MEASURED_SYNC["s"]
    try:
        import jax
        import jax.numpy as jnp
        f = jax.jit(lambda x: x + 1)
        x = jnp.zeros((8,), jnp.int32)
        np.asarray(f(x))                     # compile outside the timing
        t0 = time.perf_counter()
        n = 10
        for _ in range(n):
            x = f(x)
            np.asarray(x)
        dt = (time.perf_counter() - t0) / n
    except Exception:
        dt = DEFAULT_DECODE_SYNC_S
    _MEASURED_SYNC["s"] = max(dt, 1e-6)
    return _MEASURED_SYNC["s"]


def decode_tick_roofline_s(step_hbm_bytes, chip=None):
    """Analytic floor of ONE decode tick: decode is HBM-bound (the MXU
    idles), so a tick cannot beat its bytes moved / HBM bandwidth.
    `step_hbm_bytes` is every weight byte + the batch's KV prefix
    (serving.PagedGPTDecoder.step_hbm_bytes supplies it)."""
    chip = chip if isinstance(chip, ChipSpec) else chip_spec(chip)
    return step_hbm_bytes / chip.hbm_bw


def ragged_tick_legs(step_hbm_bytes, new_tokens=0, flops_per_token=0.0,
                     chip=None, mxu_efficiency=0.65):
    """(hbm_s, compute_s) legs of one mixed tick — the pair behind
    `ragged_tick_roofline_s`'s max().  Exposed so the flight-recorder
    pricing can record BOTH the overlapped prediction (max of the
    legs) and the serial one (their sum): the ROOFLINE-DRIFT ledger
    compares the measured tick against the band, telling a mispriced
    leg (measured outside even the serial sum) from a serialized
    schedule (measured at the sum while priced at the max)."""
    chip = chip if isinstance(chip, ChipSpec) else chip_spec(chip)
    hbm = step_hbm_bytes / chip.hbm_bw
    compute = (max(float(new_tokens), 0.0) *
               max(float(flops_per_token), 0.0) /
               (chip.peak_flops * mxu_efficiency))
    return hbm, compute


def ragged_tick_roofline_s(step_hbm_bytes, new_tokens=0,
                           flops_per_token=0.0, chip=None,
                           mxu_efficiency=0.65):
    """Analytic floor of ONE MIXED (ragged) tick, priced on its TOTAL
    new-token count — the packed layout's dispatch unit (pay for
    tokens, not windows): the decode rows keep the tick HBM-bound
    (every weight byte + the batch's KV prefix, the
    `decode_tick_roofline_s` leg), and the tick's `new_tokens` new
    positions (one per decode row + the prefill rows' chunk shares)
    add compute at `flops_per_token` (2x params for a GPT block
    stack). The tick cannot beat the slower leg — max(HBM, token
    compute) — which is exactly why chunking works: while the token
    total's compute fits under the HBM leg, prompt tokens stream into
    the pool at ZERO marginal tick time."""
    hbm, compute = ragged_tick_legs(step_hbm_bytes, new_tokens,
                                    flops_per_token, chip=chip,
                                    mxu_efficiency=mxu_efficiency)
    return max(hbm, compute)


def ragged_chunk_tokens(step_hbm_bytes, flops_per_token, chip=None,
                        mxu_efficiency=0.65, cap=256, floor=8):
    """Default per-tick new-token budget for the ragged scheduler: the
    largest power of two whose compute leg hides under the decode
    tick's HBM leg (those tokens ride 'free' inside the HBM-bound tick
    — `ragged_tick_roofline_s(b, W, f) == decode_tick_roofline_s(b)`),
    clamped to [floor, cap]. The scheduler uses it as the per-slot
    chunk cap, and the PACKED dispatch buckets (`HorizonPlan.
    t_tokens`, pow2 totals) inherit the same hide-under-HBM logic:
    a packed tick whose total stays under this budget adds no
    marginal tick time. `cap` bounds per-tick latency jitter for the
    decode rows sharing the tick; `floor` keeps progress on prompts
    even for models whose tick is compute-tight."""
    chip = chip if isinstance(chip, ChipSpec) else chip_spec(chip)
    hbm = step_hbm_bytes / chip.hbm_bw
    per_tok = (max(float(flops_per_token), 0.0) /
               (chip.peak_flops * mxu_efficiency))
    if per_tok <= 0:
        return int(cap)
    w = int(floor)
    while w * 2 <= int(cap) and (w * 2) * per_tok <= hbm:
        w *= 2
    return w


def decode_horizon(step_hbm_bytes, host_sync_s=None, chip=None,
                   k_cap=32, sync_overhead_frac=0.10,
                   chunk_tokens=0, flops_per_token=0.0):
    """Best multi-step decode horizon K — how many device-resident
    ticks to fuse per host sync (serving.ContinuousBatchingEngine's
    default k_max).

    With K ticks fused, per-token time ≈ t_tick + h/K where t_tick is
    the tick roofline and h the host overhead per sync. Pick the
    smallest K that keeps the sync share at or below
    `sync_overhead_frac` of the tick roofline (h/(K·t_tick) ≤ frac),
    capped at `k_cap` (scheduling granularity: retirement/admission
    latency grows with K, and the engine buckets K to powers of two
    for a bounded compile count). Small models on fast chips price to
    the cap — the tick is so short that ANY host interposition
    dominates; models whose tick dwarfs the sync cost price K=1, where
    the fused loop gains nothing.

    The RAGGED extension: with `chunk_tokens`/`flops_per_token` the
    tick is priced as a MIXED tick (`ragged_tick_roofline_s` — decode
    HBM leg plus the prefill chunk's compute leg), so a scheduler that
    admits prompt chunks into the horizon amortizes the same sync cost
    over its slightly longer ticks (a compute-heavy chunk budget prices
    a smaller K)."""
    import math
    if host_sync_s is None:
        host_sync_s = measured_host_sync_s()
    if chunk_tokens:
        t = ragged_tick_roofline_s(step_hbm_bytes, chunk_tokens,
                                   flops_per_token, chip=chip)
    else:
        t = decode_tick_roofline_s(step_hbm_bytes, chip=chip)
    if t <= 0:
        return int(k_cap)
    k = math.ceil(host_sync_s / (sync_overhead_frac * t))
    return int(min(max(k, 1), int(k_cap)))


# --------------------------------------------------------- SLO classes
#
# Per-class sync-overhead budgets for multi-tenant serving
# (serving.tenancy): the LATENCY tier deliberately accepts a much
# larger host-sync share — syncing more often is exactly what shortens
# the queue-wait/TTFT tail, because admission (and preemption) can only
# happen at horizon boundaries. The THROUGHPUT tier keeps the default
# 10% amortization. Both classes price through the SAME mixed-tick
# roofline (`ragged_tick_roofline_s` via `decode_horizon`), so the
# per-class targets are roofline-DERIVED, not hand-tuned constants.

SLO_SYNC_FRAC = {"latency": 0.5, "throughput": 0.10}


def slo_horizon(step_hbm_bytes, slo, host_sync_s=None, chip=None,
                k_cap=32, chunk_tokens=0, flops_per_token=0.0):
    """Per-SLO-class decode horizon K: `decode_horizon` priced with the
    class's sync-overhead budget (`SLO_SYNC_FRAC`). The latency tier's
    smaller K bounds how long a newly arrived latency prompt can sit
    in the queue before the next admission boundary; the throughput
    tier amortizes the sync like the single-tenant engine."""
    frac = SLO_SYNC_FRAC.get(slo)
    if frac is None:
        raise ValueError(f"unknown SLO class {slo!r}; known: "
                         f"{sorted(SLO_SYNC_FRAC)}")
    return decode_horizon(step_hbm_bytes, host_sync_s=host_sync_s,
                          chip=chip, k_cap=k_cap,
                          sync_overhead_frac=frac,
                          chunk_tokens=chunk_tokens,
                          flops_per_token=flops_per_token)


def slo_p99_target_s(step_hbm_bytes, slo, host_sync_s=None, chip=None,
                     k_cap=32, chunk_tokens=0, flops_per_token=0.0):
    """Roofline-derived per-class p99 target for one horizon boundary:
    the class's K ticks at the mixed-tick roofline plus one host sync
    — the longest a request of that class should wait between two
    scheduling opportunities on a correctly composed engine. The
    multi-tenant bench reports measured per-class p99 NEXT to this
    number (serving.tenancy.TenantEngine.tenancy_summary), so a
    violated target points at composition, not at a hand-tuned
    constant."""
    if host_sync_s is None:
        host_sync_s = measured_host_sync_s()
    k = slo_horizon(step_hbm_bytes, slo, host_sync_s=host_sync_s,
                    chip=chip, k_cap=k_cap, chunk_tokens=chunk_tokens,
                    flops_per_token=flops_per_token)
    tick = ragged_tick_roofline_s(step_hbm_bytes, chunk_tokens,
                                  flops_per_token, chip=chip)
    return k * tick + host_sync_s


def prefill_ttft_s(prompt_tokens, flops_per_token, cached_frac=0.0,
                   chip=None, host_sync_s=None, mxu_efficiency=0.65):
    """Analytic time-to-first-token of one prompt: the compute roofline
    of the UNCACHED prompt span plus one host sync.

    `cached_frac` is the prefix-cache hit fraction of the prompt
    (serving.ServeStats.prefix_hit_rate view): cached pages are mounted
    into the page table HOST-side — zero device FLOPs — so prefill
    compute scales with the (1 - cached_frac) remainder. A full hit
    still re-consumes one position for logits, which the one-sync floor
    absorbs. This is the pricing half of the prefix cache: TTFT and
    prefill FLOPs both collapse linearly with hit rate (the bench
    scenario's committed JSON lines measure the same curve)."""
    chip = chip if isinstance(chip, ChipSpec) else chip_spec(chip)
    if host_sync_s is None:
        host_sync_s = measured_host_sync_s()
    frac = min(max(float(cached_frac), 0.0), 1.0)
    uncached = max(float(prompt_tokens), 0.0) * (1.0 - frac)
    compute = (uncached * max(float(flops_per_token), 0.0)
               / (chip.peak_flops * mxu_efficiency))
    return compute + host_sync_s


def kv_restore_s(restore_bytes, chip=None, shared=False):
    """Analytic floor of re-mounting spilled KV pages from pinned host
    RAM: bytes over the host<->chip wire (`ChipSpec.host_bw` — the PCIe
    DMA leg). The tiered-KV admission compares this against the
    recompute price of the same span (`prefill_ttft_s` with no sync
    floor: the ragged path has no extra sync either way) and restores
    only when the wire beats the prefill — big-model pages win (KV
    bytes/token are fixed but recompute FLOPs grow with params), tiny
    models recompute (serving.kv_tier owns the decision; ServeStats
    tier_restores/tier_recomputes make it observable).

    `shared=True` prices the CROSS-PROCESS tier
    (serving.fleet.SharedHostKVTier): the payload sits in an shm-/
    file-backed store another replica wrote, so the restore pays a
    host-RAM read leg (`ChipSpec.host_read_bw`) before the DMA — the
    two legs are serial (read, then enqueue H2D), so they add."""
    chip = chip if isinstance(chip, ChipSpec) else chip_spec(chip)
    b = max(float(restore_bytes), 0.0)
    t = b / chip.host_bw
    if shared:
        t += b / chip.host_read_bw
    return t


def train_horizon(step_s, host_sync_s=None, n_cap=32,
                  sync_overhead_frac=0.10):
    """Best multi-step TRAINING horizon N — how many fused train steps
    `Trainer.step_multi` should scan per host dispatch (the `decode_horizon`
    pricing applied to training: `step_s` is the step's analytic floor,
    normally `roofline_step_time(...).step_s`, though a measured step
    time prices identically).

    With N steps fused, per-step overhead ≈ h/N where h is the host
    cost of one dispatch+fetch sync (`measured_host_sync_s`). Pick the
    smallest N that keeps the sync share at or below
    `sync_overhead_frac` of the step floor (h/(N·step_s) ≤ frac),
    capped at `n_cap` (horizon granularity: logging/checkpoint/callback
    latency grows with N, and each distinct N compiles one scan
    program). Small models price to the cap — eager host overhead
    dominates their step; a 1.3B step dwarfs the sync cost and prices
    N=1, where fusing gains nothing."""
    import math
    if host_sync_s is None:
        host_sync_s = measured_host_sync_s()
    if step_s is None or step_s <= 0:
        return int(n_cap)
    n = math.ceil(host_sync_s / (sync_overhead_frac * step_s))
    return int(min(max(n, 1), int(n_cap)))


# jaxpr primitive names -> the StableHLO collective they lower to, so
# callers can query with either vocabulary (the memory/sharding passes
# walk jaxprs, the HLO analyzers walk StableHLO text)
_COLLECTIVE_ALIASES = {
    "psum": "all_reduce",
    "pmax": "all_reduce",
    "pmin": "all_reduce",
    "ppermute": "collective_permute",
    "pshuffle": "collective_permute",
    "psum_scatter": "reduce_scatter",
    "pbroadcast": "collective_broadcast",
    "all_gather_invariant": "all_gather",
}


def collective_wire_bytes(op, payload_bytes, group_size):
    """Analytic bytes-on-the-wire per participating device for one
    collective, assuming the bandwidth-optimal ring algorithms XLA uses
    on ICI (the offline half of the T3-style compute/collective split;
    paddle_tpu.analysis cross-checks lowered programs against this).

    all_reduce      ring reduce-scatter + all-gather: 2(n-1)/n * payload
    all_gather      (n-1)/n * full gathered payload
    reduce_scatter  (n-1)/n * full pre-scatter payload
    all_to_all      (n-1)/n * payload (each device keeps 1/n)
    collective_permute / broadcast: one payload hop

    `payload_bytes` is the FULL (gathered/unreduced) array size for
    every op. group_size<=1 is a degenerate group (XLA folds the op to
    a copy): 0 wire bytes. jaxpr primitive names (psum, ppermute,
    psum_scatter, ...) are accepted as aliases.
    """
    try:
        n = int(group_size or 1)
    except (TypeError, ValueError):
        n = 1
    if n <= 1 or not payload_bytes or payload_bytes <= 0:
        return 0
    op = _COLLECTIVE_ALIASES.get(op, op)
    frac = (n - 1) / n
    factor = {
        "all_reduce": 2 * frac,
        "all_gather": frac,
        "reduce_scatter": frac,
        "all_to_all": frac,
        "collective_permute": 1.0,
        "collective_broadcast": 1.0,
    }.get(op, 1.0)
    return int(payload_bytes * factor)


def collective_wire_split(op, payload_bytes, group_size, host_count=1):
    """ICI/DCN split of `collective_wire_bytes`: a ring over n devices
    spanning h hosts crosses a host boundary on h of its n hops, so
    h/n of the wire volume rides DCN and the rest stays on ICI (the
    ROADMAP "multi-host memory model" item — every hop used to be
    priced at ICI cost). h<=1 (chip-local axis) puts everything on ICI.
    Returns {"ici": bytes, "dcn": bytes}."""
    total = collective_wire_bytes(op, payload_bytes, group_size)
    try:
        n = max(int(group_size or 1), 1)
        h = max(int(host_count or 1), 1)
    except (TypeError, ValueError):
        n, h = 1, 1
    if total <= 0 or h <= 1 or n <= 1:
        return {"ici": total, "dcn": 0}
    dcn = int(total * min(h, n) / n)
    return {"ici": total - dcn, "dcn": dcn}


def axis_host_count(mesh, axis):
    """How many hosts one line of `axis` spans in this mesh — the h of
    `collective_wire_split`. Walks mesh.devices along the axis with all
    other axes held at 0 and counts distinct process indexes (duck-typed:
    anything with .axis_names and a .devices ndarray of objects carrying
    .process_index works, so multi-host topologies are testable offline).
    Unknown axes or failures fall back to 1 (chip-local)."""
    try:
        names = list(mesh.axis_names)
        if axis not in names:
            return 1
        devs = mesh.devices
        idx = [0] * devs.ndim
        ax = names.index(axis)
        procs = set()
        for i in range(devs.shape[ax]):
            idx[ax] = i
            procs.add(getattr(devs[tuple(idx)], "process_index", 0))
        return max(len(procs), 1)
    except Exception:
        return 1


class CostModel:
    def build_program(self):
        from . import static
        from . import nn, optimizer
        import paddle_tpu as paddle

        paddle.enable_static()
        x = static.data("cost_model_X", [16, 1], "float32")
        lin = nn.Linear(1, 10)
        hidden = lin(x)
        loss = paddle.mean(hidden)
        optimizer.SGD(learning_rate=0.01, parameters=lin.parameters()).minimize(loss)
        self._feed = {"cost_model_X": np.ones((16, 1), np.float32)}
        self._fetch = [loss]
        return static.default_startup_program(), static.default_main_program()

    def profile_measure(self, startup_program=None, main_program=None,
                        device="tpu", fetch_cost_list=("time",)):
        from . import static
        exe = static.Executor()
        exe.run(main_program, feed=self._feed, fetch_list=self._fetch)  # compile
        t0 = time.perf_counter()
        for _ in range(10):
            exe.run(main_program, feed=self._feed, fetch_list=self._fetch)
        dt = (time.perf_counter() - t0) / 10
        return {"time": dt * 1e3}  # ms, like the reference's time cost

    _OP_BENCH = {
        # op -> (builder returning (fn, args)); timed lazily on first query
        "matmul": lambda jnp, rng: (lambda a, b: a @ b,
                                    (rng((256, 256)), rng((256, 256)))),
        "relu": lambda jnp, rng: (lambda a: jnp.maximum(a, 0), (rng((512, 512)),)),
        "softmax": lambda jnp, rng: (lambda a: jnp.exp(a - a.max(-1, keepdims=True))
                                     / jnp.exp(a - a.max(-1, keepdims=True)).sum(-1, keepdims=True),
                                     (rng((512, 512)),)),
        "layer_norm": lambda jnp, rng: (
            lambda a: (a - a.mean(-1, keepdims=True))
            / jnp.sqrt(a.var(-1, keepdims=True) + 1e-5), (rng((512, 512)),)),
        "elementwise_add": lambda jnp, rng: (lambda a, b: a + b,
                                             (rng((512, 512)), rng((512, 512)))),
    }

    def static_cost_data(self):
        """Measured per-op microbenchmark table (reference reads a shipped
        benchmark JSON; here the ops are timed on the live backend once)."""
        if not hasattr(self, "_static_costs"):
            self._static_costs = {
                name: self._time_op(name) for name in self._OP_BENCH}
        return self._static_costs

    def _time_op(self, op_name, forward=True, dtype="float32"):
        import jax
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        fn, args = self._OP_BENCH[op_name](
            jnp, lambda shape: jnp.asarray(rng.randn(*shape), dtype))
        if not forward:
            fwd = fn
            fn = jax.grad(lambda *a: jnp.sum(fwd(*a)).astype(jnp.float32))
        jfn = jax.jit(fn)
        jax.block_until_ready(jfn(*args))    # compile
        t0 = time.perf_counter()
        for _ in range(20):
            out = jfn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / 20 * 1e3   # ms

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        if op_name not in self._OP_BENCH:
            return {"op_time": "0"}
        cache = getattr(self, "_op_cost_cache", None)
        if cache is None:
            cache = self._op_cost_cache = {}
        key = (op_name, forward, dtype)
        if key not in cache:
            cache[key] = self._time_op(op_name, forward=forward, dtype=dtype)
        return {"op_time": str(cache[key])}
