"""Cost model — reference python/paddle/cost_model/cost_model.py.

The reference profiles a static Program op-by-op against a benchmark JSON.
TPU-native: XLA's compiled cost analysis gives per-program FLOPs/bytes
analytically, and profile_measure times the real jitted program.
"""
import time

import numpy as np

__all__ = ["CostModel", "collective_wire_bytes"]


# jaxpr primitive names -> the StableHLO collective they lower to, so
# callers can query with either vocabulary (the memory/sharding passes
# walk jaxprs, the HLO analyzers walk StableHLO text)
_COLLECTIVE_ALIASES = {
    "psum": "all_reduce",
    "pmax": "all_reduce",
    "pmin": "all_reduce",
    "ppermute": "collective_permute",
    "pshuffle": "collective_permute",
    "psum_scatter": "reduce_scatter",
    "pbroadcast": "collective_broadcast",
    "all_gather_invariant": "all_gather",
}


def collective_wire_bytes(op, payload_bytes, group_size):
    """Analytic bytes-on-the-wire per participating device for one
    collective, assuming the bandwidth-optimal ring algorithms XLA uses
    on ICI (the offline half of the T3-style compute/collective split;
    paddle_tpu.analysis cross-checks lowered programs against this).

    all_reduce      ring reduce-scatter + all-gather: 2(n-1)/n * payload
    all_gather      (n-1)/n * full gathered payload
    reduce_scatter  (n-1)/n * full pre-scatter payload
    all_to_all      (n-1)/n * payload (each device keeps 1/n)
    collective_permute / broadcast: one payload hop

    `payload_bytes` is the FULL (gathered/unreduced) array size for
    every op. group_size<=1 is a degenerate group (XLA folds the op to
    a copy): 0 wire bytes. jaxpr primitive names (psum, ppermute,
    psum_scatter, ...) are accepted as aliases.
    """
    try:
        n = int(group_size or 1)
    except (TypeError, ValueError):
        n = 1
    if n <= 1 or not payload_bytes or payload_bytes <= 0:
        return 0
    op = _COLLECTIVE_ALIASES.get(op, op)
    frac = (n - 1) / n
    factor = {
        "all_reduce": 2 * frac,
        "all_gather": frac,
        "reduce_scatter": frac,
        "all_to_all": frac,
        "collective_permute": 1.0,
        "collective_broadcast": 1.0,
    }.get(op, 1.0)
    return int(payload_bytes * factor)


class CostModel:
    def build_program(self):
        from . import static
        from . import nn, optimizer
        import paddle_tpu as paddle

        paddle.enable_static()
        x = static.data("cost_model_X", [16, 1], "float32")
        lin = nn.Linear(1, 10)
        hidden = lin(x)
        loss = paddle.mean(hidden)
        optimizer.SGD(learning_rate=0.01, parameters=lin.parameters()).minimize(loss)
        self._feed = {"cost_model_X": np.ones((16, 1), np.float32)}
        self._fetch = [loss]
        return static.default_startup_program(), static.default_main_program()

    def profile_measure(self, startup_program=None, main_program=None,
                        device="tpu", fetch_cost_list=("time",)):
        from . import static
        exe = static.Executor()
        exe.run(main_program, feed=self._feed, fetch_list=self._fetch)  # compile
        t0 = time.perf_counter()
        for _ in range(10):
            exe.run(main_program, feed=self._feed, fetch_list=self._fetch)
        dt = (time.perf_counter() - t0) / 10
        return {"time": dt * 1e3}  # ms, like the reference's time cost

    _OP_BENCH = {
        # op -> (builder returning (fn, args)); timed lazily on first query
        "matmul": lambda jnp, rng: (lambda a, b: a @ b,
                                    (rng((256, 256)), rng((256, 256)))),
        "relu": lambda jnp, rng: (lambda a: jnp.maximum(a, 0), (rng((512, 512)),)),
        "softmax": lambda jnp, rng: (lambda a: jnp.exp(a - a.max(-1, keepdims=True))
                                     / jnp.exp(a - a.max(-1, keepdims=True)).sum(-1, keepdims=True),
                                     (rng((512, 512)),)),
        "layer_norm": lambda jnp, rng: (
            lambda a: (a - a.mean(-1, keepdims=True))
            / jnp.sqrt(a.var(-1, keepdims=True) + 1e-5), (rng((512, 512)),)),
        "elementwise_add": lambda jnp, rng: (lambda a, b: a + b,
                                             (rng((512, 512)), rng((512, 512)))),
    }

    def static_cost_data(self):
        """Measured per-op microbenchmark table (reference reads a shipped
        benchmark JSON; here the ops are timed on the live backend once)."""
        if not hasattr(self, "_static_costs"):
            self._static_costs = {
                name: self._time_op(name) for name in self._OP_BENCH}
        return self._static_costs

    def _time_op(self, op_name, forward=True, dtype="float32"):
        import jax
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        fn, args = self._OP_BENCH[op_name](
            jnp, lambda shape: jnp.asarray(rng.randn(*shape), dtype))
        if not forward:
            fwd = fn
            fn = jax.grad(lambda *a: jnp.sum(fwd(*a)).astype(jnp.float32))
        jfn = jax.jit(fn)
        jax.block_until_ready(jfn(*args))    # compile
        t0 = time.perf_counter()
        for _ in range(20):
            out = jfn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / 20 * 1e3   # ms

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        if op_name not in self._OP_BENCH:
            return {"op_time": "0"}
        cache = getattr(self, "_op_cost_cache", None)
        if cache is None:
            cache = self._op_cost_cache = {}
        key = (op_name, forward, dtype)
        if key not in cache:
            cache[key] = self._time_op(op_name, forward=forward, dtype=dtype)
        return {"op_time": str(cache[key])}
