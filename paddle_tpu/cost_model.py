"""Cost model — reference python/paddle/cost_model/cost_model.py.

The reference profiles a static Program op-by-op against a benchmark JSON.
TPU-native: XLA's compiled cost analysis gives per-program FLOPs/bytes
analytically, and profile_measure times the real jitted program.
"""
import time

import numpy as np

__all__ = ["CostModel"]


class CostModel:
    def build_program(self):
        from . import static
        from . import nn, optimizer
        import paddle_tpu as paddle

        paddle.enable_static()
        x = static.data("cost_model_X", [16, 1], "float32")
        lin = nn.Linear(1, 10)
        hidden = lin(x)
        loss = paddle.mean(hidden)
        optimizer.SGD(learning_rate=0.01, parameters=lin.parameters()).minimize(loss)
        self._feed = {"cost_model_X": np.ones((16, 1), np.float32)}
        self._fetch = [loss]
        return static.default_startup_program(), static.default_main_program()

    def profile_measure(self, startup_program=None, main_program=None,
                        device="tpu", fetch_cost_list=("time",)):
        from . import static
        exe = static.Executor()
        exe.run(main_program, feed=self._feed, fetch_list=self._fetch)  # compile
        t0 = time.perf_counter()
        for _ in range(10):
            exe.run(main_program, feed=self._feed, fetch_list=self._fetch)
        dt = (time.perf_counter() - t0) / 10
        return {"time": dt * 1e3}  # ms, like the reference's time cost

    def static_cost_data(self):
        return {}

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        return {"op_time": "0"}
