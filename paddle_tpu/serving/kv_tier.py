"""Tiered KV memory: a pinned-host-RAM spill tier behind the prefix
cache, and the pricing that decides when a spilled page is worth the
wire.

The prefix cache (prefix_cache.py) made shared-prompt KV pages
content-addressable inside HBM — but HBM is the SMALL tier: at
production scale the shared-prompt working set exceeds the pool, the
cache evicts at the HBM cliff, and hit rate collapses exactly when the
fleet needs it (the serving-under-load axis of the Gemma-on-TPU
comparison, PAPERS.md arxiv 2605.25645; the memory-hierarchy layer the
Ragged Paged Attention design leaves open, arxiv 2604.15464). This
module adds the second level:

- **Spill, don't evict.** A refcount-0 parked page reclaimed under
  pool pressure first copies its bytes (and, for an int8 pool, its
  write-time scale planes — the spill is ALREADY quantized, half the
  host bytes for free) into a capacity-bounded host LRU
  (`HostKVTier`), keyed by the same chain key. The device page then
  returns to the free list as before — HBM holds the hot set, host
  RAM the warm set.
- **Priced re-mount.** An admission whose chain continues past the
  device-resident run into host-resident entries restores them via
  H2D only when `cost_model.kv_restore_s(bytes)` (the PCIe leg,
  `ChipSpec.host_bw`) beats the prefill recompute of the same span
  (`cost_model.prefill_ttft_s`, no sync floor — the ragged path pays
  no extra sync either way). Otherwise it recomputes and merely
  refreshes the host entry's recency: the recomputed bytes are
  bit-identical to the spilled ones (write-time (request, position)
  determinism), so the stale payload stays valid. Either way the
  decision is observable: ServeStats `tier_restores` /
  `tier_recomputes` / `tier_spills` / `host_tier_bytes`, and
  flight-recorder "spill" events + ("h2d_restore",) tick records with
  predicted-vs-measured H2D in the drift ledger.
- **Byte identity is the gate.** A restored page's bytes are the SAME
  write-time bytes that were spilled (lossless D2H/H2D round trip),
  and a recomputed block's bytes equal them by the prefill's
  position-local determinism — so tier-on, tier-off and capacity-0
  engines emit byte-identical streams under admission churn
  (fuzz-pinned in tests/test_kv_tier.py, the same discipline every
  scheduler/quant feature in this package lands under).

`PrefixCache.save(dir)` / `PrefixCache.load(dir, decoder)` extend the
hierarchy to DISK across engine restarts (prefix_cache.py), through
the decoder's `pool_state`/`load_pool_state` seam and keyed by
`cache_fingerprint()` — a mismatched decoder refuses, exactly like a
quant-config mismatch does today.
"""
import collections

import numpy as np

__all__ = ["HostKVTier", "payload_bytes", "restore_beats_recompute"]

# default host budget: enough for thousands of tiny-model pages, and a
# deliberate bound — the tier is an LRU cache, not a leak
DEFAULT_CAPACITY_BYTES = 256 << 20


def payload_bytes(payload):
    """Host bytes one spilled page costs: every leaf of its K and V
    payloads (int8 pools pay quantized bytes + scale rows — already
    half the unquantized spill)."""
    return int(sum(leaf.nbytes for part in ("k", "v")
                   for leaf in payload[part]))


def restore_beats_recompute(restore_bytes, span_tokens, flops_per_token,
                            chip=None, shared=False):
    """THE tier decision: is re-mounting `restore_bytes` over the host
    wire cheaper than recomputing `span_tokens` of prefill?  Pure
    pricing (`cost_model.kv_restore_s` vs the compute leg of
    `prefill_ttft_s` with no sync floor — admission pays no extra sync
    either way), so the call sites (engine admission, tests) can never
    disagree on the formula. `shared=True` prices the cross-process
    tier (`serving.fleet.SharedHostKVTier`): the payload is read out
    of an shm-/file-backed store first (`ChipSpec.host_read_bw`),
    THEN crosses PCIe — the engine passes the tier's own `shared`
    attribute so the fleet's restore decision never flatters the
    wire."""
    from ..cost_model import kv_restore_s, prefill_ttft_s
    return kv_restore_s(restore_bytes, chip=chip, shared=shared) < \
        prefill_ttft_s(span_tokens, flops_per_token, chip=chip,
                       host_sync_s=0.0)


class _TierEntry:
    __slots__ = ("key", "payload", "nbytes", "page")

    def __init__(self, key, payload, nbytes, page=None):
        self.key = key
        self.payload = payload
        self.nbytes = nbytes
        self.page = page        # device page currently holding a
        # restored twin of this entry (None = host-only). Audit-only
        # backref: the page ledger's host rows cross-check it against
        # the free list (a key both host-resident-with-a-device-twin
        # and device-free is a dropped unmount — MEM-PAGE-REFCOUNT).


class HostKVTier:
    """Capacity-bounded LRU of spilled KV pages in host RAM, keyed by
    the prefix cache's chain key.

    An entry's payload is the exact device bytes of one page —
    ``{"k": (leaf arrays...), "v": (...)}`` as produced by
    `PagedGPTDecoder.fetch_page_payload` — so restore is a lossless
    H2D scatter and the byte-identical-stream invariant survives the
    round trip. int8 pools spill (int8 page bytes, f32 scale rows):
    the host cost is the QUANTIZED cost. `capacity_bytes=0` refuses
    every put — the exact tier-off twin the equivalence tests compare
    against (mirroring `PrefixCache(capacity=0)`)."""

    # per-process tier: restores pay PCIe only. The cross-process twin
    # (serving.fleet.SharedHostKVTier) flips this — the engine reads
    # it (getattr-defaulted) to price the shared host-read leg into
    # restore_beats_recompute
    shared = False

    def __init__(self, capacity_bytes=DEFAULT_CAPACITY_BYTES):
        self.capacity_bytes = int(capacity_bytes)
        self._entries = collections.OrderedDict()   # key -> _TierEntry
        self.bytes_used = 0
        self.evictions = 0          # entries LRU'd out under capacity
        self.puts = 0               # accepted spills (lifetime)

    # ------------------------------------------------------------ query

    def __contains__(self, key):
        return key in self._entries

    def __len__(self):
        return len(self._entries)

    @property
    def n_entries(self):
        return len(self._entries)

    def entry_bytes(self, key):
        return self._entries[key].nbytes

    def items(self):
        """(key, entry) pairs in LRU order (oldest first) — the
        persistence walk (`PrefixCache.save`) keeps this order so a
        loaded tier evicts in the same sequence."""
        return list(self._entries.items())

    # ----------------------------------------------------------- insert

    def put(self, key, payload, page=None):
        """Spill one page's payload under `key`; returns False when the
        capacity bound refuses it (entry bigger than the whole tier,
        or capacity 0 — the tier-off twin). Evicts LRU entries to fit;
        a re-put of an existing key refreshes payload + recency."""
        nbytes = payload_bytes(payload)
        if nbytes > self.capacity_bytes:
            return False
        old = self._entries.pop(key, None)
        if old is not None:
            self.bytes_used -= old.nbytes
        while self._entries and self.bytes_used + nbytes > \
                self.capacity_bytes:
            _, victim = self._entries.popitem(last=False)
            self.bytes_used -= victim.nbytes
            self.evictions += 1
        self._entries[key] = _TierEntry(key, payload, nbytes, page=page)
        self.bytes_used += nbytes
        self.puts += 1
        return True

    def get(self, key):
        """Payload of `key` (touches recency). KeyError when absent —
        callers gate on `key in tier`."""
        e = self._entries[key]
        self._entries.move_to_end(key)
        return e.payload

    def touch(self, key):
        """Refresh recency without reading (the recompute-refresh path:
        a hot entry whose span was re-prefilled must not age out)."""
        if key in self._entries:
            self._entries.move_to_end(key)

    # ------------------------------------------- device-twin bookkeeping

    def note_mounted(self, key, page):
        """A restored twin of `key` now lives in device page `page`
        (the ledger's host rows cross-check the backref)."""
        if key in self._entries:
            self._entries[key].page = int(page)

    def note_unmounted(self, key):
        """The device twin was evicted (and needs no re-spill: the
        host payload is still the exact write-time bytes); also
        refreshes recency — the entry is hot again."""
        e = self._entries.get(key)
        if e is not None:
            e.page = None
            self._entries.move_to_end(key)

    # ------------------------------------------------------------ ledger

    def ledger(self):
        """{key hex: {"bytes": n, "page": device twin or None}} — the
        host-tier rows of `ContinuousBatchingEngine.page_ledger()`,
        audited by MEM-PAGE-REFCOUNT (`analysis.memory
        .audit_page_ledger`): a host entry whose device twin sits on
        the free list is a dropped unmount."""
        return {e.key.hex(): {"bytes": e.nbytes, "page": e.page}
                for e in self._entries.values()}
