"""Paged-KV GPT decode executor: stacked weights, compiled decode /
prefill / verify programs over page pools (see package docstring in
`paddle_tpu/serving/__init__.py` for the architecture notes)."""
import collections
import functools
import hashlib
import weakref

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PagedGPTDecoder", "MultiDecodeOut", "RaggedMultiOut",
           "_spec_accept", "_sample_tokens", "_ln", "_mm", "_mm_heads",
           "_quantize_w", "_quantize_kv", "_kv_set", "INT4_GROUP",
           "_quantize_kv_int4", "_dequantize_kv_int4", "_pack_int4",
           "_unpack_int4"]

# every live decoder, so the tier-1 conftest's module-boundary GC hook
# can trim compiled-program memos (the Trainer._LIVE_TRAINERS pattern)
_LIVE_DECODERS = weakref.WeakSet()


def clear_compiled_memos():
    """Drop every live decoder's lazily built compiled-program memos
    (fused multi/ragged loops, chunked prefill, verify, CoW copy). A
    finished test module's decoders no longer need them; anything
    still live recompiles on its next call. Returns entries dropped."""
    n = 0
    for dec in list(_LIVE_DECODERS):
        for memo in (dec._multis, dec._raggeds, dec._packeds,
                     dec._packed_prefills, dec._mount_multi):
            n += len(memo)
            memo.clear()
        for attr in ("_verify", "_probs", "_suffix_prefill", "_copy",
                     "_mount"):
            if getattr(dec, attr) is not None:
                n += 1
                setattr(dec, attr, None)
    return n


# decode_multi's result bundle: device arrays — the engine feeds
# tokens/lens/done/remaining straight into the next horizon's call and
# fetches tokens_block/done_before only at sync points
MultiDecodeOut = collections.namedtuple(
    "MultiDecodeOut", ["tokens_block", "done_before", "tokens", "lens",
                       "done", "remaining", "logits_block"])

# ragged_multi's result bundle: like MultiDecodeOut plus the device-
# resident prompt-suffix carry (pend/pend_n), the per-tick `emitted`
# mask (False for filler ticks of frozen slots AND for mid-prefill
# ticks, which consume prompt chunks without producing a token), and
# `real` [k] — the REAL token positions each tick consumed (live rows'
# new_len summed; frozen rows 0). The engine's pad-fraction ledger is
# dispatched-minus-real: the device is the one source of truth for how
# much of a padded dispatch was actual work (EOS can freeze a slot
# mid-horizon, which no host-side plan can predict).
RaggedMultiOut = collections.namedtuple(
    "RaggedMultiOut", ["tokens_block", "emitted", "real", "tokens",
                       "lens", "done", "remaining", "pend", "pend_n"])


def pow2_at_least(n):
    """Smallest power of two >= max(n, 1) — THE bucket-rounding rule
    shared by the packed dispatch (scheduler `t_tokens`, the decoder's
    default buckets, the packed prefill): one definition, so the
    scheduler's bucket and the decoder's coverage guarantee can never
    diverge on an off-by-one."""
    p = 1
    while p < max(int(n), 1):
        p *= 2
    return p


def _ln(x, w, b):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-5) * w + b).astype(x.dtype)


def _quantize_w(w):
    """Per-out-channel symmetric int8 via the shared quantization recipe
    (quantization.quantize_weight) — one implementation so serving a8w8
    can't drift from QuantizedLinearA8W8/PTQ."""
    from ..quantization import quantize_weight
    q, scale = quantize_weight(w, axis=0)
    return q, scale.reshape(-1)


def _quantize_kv(val):
    """Write-time per-token int8 quantization of K (or V) vectors: one
    symmetric scale per TOKEN from the token's own [H, D] amax
    (scale = amax/127, floored so an all-zero vector stays
    representable). The scale depends only on the token's values —
    which are position-local (row-local matmuls, per-position
    embeddings) — so a token's stored bytes depend only on (request,
    position), never on batch composition, chunk schedule or page
    assignment: the byte-identical-stream discipline survives
    quantization unchanged. val [..., H, D] -> (int8 [..., H, D],
    f32 scale [...])."""
    v32 = val.astype(jnp.float32)
    amax = jnp.max(jnp.abs(v32), axis=(-2, -1))
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(v32 / scale[..., None, None]),
                 -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


# int4 KV quantization group: one f32 scale per GROUP of flattened
# head*dim elements (per-token scales, as int8, would leave int4's
# narrow range too coarse across heads with very different magnitudes;
# per-group recovers most of the accuracy at 4/GROUP bytes/elem of
# metadata). The pool stores (uint8 nibble pages, f32 group-scale
# planes); dequant happens inside the attention body
# (ops/ragged_paged_attention._dequant_page_int4), never in HBM.
INT4_GROUP = 32


def _pack_int4(q):
    """Pack int4 values (int8 in [-8, 7], even last dim) into uint8
    nibble pairs: element 2i rides the LOW nibble of byte i, 2i+1 the
    high — the same layout ops/w4_matmul unpacks, so an int4 pool can
    later share its in-kernel dequant idiom."""
    lo = (q[..., 0::2].astype(jnp.uint8)) & 0xF
    hi = (q[..., 1::2].astype(jnp.uint8)) & 0xF
    return lo | (hi << 4)


def _unpack_int4(packed):
    """Inverse of `_pack_int4`: uint8 nibble pairs -> int8 values in
    [-8, 7] (sign-extended), last dim doubled."""
    lo = (packed & 0xF).astype(jnp.int8)
    hi = ((packed >> 4) & 0xF).astype(jnp.int8)
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    return jnp.stack([lo, hi], axis=-1).reshape(
        packed.shape[:-1] + (packed.shape[-1] * 2,))


def _quantize_kv_int4(val, group=INT4_GROUP):
    """Write-time int4 KV quantization with PER-GROUP scales: the
    token's [H, D] vector flattens to H*D elements, each `group`-run
    shares one symmetric f32 scale from its own amax (floored like
    `_quantize_kv`), values clip to [-7, 7] and pack two-per-byte
    (`_pack_int4`). Like the int8 path, the scales depend only on the
    token's own values, so stored bytes stay a pure function of
    (request, position) — the byte-identical-stream discipline carries
    over unchanged (`_kv_set` dispatches here for uint8 pools;
    `pool_token_bytes(kv_quant="int4")` prices the stored layout).
    val [..., H, D] -> (packed uint8
    [..., ceil(ceil(H*D/group)*group / 2)] — H*D zero-padded up to a
    whole number of groups and an even nibble count — f32 scales
    [..., ceil(H*D/group)])."""
    v32 = val.astype(jnp.float32)
    hd = v32.shape[-2] * v32.shape[-1]
    group = min(int(group), hd)
    flat = v32.reshape(v32.shape[:-2] + (hd,))
    n_groups = (hd + group - 1) // group      # ceil, like the pricing
    pad = n_groups * group - hd
    if pad:
        # zero-pad the tail group (zeros quantize to 0 under any
        # scale, so padding never moves a real element's scale and
        # stored bytes stay a pure function of the token's values)
        flat = jnp.concatenate(
            [flat, jnp.zeros(flat.shape[:-1] + (pad,), jnp.float32)],
            axis=-1)
    g = flat.reshape(flat.shape[:-1] + (n_groups, group))
    amax = jnp.max(jnp.abs(g), axis=-1)
    scale = jnp.maximum(amax / 7.0, 1e-8)
    q = jnp.clip(jnp.round(g / scale[..., None]), -7, 7).astype(jnp.int8)
    q = q.reshape(flat.shape)
    if q.shape[-1] % 2:                       # nibble pairs need even
        q = jnp.concatenate(
            [q, jnp.zeros(q.shape[:-1] + (1,), jnp.int8)], axis=-1)
    return _pack_int4(q), scale.astype(jnp.float32)


def _dequantize_kv_int4(packed, scale, heads_shape, group=INT4_GROUP):
    """Inverse of `_quantize_kv_int4` up to quantization error:
    unpack nibbles, multiply each group by its scale, reshape back to
    [..., H, D] (`heads_shape` = (H, D))."""
    q = _unpack_int4(packed).astype(jnp.float32)
    hd = int(heads_shape[0]) * int(heads_shape[1])
    group = min(int(group), hd)
    n_groups = scale.shape[-1]
    q = q[..., :n_groups * group]             # drop the pack-parity pad
    g = q.reshape(q.shape[:-1] + (n_groups, group)) * scale[..., None]
    flat = g.reshape(q.shape[:-1] + (n_groups * group,))[..., :hd]
    return flat.reshape(q.shape[:-1] + tuple(heads_shape))


def pool_token_bytes(cfg, kv_quant=None, itemsize=2):
    """KV bytes one context token costs PER LAYER under a pool layout
    (K and V together). int8 pools pay 1 B/elem payload + one 4 B f32
    write-time scale per plane; int4 pools pay 0.5 B/elem packed
    nibbles + one f32 scale per `INT4_GROUP` elements (per-group
    scales — see `_quantize_kv_int4`). THE byte model behind
    `PagedGPTDecoder.kv_token_bytes` / `step_hbm_bytes` and the
    capacity bench (`bench.run_decode_capacity`) — one definition, so
    the bench can price big-model shapes without building the model
    and can never drift from what the decoder reports."""
    if kv_quant not in (None, "int8", "int4"):
        raise ValueError(
            f"kv_quant must be None, 'int8' or 'int4', got {kv_quant!r} "
            "(an unquantized pool is kv_quant=None priced at `itemsize` "
            "bytes/elem — there is no 'bf16' spelling)")
    hd = cfg.num_heads * cfg.head_dim
    if kv_quant == "int4":
        group = min(INT4_GROUP, hd)
        n_groups = (hd + group - 1) // group
        # stored payload is ceil-padded to whole groups and an even
        # nibble count (`_quantize_kv_int4`) — price the stored bytes
        per_tensor = (n_groups * group + 1) // 2 + 4 * n_groups
    elif kv_quant == "int8":
        per_tensor = hd + 4          # one f32 write-time scale/token
    else:
        per_tensor = hd * itemsize
    return int(2 * per_tensor)


def _kv_set(pool, pids, offs, val):
    """Write `val` [..., H, D] at (pids, offs) of ONE layer's page pool
    — the single KV write primitive behind every serving path (decode
    ticks, chunked suffix prefill, the verify window, ragged horizons;
    scratch routing is the caller's pids). A plain pool stores the
    cast value; a quantized pool (pages, scales) quantizes from the
    token's own amax and stores bytes + scales together, so no write
    site can ever drift from the others — int8 pools (int8 payload)
    take the per-token-scale path (`_quantize_kv`), int4 pools (uint8
    nibble payload) the per-group path (`_quantize_kv_int4`)."""
    if isinstance(pool, tuple):
        pages, scales = pool
        if pages.dtype == jnp.uint8:
            q, s = _quantize_kv_int4(val)
        else:
            q, s = _quantize_kv(val)
        return (pages.at[pids, offs].set(q),
                scales.at[pids, offs].set(s))
    return pool.at[pids, offs].set(val.astype(pool.dtype))


def _spec_accept(p_rows, q_rows, drafts, rng):
    """Rejection-sampling acceptance for ONE slot (Leviathan et al.):
    p_rows [n+1, V] target probs — row j is the target's conditional
    AFTER the tokens preceding draft j (row 0 judges drafts[0]),
    q_rows [n, V] draft probs, drafts [n] proposed tokens.  Accept draft
    j with prob min(1, p_j(d)/q_j(d)); on rejection emit a sample from
    norm(max(p_j - q_j, 0)); if every draft is accepted emit a fresh
    sample from the last target row.  The emitted tokens are distributed
    EXACTLY as target-only sampling (unit-tested by Monte Carlo).
    Returns (n_accepted, final_token)."""
    n = len(drafts)
    for j in range(n):
        d = int(drafts[j])
        q = q_rows[j, d]
        p = p_rows[j, d]
        if q <= 0.0 or rng.random() >= min(1.0, p / q):
            resid = np.maximum(p_rows[j] - q_rows[j], 0.0)
            tot = resid.sum()
            if tot <= 1e-12:       # p==q everywhere: any target sample
                resid, tot = p_rows[j], p_rows[j].sum()
            return j, int(rng.choice(len(resid), p=resid / tot))
    row = p_rows[n]
    return n, int(rng.choice(len(row), p=row / row.sum()))


def _sample_tokens(logits, sampling, keys):
    """Per-slot next-token choice: greedy, or seeded temperature/top-k/
    top-p sampling (keys: [S] per-slot PRNG keys derived from
    (seed, request id, position) — see PagedGPTDecoder._pos_keys — so a
    request's draws don't depend on batch composition or scheduling;
    the mask itself is shared with generate() via
    models.generation.mask_logits)."""
    if sampling is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    from ..models.generation import mask_logits
    temperature, top_k, top_p = sampling
    masked = mask_logits(logits, temperature, top_k, top_p)
    return jax.vmap(jax.random.categorical)(keys, masked).astype(jnp.int32)


def _lora_delta(wl, y, aids):
    """Per-token low-rank qkv delta over the SHARED base weights — the
    multi-LoRA primitive (tenancy: dozens of fine-tuned variants batch
    into one ragged horizon). `y` [T, h] are the flat post-ln1
    activations (the qkv projection's input), `aids` [T] per-token
    adapter ids (0 = base, an all-zero adapter), `wl["lora_A"]`
    [n_a, h, r] / `wl["lora_B"]` [n_a, r, 3*H*D] this layer's stacked
    adapter banks (alpha/r scaling folded into B at attach time).

    The adapter is resolved by a per-TOKEN gather — exactly how the
    packed layout resolves pages via `row_ids` — so each token's delta
    is (y_t @ A_{a_t}) @ B_{a_t}: row-local math that never sees batch
    composition. A mixed-adapter horizon therefore emits bit-identical
    streams to per-adapter engines over the same bank (test-pinned),
    and adapter 0's zero bank contributes an exact 0.0 to every
    preactivation."""
    A = wl["lora_A"][aids]                      # [T, h, r]
    B = wl["lora_B"][aids]                      # [T, r, 3*H*D]
    y32 = y.astype(jnp.float32)
    z = jnp.einsum("th,thr->tr", y32, A)
    return jnp.einsum("tr,trd->td", z, B)


def _mm_heads(x, w, b, quant):
    """x [S, h] @ head-major qkv weight [h, 3, H, D] -> [S, 3, H, D]."""
    if not quant:
        return (jnp.einsum("sh,htnd->stnd", x, w.astype(x.dtype))
                + b.astype(x.dtype))
    if quant == "w4a16":
        from ..ops.w4_matmul import w4_matmul
        packed, sw = w             # [h/2, 3, H, D] packed, [3, H, D]
        out = w4_matmul(x, packed.reshape(packed.shape[0], -1),
                        sw.reshape(-1), x.shape[-1])
        return out.reshape(x.shape[0], *b.shape) + b.astype(x.dtype)
    qw, sw = w                     # [h,3,H,D] int8, [3,H,D] f32
    sx = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                 keepdims=True) / 127.0
    sx = jnp.maximum(sx, 1e-8)
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / sx), -127,
                  127).astype(jnp.int8)
    acc = jax.lax.dot_general(xq, qw, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx[:, :, None, None] * sw
            + b).astype(x.dtype)


def _mm(x, w, b, quant):
    """x [..., in] @ w -> [..., out].  Float path, weight-only int4
    (W4A16: Pallas in-VMEM dequant), or dynamic-A8 x W8 int8 MXU
    matmul with per-row activation scales."""
    if not quant:
        return (x @ w.astype(x.dtype) + b.astype(x.dtype)).astype(x.dtype)
    if quant == "w4a16":
        from ..ops.w4_matmul import w4_matmul
        out = w4_matmul(x, w[0], w[1], x.shape[-1])
        return (out + b.astype(x.dtype)).astype(x.dtype)
    qw, sw = w
    sx = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True) / 127.0
    sx = jnp.maximum(sx, 1e-8)
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / sx), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(xq, qw, (((xq.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx * sw + b).astype(x.dtype)


class PagedGPTDecoder:
    """Stacked-weight GPT decode executor over paged KV pools."""

    def __init__(self, model, num_pages=128, page_size=16, max_batch=8,
                 max_pages_per_seq=None, quant=None, kv_quant=None,
                 use_kernel=False, dtype=None, temperature=0.0, top_k=0,
                 top_p=1.0, seed=0, mesh=None, packed=True):
        cfg = model.cfg
        self.cfg = cfg
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_batch = max_batch
        self.max_pages = max_pages_per_seq or \
            (cfg.max_seq_len + page_size - 1) // page_size
        self.quant = quant
        self.kv_quant = kv_quant
        self.use_kernel = use_kernel
        # PACKED token-stream layout (default): ragged horizons and
        # chunked prefill dispatch flat [total_new_tokens] streams with
        # per-token row ids instead of dense [S, w] windows — decode
        # rows pay one token per tick, not w. packed=False keeps the
        # dense window layout end to end: the A/B twin the
        # byte-identity tests (and the pad-fraction bench) compare
        # against.
        self.packed = bool(packed)
        assert quant in (None, "a8w8", "w4a16"), quant
        assert kv_quant in (None, "int8", "int4"), kv_quant
        # temperature 0 = greedy (reference decode convention)
        self.sampling = None if not temperature else \
            (float(temperature), int(top_k), float(top_p))
        self.seed = int(seed)
        self._draws = 0
        dtype = dtype or jnp.dtype(cfg.dtype)

        state = {k: np.asarray(v._value)
                 for k, v in model.state_dict().items()}
        L = cfg.num_layers

        def stack(fmt):
            return jnp.asarray(
                np.stack([state[fmt.format(i)] for i in range(L)]))

        H, D = cfg.num_heads, cfg.head_dim
        w = {
            "ln1_w": stack("blocks.{}.ln1.weight"),
            "ln1_b": stack("blocks.{}.ln1.bias"),
            # head-major qkv layout [L, h, 3, H, D]: under tp the shard
            # axis is the HEAD dim, which propagates cleanly through the
            # per-head attention and the head-sharded KV pages (a flat
            # [h, 3h] out-dim shard mixes q/k/v columns and costs an
            # all-gather per layer)
            "qkv_w": stack("blocks.{}.qkv.weight").reshape(
                cfg.num_layers, cfg.hidden_size, 3, H, D),
            "qkv_b": stack("blocks.{}.qkv.bias").reshape(
                cfg.num_layers, 3, H, D),
            "proj_w": stack("blocks.{}.proj.weight"),
            "proj_b": stack("blocks.{}.proj.bias"),
            "ln2_w": stack("blocks.{}.ln2.weight"),
            "ln2_b": stack("blocks.{}.ln2.bias"),
            "fc1_w": stack("blocks.{}.fc1.weight"),
            "fc1_b": stack("blocks.{}.fc1.bias"),
            "fc2_w": stack("blocks.{}.fc2.weight"),
            "fc2_b": stack("blocks.{}.fc2.bias"),
        }
        if quant:
            if quant == "w4a16":
                from ..ops.w4_matmul import quantize_w4 as quantizer
            else:
                quantizer = _quantize_w
            for k in ("qkv_w", "proj_w", "fc1_w", "fc2_w"):
                v = w[k]
                shp = v.shape
                if v.ndim > 3:          # qkv head-major: flatten to 2-D
                    v = v.reshape(shp[0], shp[1], -1)
                q, s = jax.vmap(quantizer)(v)
                # restore the head-major rank (w4's packed in-dim is
                # h/2) so _shard_for_tp's specs apply to both quant
                # modes exactly as to fp; the scan slices tuples
                # leaf-wise per layer
                w[k] = (q.reshape((shp[0], q.shape[1]) + shp[2:]),
                        s.reshape((shp[0],) + shp[2:]))
        self.weights = w
        self.wte = jnp.asarray(state["wte.weight"])
        self.wpe = jnp.asarray(state["wpe.weight"])
        self.ln_f_w = jnp.asarray(state["ln_f.weight"])
        self.ln_f_b = jnp.asarray(state["ln_f.bias"])
        self.lm_head = jnp.asarray(
            state.get("lm_head.weight", state["wte.weight"].T))

        H, D = cfg.num_heads, cfg.head_dim
        # activations/embeddings compute at this width whatever the
        # pool stores (the int8 pool dequantizes inside the attention
        # body, never in HBM)
        self.compute_dtype = dtype
        if kv_quant == "int4":
            # nibble-packed pages + one f32 write-time scale per
            # (layer, token, group) for each of K and V: the token's
            # H*D elements pack two-per-byte with a per-INT4_GROUP
            # scale plane next to them (`_quantize_kv_int4` pads the
            # tail group and the odd nibble) — the KV byte stream
            # behind the decode roofline drops ~4x vs bf16
            hd = H * D
            grp = min(INT4_GROUP, hd)
            G = (hd + grp - 1) // grp
            PB = (G * grp + 1) // 2
            self.k_pages = (
                jnp.zeros((L, num_pages, page_size, PB), jnp.uint8),
                jnp.zeros((L, num_pages, page_size, G), jnp.float32))
            self.v_pages = (
                jnp.zeros((L, num_pages, page_size, PB), jnp.uint8),
                jnp.zeros((L, num_pages, page_size, G), jnp.float32))
        elif kv_quant:
            # int8 pages + one f32 write-time scale per (layer, token)
            # for each of K and V: 4 bytes/token/layer of metadata per
            # plane next to the H*D int8 payload — the KV byte stream
            # behind the decode roofline halves vs bf16
            self.k_pages = (
                jnp.zeros((L, num_pages, page_size, H, D), jnp.int8),
                jnp.zeros((L, num_pages, page_size), jnp.float32))
            self.v_pages = (
                jnp.zeros((L, num_pages, page_size, H, D), jnp.int8),
                jnp.zeros((L, num_pages, page_size), jnp.float32))
        else:
            self.k_pages = jnp.zeros((L, num_pages, page_size, H, D),
                                     dtype)
            self.v_pages = jnp.zeros((L, num_pages, page_size, H, D),
                                     dtype)

        # tensor-parallel serving: shard the 3h/ffn/head dims of the
        # stacked weights and the HEAD dim of the KV pages over 'tp';
        # GSPMD inserts the all-reduces after proj/ffn2 — the Megatron
        # decode layout, no code changes in the step function
        self.mesh = mesh
        if mesh is None:
            from ..distributed.mesh import get_mesh
            m = get_mesh(create_default=False)
            if m is not None and m.shape.get("tp", 1) > 1:
                self.mesh = m
        if self.mesh is not None:
            self._shard_for_tp()

        self._decode = jax.jit(self._decode_step, donate_argnums=(1, 2))
        self._multis = {}     # (k, return_logits) -> jitted fused loop
        self._raggeds = {}    # (k, w) -> jitted mixed ragged horizon
        self._packeds = {}    # (k, t) -> jitted PACKED mixed horizon
        # (w rides as a traced scalar — per-dispatch width changes
        # never compile a new program; dispatches bucket by total
        # token count t alone)
        self._packed_prefills = {}   # t -> jitted packed prefill
        self._verify = None   # jitted lazily (speculative decoding only)
        self._probs = None    # jitted lazily (sampled speculation)
        self._suffix_prefill = None   # jitted lazily (chunked prefill)
        self._copy = None     # jitted lazily (copy-on-write page copy)
        self._mount = None    # jitted lazily (host-tier page restore)
        self._mount_multi = {}   # span length -> jitted batched restore
        # engines serving over this pool (weak): load_pool_state
        # refuses while any of them holds live refcounted pages —
        # swapping pool bytes under a live PrefixCache ledger would
        # silently orphan it
        self._engines = weakref.WeakSet()
        # multi-LoRA (serving.tenancy): stacked low-rank adapter banks
        # over the shared base weights, attached via attach_adapters.
        # None = no adapters — every compiled program keeps its exact
        # pre-tenancy signature and trace (the HLO regression pins).
        self.lora = None
        self.n_adapters = 0
        self._adapter_salts = [b""]
        _LIVE_DECODERS.add(self)

    # ---------------------------------------------------- multi-LoRA

    def attach_adapters(self, adapters, alpha=None):
        """Attach stacked low-rank (LoRA) adapter banks for multi-LoRA
        serving: `adapters` is a list of per-adapter (A, B) pairs with
        A [L, h, r] and B [L, r, 3, H, D] (or [L, r, 3*H*D]) — the
        low-rank qkv delta of one fine-tuned variant over the SHARED
        base weights. Adapter id 0 is reserved for the base model (an
        exact all-zero bank); caller adapters are ids 1..n. Mixed
        ranks zero-pad to the max (zero rows/cols contribute exact
        0.0). `alpha` scales every delta by alpha/r, folded into B at
        attach time (default: alpha == r, scale 1).

        Rows gather the bank per TOKEN (`_lora_delta` — the packed
        layout's row-id idiom applied to weights), so one ragged
        horizon serves every variant through one compiled program; the
        jit wrappers retrace automatically (the weights pytree gains
        the bank leaves and an `aids` input). Per-adapter
        `adapter_salt` fingerprints keep prefix-cache page sharing
        sound across variants: pages never alias across differing
        adapter banks (the MEM-PAGE-REFCOUNT ledger audit checks the
        live engine's slot adapters)."""
        cfg = self.cfg
        L = cfg.num_layers
        hd3 = 3 * cfg.num_heads * cfg.head_dim
        h = cfg.hidden_size
        ranks = []
        pairs = []
        for a, b in adapters:
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32).reshape(L, a.shape[-1], hd3)
            if a.shape != (L, h, a.shape[-1]):
                raise ValueError(
                    f"adapter A must be [num_layers, hidden, r], got "
                    f"{a.shape}")
            ranks.append(a.shape[-1])
            pairs.append((a, b))
        R = max(ranks) if ranks else 1
        n = len(pairs)
        A = np.zeros((L, n + 1, h, R), np.float32)
        B = np.zeros((L, n + 1, R, hd3), np.float32)
        salts = [b""]
        for i, ((a, b), r) in enumerate(zip(pairs, ranks), start=1):
            scale = (float(alpha) / r) if alpha is not None else 1.0
            A[:, i, :, :r] = a
            B[:, i, :r, :] = b * scale
            # CONTENT hash, not content sums: two structurally related
            # fine-tunes (e.g. a row permutation) can share every sum,
            # and colliding salts would alias their cache pages — the
            # exact corruption the slot_adapters audit exists to catch
            h = hashlib.blake2b(digest_size=16)
            h.update(np.float32(scale).tobytes())
            h.update(a.tobytes())
            h.update(b.tobytes())
            salts.append(h.digest())
        self.lora = {"lora_A": jnp.asarray(A), "lora_B": jnp.asarray(B)}
        self.n_adapters = n
        self._adapter_salts = salts
        return self

    def adapter_salt(self, aid):
        """Prefix-cache key salt of adapter `aid` (b"" for the base
        model, id 0): KV bytes written under an adapter depend on its
        bank, so chain keys must fold it in or pages would alias
        across variants."""
        return self._adapter_salts[int(aid)]

    def _w(self):
        """Weights pytree the compiled programs consume: the stacked
        base weights, plus the LoRA banks when attached (the bank
        leaves ride the per-layer lax.scan next to the base stacks;
        `cache_fingerprint` keeps reading `self.weights` only — the
        BASE identity — with adapters salted separately)."""
        return {**self.weights, **self.lora} if self.lora else \
            self.weights

    def _aids_or_default(self, aids):
        """[S] int32 adapter ids (None -> all base) — only consulted
        when a bank is attached; without one the compiled programs
        never see an aids input."""
        if aids is None:
            return np.zeros(self.max_batch, np.int32)
        return np.asarray(aids, np.int32)

    def _probs_of(self, logits):
        """softmax over the decoder's sampling mask (the distribution its
        sampled tokens are actually drawn from)."""
        if self._probs is None:
            from ..models.generation import mask_logits
            if self.sampling:
                t, tk, tp = self.sampling
                self._probs = jax.jit(lambda lg: jax.nn.softmax(
                    mask_logits(lg, t, tk, tp), axis=-1))
            else:
                self._probs = jax.jit(
                    lambda lg: jax.nn.softmax(lg, axis=-1))
        return np.asarray(self._probs(logits))

    def _shard_for_tp(self):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        tp = mesh.shape.get("tp", 1)
        if self.cfg.num_heads % tp:
            raise ValueError(
                f"num_heads {self.cfg.num_heads} must divide over "
                f"tp={tp} for tensor-parallel serving")
        if self.cfg.ffn_hidden % tp:
            raise ValueError(
                f"ffn_hidden {self.cfg.ffn_hidden} must divide over "
                f"tp={tp} for tensor-parallel serving")

        def put(v, *spec):
            return jax.device_put(v, NamedSharding(mesh, P(*spec)))

        w = self.weights

        def put_w(key, *spec):
            if isinstance(w[key], tuple):      # a8w8 (q, per-out scale)
                q, s = w[key]
                w[key] = (put(q, *spec), put(s, spec[0], *spec[2:]))
            else:
                w[key] = put(w[key], *spec)

        # column-parallel qkv (HEAD axis — aligns with the per-head
        # attention and the head-sharded pages, no reshard) and fc1;
        # row-parallel proj/fc2; biases follow their out dims
        put_w("qkv_w", None, None, None, "tp", None)
        w["qkv_b"] = put(w["qkv_b"], None, None, "tp", None)
        put_w("proj_w", None, "tp", None)
        put_w("fc1_w", None, None, "tp")
        w["fc1_b"] = put(w["fc1_b"], None, "tp")
        put_w("fc2_w", None, "tp", None)
        self.wte = put(self.wte, None, None)
        if self.lm_head.shape[-1] % tp == 0:
            self.lm_head = put(self.lm_head, None, "tp")
        else:
            # odd vocab (e.g. 50257): keep the head replicated rather
            # than fail — logits are [S, V] and small at decode batch
            self.lm_head = put(self.lm_head, None, None)
        # KV pages: heads sharded — each tp shard holds its heads' pages
        # (int8 pools shard the byte payload the same way; the per-token
        # scale planes have no head axis and replicate — their amax
        # reduces over ALL heads, a tiny per-layer collective GSPMD
        # inserts at the write). int4 pools replicate BOTH leaves: the
        # nibble axis is the flattened H*D stream packed two-per-byte,
        # so a head boundary can land mid-byte and mid-group — there is
        # no clean head shard of the packed payload.
        def put_pool(pool):
            if isinstance(pool, tuple):
                if pool[0].dtype == jnp.uint8:
                    return (put(pool[0], None, None, None, None),
                            put(pool[1], None, None, None, None))
                return (put(pool[0], None, None, None, "tp", None),
                        put(pool[1], None, None, None))
            return put(pool, None, None, None, "tp", None)

        self.k_pages = put_pool(self.k_pages)
        self.v_pages = put_pool(self.v_pages)

    # -- compiled programs -------------------------------------------------

    def _forward_tokens(self, weights, k_pages, v_pages, tokens, lens,
                        table, pids, offs, aids=None):
        """Shared single-position forward over all slots: embed `tokens`
        at position `lens`, write K/V at (pids, offs) — callers route
        frozen slots' pids to the reserved scratch page — and attend
        over each slot's pages. Returns (logits [S, V], k_pages,
        v_pages). Both the per-tick step and every tick of the fused
        multi-step scan run THIS body, so they cannot drift. `aids`
        [S] selects each slot's LoRA adapter when a bank is attached
        (None with no bank — the program shape is then exactly the
        pre-tenancy one)."""
        cfg = self.cfg
        H, D = cfg.num_heads, cfg.head_dim
        S = tokens.shape[0]
        x = (self.wte[tokens] +
             self.wpe[jnp.clip(lens, 0, cfg.max_seq_len - 1)]
             ).astype(self.compute_dtype)                      # [S, h]
        quant = self.quant

        def layer(x, wkv):
            wl, kp, vp = wkv
            y = _ln(x, wl["ln1_w"], wl["ln1_b"])
            qkv = _mm_heads(y, wl["qkv_w"], wl["qkv_b"], quant)  # [S,3,H,D]
            if aids is not None:
                qkv = qkv + _lora_delta(wl, y, aids).reshape(
                    S, 3, H, D).astype(qkv.dtype)
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            kp = _kv_set(kp, pids, offs, k)
            vp = _kv_set(vp, pids, offs, v)
            # the ONE ragged kernel behind every serving path (decode is
            # the W=1 row kind): causal over kpos <= lens, i.e. the
            # slot's prefix plus the key written just above
            from ..ops.ragged_paged_attention import ragged_paged_attention
            attn = ragged_paged_attention(q[:, None], kp, vp, table, lens,
                                          use_kernel=self.use_kernel)
            x = x + _mm(attn.reshape(S, H * D), wl["proj_w"], wl["proj_b"],
                        quant)
            y = _ln(x, wl["ln2_w"], wl["ln2_b"])
            h = jax.nn.gelu(_mm(y, wl["fc1_w"], wl["fc1_b"], quant),
                            approximate=True)
            x = x + _mm(h, wl["fc2_w"], wl["fc2_b"], quant)
            return x, (kp, vp)

        x, (k_pages, v_pages) = jax.lax.scan(
            layer, x, (weights, k_pages, v_pages))
        x = _ln(x, self.ln_f_w, self.ln_f_b)
        logits = x.astype(jnp.float32) @ self.lm_head.astype(jnp.float32)
        return logits, k_pages, v_pages

    def _pos_keys(self, kids, pos):
        """Per-slot PRNG keys from (seed, kid, position): draws depend
        only on the decoder seed, the request identity (`kids` — the
        engine passes the request id; direct callers default to the
        slot index) and the position of the token being consumed.
        NOTHING about scheduling enters the key, so the same request
        sampled through the per-tick loop, the fused multi-step loop,
        or any admission/batch composition draws the same tokens."""
        base = jax.random.PRNGKey(self.seed)
        return jax.vmap(lambda kid, p: jax.random.fold_in(
            jax.random.fold_in(base, kid), p))(kids, pos)

    def _decode_step(self, weights, k_pages, v_pages, tokens, lens, table,
                     kids, aids=None):
        """tokens [S], lens [S] (tokens already counted, i.e. position of
        the incoming token), table [S, max_pages], kids [S] (sampling
        key ids, see _pos_keys) -> (next [S], logits [S, V], k_pages,
        v_pages)."""
        ps = self.page_size
        pids = jnp.take_along_axis(table, (lens // ps)[:, None],
                                   axis=1)[:, 0]                # [S]
        offs = lens % ps
        logits, k_pages, v_pages = self._forward_tokens(
            weights, k_pages, v_pages, tokens, lens, table, pids, offs,
            aids=aids)
        keys = None
        if self.sampling is not None:
            keys = self._pos_keys(kids, lens)
        nxt = _sample_tokens(logits, self.sampling, keys)
        return nxt, logits, k_pages, v_pages

    def _decode_multi_step(self, weights, k_pages, v_pages, tokens, lens,
                           table, kids, done, remaining, eos, aids=None,
                           *, k, return_logits=False):
        """K fused decode ticks inside ONE compiled program (lax.scan):
        each tick's sampled token feeds the next tick on device, so the
        host syncs once per K tokens instead of once per token.

        tokens/lens/table/kids as in `_decode_step`. Tick j draws with
        the (seed, kid, lens+j) key — exactly the keys the per-tick
        loop would use at those positions, so fused and per-tick decode
        emit byte-identical streams. `done` [S] bool freezes a slot
        from tick 0 (inactive or already finished); a slot also freezes
        itself after emitting its first `eos` (pass -1 for none) or
        after `remaining` [S] tokens (its budget). Frozen slots' `lens`
        stop advancing and their K/V writes route to the reserved
        scratch page, so the pages stay exactly as the per-tick engine
        would leave them.

        Returns (block [k, S] emitted tokens, done_before [k, S] — True
        where the slot was already frozen, i.e. the token is filler —
        final tokens/lens/done/remaining, k_pages, v_pages[, logits
        [k, S, V] when return_logits])."""
        ps = self.page_size
        scratch = self.num_pages - 1

        def tick(carry, _):
            tokens, lens, done, remaining, kp, vp = carry
            pids = jnp.take_along_axis(table, (lens // ps)[:, None],
                                       axis=1)[:, 0]
            pids = jnp.where(done, scratch, pids)
            offs = lens % ps
            logits, kp, vp = self._forward_tokens(
                weights, kp, vp, tokens, lens, table, pids, offs,
                aids=aids)
            keys = None
            if self.sampling is not None:
                keys = self._pos_keys(kids, lens)
            nxt = _sample_tokens(logits, self.sampling, keys)
            nxt = jnp.where(done, tokens, nxt)
            rem = jnp.where(done, remaining, remaining - 1)
            new_done = done | (nxt == eos) | (rem <= 0)
            new_lens = jnp.where(done, lens, lens + 1)
            out = (nxt, done, logits) if return_logits else (nxt, done)
            return (nxt, new_lens, new_done, rem, kp, vp), out

        carry = (tokens, lens, done, remaining, k_pages, v_pages)
        carry, outs = jax.lax.scan(tick, carry, jnp.arange(k))
        tokens, lens, done, remaining, k_pages, v_pages = carry
        ret = (outs[0], outs[1], tokens, lens, done, remaining,
               k_pages, v_pages)
        if return_logits:
            ret += (outs[2],)
        return ret

    def _windowed_layer(self, pos, pids, offs, table, aids=None):
        """ONE ragged-attention transformer layer shared by the verify
        window (`_verify_step`), the chunked prefill
        (`_prefill_suffix_step`) and every tick of the mixed ragged
        horizon (`_ragged_multi_step`): write each position's K/V at
        (pids, offs) — callers route out-of-range/padded positions to
        the scratch page — attend over the row's pages with
        per-position causality (kpos <= pos) through the shared
        `ops.ragged_paged_attention` primitive, then residual proj +
        FFN. A single body means a masking or scratch-routing fix can
        never diverge the programs (the byte-identical cache-on/off and
        ragged-vs-per-tick guarantees ride on every path computing
        exactly the same per-position bytes)."""
        cfg = self.cfg
        H, D = cfg.num_heads, cfg.head_dim
        n, W = pos.shape
        quant = self.quant

        def layer(x, wkv):
            wl, kp, vp = wkv
            y = _ln(x, wl["ln1_w"], wl["ln1_b"])
            yf = y.reshape(n * W, -1)
            qkv = _mm_heads(yf, wl["qkv_w"],
                            wl["qkv_b"], quant).reshape(n, W, 3, H, D)
            if aids is not None:
                # every window token of row i wears row i's adapter
                aid_tok = jnp.broadcast_to(
                    aids[:, None], (n, W)).reshape(-1)
                qkv = qkv + _lora_delta(wl, yf, aid_tok).reshape(
                    n, W, 3, H, D).astype(qkv.dtype)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            kp = _kv_set(kp, pids, offs, k)
            vp = _kv_set(vp, pids, offs, v)
            # pos rows are contiguous windows (start + arange(W)), so
            # the row's first entry IS its cached length
            from ..ops.ragged_paged_attention import ragged_paged_attention
            attn = ragged_paged_attention(
                q, kp, vp, table, pos[:, 0],
                use_kernel=self.use_kernel).astype(x.dtype)
            o = _mm(attn.reshape(n * W, H * D), wl["proj_w"],
                    wl["proj_b"], quant).reshape(n, W, -1)
            x = x + o
            y = _ln(x, wl["ln2_w"], wl["ln2_b"])
            h = jax.nn.gelu(
                _mm(y.reshape(n * W, -1), wl["fc1_w"], wl["fc1_b"],
                    quant), approximate=True)
            x = x + _mm(h, wl["fc2_w"], wl["fc2_b"],
                        quant).reshape(n, W, -1)
            return x, (kp, vp)

        return layer

    def _verify_step(self, weights, k_pages, v_pages, tokens, lens, table):
        """Speculative verify: tokens [S, W] (last accepted token + the
        draft proposals) are consumed in ONE forward — KV written at
        positions lens..lens+W-1, causal attention against the paged
        prefix — returning the target's greedy choice after every
        position ([S, W] argmaxes). Rejected positions need no cleanup:
        lens is the source of truth and stale entries are overwritten."""
        cfg, ps = self.cfg, self.page_size
        S, W = tokens.shape
        pos = lens[:, None] + jnp.arange(W)[None, :]            # [S, W]
        x = (self.wte[tokens] +
             self.wpe[jnp.clip(pos, 0, cfg.max_seq_len - 1)]
             ).astype(self.compute_dtype)                       # [S, W, h]
        MP = table.shape[1]
        # margin guard: window positions past the table's capacity (the
        # engine admits with a +k margin, so only pathological callers
        # get here) write to the reserved scratch page, never to a
        # clamped REAL page of the sequence
        in_range = pos < MP * ps
        pids = jnp.take_along_axis(table, jnp.minimum(pos // ps, MP - 1),
                                   axis=1)                      # [S, W]
        pids = jnp.where(in_range, pids, self.num_pages - 1)
        offs = pos % ps

        x, (k_pages, v_pages) = jax.lax.scan(
            self._windowed_layer(pos, pids, offs, table), x,
            (weights, k_pages, v_pages))
        x = _ln(x, self.ln_f_w, self.ln_f_b)
        logits = x.astype(jnp.float32) @ self.lm_head.astype(jnp.float32)
        return (jnp.argmax(logits, axis=-1).astype(jnp.int32), logits,
                k_pages, v_pages)

    def verify(self, tokens, lens, table, return_probs=False):
        """Batched speculative verify (see _verify_step)."""
        if self._verify is None:
            self._verify = jax.jit(self._verify_step,
                                   donate_argnums=(1, 2))
        out, logits, self.k_pages, self.v_pages = self._verify(
            self.weights, self.k_pages, self.v_pages,
            jnp.asarray(tokens, jnp.int32), jnp.asarray(lens, jnp.int32),
            jnp.asarray(table, jnp.int32))
        if return_probs:
            return np.asarray(out), self._probs_of(logits)
        return np.asarray(out)

    def _ragged_forward(self, weights, k_pages, v_pages, ids, start,
                        true_len, table, kids, frozen=None, aids=None):
        """The shared RAGGED chunk forward: consume each row's [W]-wide
        window of new tokens at positions start..true_len-1, attending
        against the row's paged prefix. ids [n, W] window tokens
        (zero-padded), start [n] positions already in the pages (cached
        prefix + previously consumed chunks; = the decode position for
        a decode row), true_len [n] position count after this window,
        table [n, max_pages], kids [n] sampling key ids, `frozen` [n]
        routes EVERY write of a frozen row to scratch (the fused
        horizon's done mask).

        K/V is written at positions start..true_len-1 — padded
        positions (pos >= true_len) and table overflow route to the
        reserved scratch page, so real pages hold ONLY real KV (full
        blocks become content-addressable cache entries). Per-position
        computations are independent of the padded width W and the
        batch rows (matmuls are row-local, attention reduces over the
        row's own page gather), so a position's bytes are identical
        whether it was computed alone, in a batch, as a decode tick
        (W=1 window) or inside any chunking of its prompt — the
        property every byte-identical equivalence test pins. The layer
        body is `_windowed_layer`, shared with `_verify_step`. Returns
        (next token [n] — sampled at position true_len-1 with the
        standard (seed, kid, position) key — k_pages, v_pages)."""
        cfg, ps = self.cfg, self.page_size
        n, W = ids.shape
        pos = start[:, None] + jnp.arange(W)[None, :]           # [n, W]
        x = (self.wte[ids] +
             self.wpe[jnp.clip(pos, 0, cfg.max_seq_len - 1)]
             ).astype(self.compute_dtype)                       # [n, W, h]
        MP = table.shape[1]
        # scratch-route every write that isn't a real position: the
        # padded tail (pos >= true_len), table overflow, frozen rows
        in_range = (pos < true_len[:, None]) & (pos < MP * ps)
        if frozen is not None:
            in_range = in_range & ~frozen[:, None]
        pids = jnp.take_along_axis(table, jnp.minimum(pos // ps, MP - 1),
                                   axis=1)                      # [n, W]
        pids = jnp.where(in_range, pids, self.num_pages - 1)
        offs = pos % ps

        x, (k_pages, v_pages) = jax.lax.scan(
            self._windowed_layer(pos, pids, offs, table, aids=aids), x,
            (weights, k_pages, v_pages))
        x = _ln(x, self.ln_f_w, self.ln_f_b)
        last = jnp.take_along_axis(
            x, jnp.clip(true_len - 1 - start, 0, W - 1)
            [:, None, None].astype(jnp.int32), axis=1)[:, 0]    # [n, h]
        logits = last.astype(jnp.float32) @ \
            self.lm_head.astype(jnp.float32)
        keys = None
        if self.sampling is not None:
            # same (seed, kid, position) key walk as decode: the
            # window's last token sits at true_len-1, whatever span of
            # the prompt was cache-mounted or chunked before it
            keys = self._pos_keys(kids, true_len - 1)
        return _sample_tokens(logits, self.sampling, keys), \
            k_pages, v_pages

    def _prefill_suffix_step(self, weights, k_pages, v_pages, ids, start,
                             true_len, table, kids, aids=None):
        """Chunked prefill: consume the UNCACHED suffix of each prompt
        in one forward, attending against the paged prefix (the
        prefix-cache mounts cached pages into `table` host-side; a
        `start=0` row is simply a full, uncached prompt). The body is
        `_ragged_forward` — the same program shape as a decode tick,
        which is its W=1 special case."""
        return self._ragged_forward(weights, k_pages, v_pages, ids,
                                    start, true_len, table, kids,
                                    aids=aids)

    def _ragged_multi_step(self, weights, k_pages, v_pages, tokens, lens,
                           table, kids, done, remaining, eos, pend,
                           pend_n, aids=None, *, k, w):
        """K MIXED ragged ticks inside ONE compiled program: every tick
        serves decode rows and prefill-chunk rows together through the
        same `_ragged_forward` body (Ragged Paged Attention, arxiv
        2604.15464) — so a prompt streams into the KV pool w tokens per
        tick WITHOUT a separate host-blocking prefill dispatch, and
        running decode slots keep emitting a token per tick alongside
        it.

        Carry per slot: `tokens` [S] last emitted token, `lens` [S]
        positions consumed so far (mounted prefix + chunks + decode
        appends), `done`/`remaining` as in `_decode_multi_step`, and
        the device-resident prompt suffix `pend` [S, P] with its length
        `pend_n` [S] (P static = the pool's token capacity). A tick's
        window for slot s is its next min(pend_n, w) suffix tokens
        while prefilling (new_len up to w), or its one sampled token
        once decoding (new_len=1) — the ragged row kinds of the paper.
        A prefill row emits nothing until the tick that consumes its
        last suffix token, which samples the first generated token at
        position true_len-1 with the standard (seed, kid, position)
        key — exactly the token the host-blocking chunked prefill
        would have produced, so streams are byte-identical across
        schedules. Frozen slots' writes route to scratch as in the
        decode-only loop.

        Returns (block [k, S] tokens, emitted [k, S] — True where the
        tick really produced a token (False for filler AND mid-prefill
        ticks) — final tokens/lens/done/remaining/pend/pend_n,
        k_pages, v_pages)."""
        S = tokens.shape[0]
        P = pend.shape[1]

        def tick(carry, _):
            tokens, lens, done, remaining, pend, pend_n, kp, vp = carry
            is_pf = pend_n > 0
            new_len = jnp.where(is_pf, jnp.minimum(pend_n, w), 1)
            window = jnp.concatenate(
                [tokens[:, None],
                 jnp.zeros((S, w - 1), jnp.int32)], axis=1) \
                if w > 1 else tokens[:, None]
            ids = jnp.where(is_pf[:, None], pend[:, :w], window)
            true = lens + new_len
            nxt, kp, vp = self._ragged_forward(
                weights, kp, vp, ids, lens, true, table, kids,
                frozen=done, aids=aids)
            emit = ~done & (pend_n <= w)       # decode row, or the
            nxt = jnp.where(emit, nxt, tokens)  # chunk finishing prefill
            rem = jnp.where(emit, remaining - 1, remaining)
            new_done = done | (emit & ((nxt == eos) | (rem <= 0)))
            new_lens = jnp.where(done, lens, lens + new_len)
            # real positions this tick consumed (the pad-fraction
            # ledger's numerator): live rows' new_len, frozen rows 0 —
            # the dense tick dispatched S*w positions for these
            real = jnp.sum(jnp.where(done, 0, new_len)).astype(jnp.int32)
            pend = jnp.concatenate(
                [pend[:, w:], jnp.zeros((S, min(w, P)), pend.dtype)],
                axis=1)[:, :P]
            pend_n = jnp.maximum(pend_n - w, 0)
            return (nxt, new_lens, new_done, rem, pend, pend_n, kp, vp), \
                (nxt, emit, real)

        carry = (tokens, lens, done, remaining, pend, pend_n,
                 k_pages, v_pages)
        carry, outs = jax.lax.scan(tick, carry, jnp.arange(k))
        tokens, lens, done, remaining, pend, pend_n, k_pages, v_pages = \
            carry
        return (outs[0], outs[1], outs[2], tokens, lens, done, remaining,
                pend, pend_n, k_pages, v_pages)

    def _packed_layer(self, rows, pos, pids, offs, table, aids=None):
        """ONE transformer layer over the PACKED token stream: x is
        [T, h] flat new tokens (token t of batch row `rows[t]` at
        absolute position `pos[t]`); K/V writes land at (pids, offs) —
        the caller routes padded/frozen/overflow tokens to scratch —
        and attention runs through the packed ragged primitive
        (`ops.ragged_paged_attention_packed`), which resolves each
        token's pages via its row id. Per-token math is the dense
        `_windowed_layer`'s exactly (row-local matmuls, the same
        per-page attention walk), so a real position's bytes are
        bit-identical packed vs dense — the A/B-twin guarantee."""
        cfg = self.cfg
        H, D = cfg.num_heads, cfg.head_dim
        T = rows.shape[0]
        quant = self.quant

        def layer(x, wkv):
            wl, kp, vp = wkv
            y = _ln(x, wl["ln1_w"], wl["ln1_b"])
            qkv = _mm_heads(y, wl["qkv_w"], wl["qkv_b"],
                            quant)                       # [T, 3, H, D]
            if aids is not None:
                # per-token adapter resolution via the row id — the
                # same idiom the packed attention uses for pages
                qkv = qkv + _lora_delta(wl, y, aids[rows]).reshape(
                    T, 3, H, D).astype(qkv.dtype)
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            kp = _kv_set(kp, pids, offs, k)
            vp = _kv_set(vp, pids, offs, v)
            from ..ops.ragged_paged_attention import \
                ragged_paged_attention_packed
            attn = ragged_paged_attention_packed(
                q, kp, vp, table, rows, pos,
                use_kernel=self.use_kernel).astype(x.dtype)
            x = x + _mm(attn.reshape(T, H * D), wl["proj_w"],
                        wl["proj_b"], quant)
            y = _ln(x, wl["ln2_w"], wl["ln2_b"])
            h = jax.nn.gelu(_mm(y, wl["fc1_w"], wl["fc1_b"], quant),
                            approximate=True)
            x = x + _mm(h, wl["fc2_w"], wl["fc2_b"], quant)
            return x, (kp, vp)

        return layer

    def _packed_forward(self, weights, k_pages, v_pages, ptok, pos, rows,
                        write_ok, table, last_idx, sample_pos, kids,
                        live, aids=None):
        """The shared PACKED forward: consume the flat token stream
        `ptok` [T] (token t = row `rows[t]`, position `pos[t]`),
        writing real tokens' K/V into the pages (`write_ok` [T] False
        routes to scratch: padded tail, frozen rows, table overflow)
        and attending each token over its own row's pages. `last_idx`
        [S] indexes each row's LAST stream token (garbage for rows with
        no tokens — masked by `live`), whose hidden state prices the
        row's logits; `sample_pos` [S] is the sampling position
        (true_len - 1, the standard (seed, kid, position) key walk).
        Returns (next [S], k_pages, v_pages) — exactly what the dense
        `_ragged_forward` returns, from exactly the same per-position
        bytes."""
        cfg, ps = self.cfg, self.page_size
        MP = table.shape[1]
        x = (self.wte[ptok] +
             self.wpe[jnp.clip(pos, 0, cfg.max_seq_len - 1)]
             ).astype(self.compute_dtype)                 # [T, h]
        pids = jnp.take_along_axis(
            table[rows], jnp.minimum(pos // ps, MP - 1)[:, None],
            axis=1)[:, 0]                                 # [T]
        pids = jnp.where(write_ok, pids, self.num_pages - 1)
        offs = pos % ps

        x, (k_pages, v_pages) = jax.lax.scan(
            self._packed_layer(rows, pos, pids, offs, table, aids=aids),
            x, (weights, k_pages, v_pages))
        x = _ln(x, self.ln_f_w, self.ln_f_b)
        last = x[jnp.clip(last_idx, 0, x.shape[0] - 1)]   # [S, h]
        last = jnp.where(live[:, None], last, 0.0)
        logits = last.astype(jnp.float32) @ \
            self.lm_head.astype(jnp.float32)
        keys = None
        if self.sampling is not None:
            keys = self._pos_keys(kids, sample_pos)
        return _sample_tokens(logits, self.sampling, keys), \
            k_pages, v_pages

    def _packed_multi_step(self, weights, k_pages, v_pages, tokens, lens,
                           table, kids, done, remaining, eos, pend,
                           pend_n, w, aids=None, *, k, t):
        """K MIXED ticks over the PACKED [t] token stream — the
        tentpole layout (Ragged Paged Attention, arxiv 2604.15464): a
        tick's stream concatenates every live row's new tokens (decode
        rows exactly ONE token, prefilling rows their next min(pend_n,
        w) suffix tokens, frozen rows NOTHING), so nobody pays window
        padding — the dense twin (`_ragged_multi_step`) dispatches
        S*w positions per tick, this dispatches at most t, bucketed by
        total token count alone. `w` is a TRACED scalar (the per-row
        chunk cap): per-dispatch width changes recompile nothing; the
        jit key is (k, t) — fewer compiled variants than the dense
        (k, w) grid by construction. The layout (cumsum + searchsorted
        over per-row token counts) is built on device each tick from
        the carry, so the program stays one host-sync-free lax.scan
        (SERVE-HOST-SYNC-DECODE gates it like the dense twin).

        Every per-row rule is the dense tick's verbatim: same emit
        condition, same (seed, kid, true_len-1) sampling keys, same
        freeze/budget updates, same scratch routing — and per-position
        math rides the shared packed primitive — so streams and pool
        bytes are byte-identical to the dense twin and the per-tick
        engine (test-pinned). Returns the RaggedMultiOut tuple layout
        (tokens_block [k, S], emitted [k, S], real [k], finals...)."""
        S = tokens.shape[0]
        P = pend.shape[1]
        MP = table.shape[1]
        ps = self.page_size

        def tick(carry, _):
            tokens, lens, done, remaining, pend, pend_n, kp, vp = carry
            is_pf = pend_n > 0
            # per-row stream share: decode 1, prefill min(pend_n, w),
            # frozen 0 (the packed layout simply skips frozen rows —
            # the dense twin computes their scratch-routed windows)
            nl = jnp.where(done, 0,
                           jnp.where(is_pf, jnp.minimum(pend_n, w), 1))
            csum = jnp.cumsum(nl)
            total = csum[-1]
            starts = csum - nl
            ti = jnp.arange(t)
            rows = jnp.clip(
                jnp.searchsorted(csum, ti, side="right"), 0, S - 1
            ).astype(jnp.int32)
            within = (ti - starts[rows]).astype(jnp.int32)
            valid = ti < total
            pos = lens[rows] + within                     # [t]
            ptok = jnp.where(
                is_pf[rows], pend[rows, jnp.clip(within, 0, P - 1)],
                tokens[rows])
            ptok = jnp.where(valid, ptok, 0)
            write_ok = valid & ~done[rows] & (pos < MP * ps)
            true = lens + nl                              # [S]
            last_idx = jnp.clip(csum - 1, 0, t - 1)
            live = ~done & (nl > 0)
            nxt, kp, vp = self._packed_forward(
                weights, kp, vp, ptok, pos, rows, write_ok, table,
                last_idx, true - 1, kids, live, aids=aids)
            emit = ~done & (pend_n <= w)
            nxt = jnp.where(emit, nxt, tokens)
            rem = jnp.where(emit, remaining - 1, remaining)
            new_done = done | (emit & ((nxt == eos) | (rem <= 0)))
            new_lens = jnp.where(done, lens, lens + nl)
            real = total.astype(jnp.int32)
            # shift each row's suffix by the DYNAMIC w (a gather — the
            # dense twin's static concatenate+slice can't take a traced
            # width); over-shift past pend_n clears like the dense path
            idx = jnp.arange(P)[None, :] + w
            pend = jnp.where(idx < P,
                             pend[jnp.arange(S)[:, None],
                                  jnp.clip(idx, 0, P - 1)], 0)
            pend_n = jnp.maximum(pend_n - w, 0)
            return (nxt, new_lens, new_done, rem, pend, pend_n, kp, vp), \
                (nxt, emit, real)

        carry = (tokens, lens, done, remaining, pend, pend_n,
                 k_pages, v_pages)
        carry, outs = jax.lax.scan(tick, carry, jnp.arange(k))
        tokens, lens, done, remaining, pend, pend_n, k_pages, v_pages = \
            carry
        return (outs[0], outs[1], outs[2], tokens, lens, done, remaining,
                pend, pend_n, k_pages, v_pages)

    def _prefill_packed_step(self, weights, k_pages, v_pages, ptok, pos,
                             rows, write_ok, table, last_idx, sample_pos,
                             kids, live, aids=None):
        """PACKED chunked prefill: one forward over the flat suffix
        stream of a whole admission batch — mixed suffix lengths share
        ONE compiled program per total-token bucket instead of one per
        (suffix-width, batch) pair (`prefill_suffix_batch` builds the
        layout host-side). The body is `_packed_forward`, the same
        program family as the packed horizon tick."""
        return self._packed_forward(weights, k_pages, v_pages, ptok,
                                    pos, rows, write_ok, table,
                                    last_idx, sample_pos, kids, live,
                                    aids=aids)

    # -- host-side API -----------------------------------------------------

    def prefill(self, ids, page_ids, kid=None):
        """Run one prompt through the model, writing KV into `page_ids`;
        returns the next token (greedy, or sampled per the decoder's
        temperature/top_k/top_p config)."""
        return self.prefill_batch([(ids, page_ids)],
                                  kids=None if kid is None else [kid])[0]

    def prefill_batch(self, requests, kids=None):
        """Prefill several prompts in full. requests: [(ids, page_ids),
        ...]; returns the first generated token per request (in order).
        `kids` are the per-request sampling key ids (see _pos_keys; the
        engine passes request ids — default: the request's index in
        this call).

        A thin wrapper over the chunked ragged body at start=0: the
        separate flash-attention length-bucketed prefill is GONE — ALL
        prefill runs through the same per-position program family as
        decode and the verify window (`_ragged_forward`), so a prompt's
        KV bytes are identical across every admission path (flash vs
        chunked drift is structurally impossible)."""
        return self.prefill_suffix_batch(
            [(ids, 0, pages) for ids, pages in requests], kids=kids)

    def prefill_suffix_batch(self, requests, kids=None, packed=None,
                             aids=None):
        """Chunked prefill over page-table rows (the prefix-cache
        admission path). requests: [(suffix_ids, start, pages), ...] —
        `pages` is the sequence's page list in block order (cached
        prefix pages mounted by the engine + freshly allocated suffix
        pages), `start` the cached prefix length (0 = nothing cached:
        the suffix IS the prompt).

        PACKED (the default): each group of up to max_batch requests
        dispatches ONE flat [total_tokens] stream
        (`_prefill_packed_step`) bucketed by total token count (pow2)
        — mixed suffix lengths share one compiled program instead of
        one per (suffix-width, batch) pair, and nobody pays
        pad-to-longest window columns. `packed=False` keeps the dense
        window twin (`_prefill_suffix_step`, per-(W, nb) pow2 buckets)
        — byte-identical first tokens (per-position math is layout-
        independent, test-pinned). Returns the first generated token
        per request (in order)."""
        if packed is None:
            packed = self.packed
        if packed:
            return self._prefill_packed_batch(requests, kids=kids,
                                              aids=aids)
        results = [None] * len(requests)
        if kids is None:
            kids = list(range(len(requests)))
        if aids is None:
            aids = [0] * len(requests)
        if self._suffix_prefill is None:
            self._suffix_prefill = jax.jit(self._prefill_suffix_step,
                                           donate_argnums=(1, 2))
        MP = self.max_pages
        groups = {}
        for i, (ids, start, pages) in enumerate(requests):
            ids = np.asarray(ids, np.int32)
            W = 4
            while W < len(ids):
                W *= 2
            groups.setdefault(W, []).append((i, ids, int(start), pages))
        for W, group in groups.items():
            while group:
                nb = 1
                while nb * 2 <= len(group) and nb * 2 <= self.max_batch:
                    nb *= 2
                chunk, group = group[:nb], group[nb:]
                pad = np.zeros((nb, W), np.int32)
                st = np.zeros(nb, np.int32)
                tl = np.ones(nb, np.int32)
                tbl = np.full((nb, MP), self.num_pages - 1, np.int32)
                kd = np.zeros(nb, np.int32)
                ad = np.zeros(nb, np.int32)
                for r, (i, ids, start, pages) in enumerate(chunk):
                    pad[r, :len(ids)] = ids
                    st[r] = start
                    tl[r] = start + len(ids)
                    k = min(len(pages), MP)
                    tbl[r, :k] = pages[:k]     # rest stays on scratch
                    kd[r] = kids[i]
                    ad[r] = aids[i]
                self._draws += 1
                call = (jnp.asarray(pad), jnp.asarray(st),
                        jnp.asarray(tl), jnp.asarray(tbl),
                        jnp.asarray(kd))
                if self.lora is not None:
                    call += (jnp.asarray(ad),)
                nxt, self.k_pages, self.v_pages = self._suffix_prefill(
                    self._w(), self.k_pages, self.v_pages, *call)
                nxt = np.asarray(nxt)
                for r, (i, _, _, _) in enumerate(chunk):
                    results[i] = int(nxt[r])
        return results

    def _prefill_packed_batch(self, requests, kids=None, aids=None):
        """PACKED prefill dispatch (see `prefill_suffix_batch`): the
        layout — flat tokens, per-token row ids and positions — is
        built host-side (all lengths are known here), bucketed to a
        pow2 total-token count, and jitted once per bucket
        (`_packed_prefills`)."""
        results = [None] * len(requests)
        if kids is None:
            kids = list(range(len(requests)))
        if aids is None:
            aids = [0] * len(requests)
        S, MP, ps = self.max_batch, self.max_pages, self.page_size
        todo = list(enumerate(requests))
        while todo:
            chunk, todo = todo[:S], todo[S:]
            t = pow2_at_least(sum(len(np.asarray(ids).reshape(-1))
                                  for _, (ids, _, _) in chunk))
            ptok = np.zeros(t, np.int32)
            pos = np.zeros(t, np.int32)
            rows = np.zeros(t, np.int32)
            ok = np.zeros(t, bool)
            last_idx = np.zeros(S, np.int32)
            spos = np.zeros(S, np.int32)
            live = np.zeros(S, bool)
            tbl = np.full((S, MP), self.num_pages - 1, np.int32)
            kd = np.zeros(S, np.int32)
            ad = np.zeros(S, np.int32)
            cur = 0
            for r, (i, (ids, start, pages)) in enumerate(chunk):
                ids = np.asarray(ids, np.int32).reshape(-1)
                n = len(ids)
                ptok[cur:cur + n] = ids
                pos[cur:cur + n] = int(start) + np.arange(n)
                rows[cur:cur + n] = r
                ok[cur:cur + n] = pos[cur:cur + n] < MP * ps
                last_idx[r] = max(cur + n - 1, 0)
                spos[r] = int(start) + n - 1
                live[r] = n > 0
                m = min(len(pages), MP)
                tbl[r, :m] = pages[:m]       # rest stays on scratch
                kd[r] = kids[i]
                ad[r] = aids[i]
                cur += n
            fn = self._packed_prefills.get(t)
            if fn is None:
                fn = jax.jit(self._prefill_packed_step,
                             donate_argnums=(1, 2))
                self._packed_prefills[t] = fn
            self._draws += 1
            call = (jnp.asarray(ptok), jnp.asarray(pos),
                    jnp.asarray(rows), jnp.asarray(ok), jnp.asarray(tbl),
                    jnp.asarray(last_idx), jnp.asarray(spos),
                    jnp.asarray(kd), jnp.asarray(live))
            if self.lora is not None:
                call += (jnp.asarray(ad),)
            nxt, self.k_pages, self.v_pages = fn(
                self._w(), self.k_pages, self.v_pages, *call)
            nxt = np.asarray(nxt)
            for r, (i, _) in enumerate(chunk):
                results[i] = int(nxt[r])
        return results

    def copy_page(self, src, dst):
        """Device-side page copy (K and V, every layer): the engine's
        copy-on-write primitive — a request about to write into a page
        it mounted SHARED gets a private copy first, so cached prefix
        pages stay immutable for their whole cached life."""
        if self._copy is None:
            def cp(kp, vp, s, d):
                # tree_map: an int8 pool's page BYTES and its scale
                # plane rows move together — a copy that left the
                # scales behind would dequantize the private page with
                # the zero-initialized scales (garbage tokens; the
                # MEM-PAGE-REFCOUNT scale audit exists to catch it)
                def one(a):
                    return a.at[:, d].set(a[:, s])
                return (jax.tree_util.tree_map(one, kp),
                        jax.tree_util.tree_map(one, vp))
            self._copy = jax.jit(cp, donate_argnums=(0, 1))
        self.k_pages, self.v_pages = self._copy(
            self.k_pages, self.v_pages,
            jnp.asarray(int(src), jnp.int32),
            jnp.asarray(int(dst), jnp.int32))

    def fetch_page_payload(self, page):
        """D2H copy of ONE page's bytes across every layer — the
        host-tier SPILL primitive: ``{"k": (leaves...), "v": (...)}``
        with each leaf the pool leaf sliced at the page ([L, ps, H, D]
        bytes; int8 pools also carry their [L, ps] f32 scale rows, so
        the spill is already quantized — half the host bytes). The
        inverse is `mount_page_payload`; the round trip is lossless,
        which is what lets a restored page keep the byte-identical-
        stream invariant."""
        p = int(page)

        def grab(pool):
            leaves = pool if isinstance(pool, tuple) else (pool,)
            return tuple(np.asarray(leaf[:, p]) for leaf in leaves)

        return {"k": grab(self.k_pages), "v": grab(self.v_pages)}

    def fetch_page_payloads(self, pages):
        """D2H copy of a WHOLE eviction wave in one stacked transfer
        per pool leaf (`fetch_page_payload` batched): the pool leaf is
        gathered at all `pages` on device ([L, n, ps, ...]) and fetched
        once, then split host-side into the per-page payload dicts the
        host tier stores. Per-page D2H paid one blocking round trip per
        victim — a pressure wave of n evictions cost n syncs for bytes
        the device could have streamed together."""
        idx = jnp.asarray([int(p) for p in pages], jnp.int32)

        def grab(pool):
            leaves = pool if isinstance(pool, tuple) else (pool,)
            return [np.asarray(leaf[:, idx]) for leaf in leaves]

        k_stk, v_stk = grab(self.k_pages), grab(self.v_pages)
        return [{"k": tuple(leaf[:, i] for leaf in k_stk),
                 "v": tuple(leaf[:, i] for leaf in v_stk)}
                for i in range(len(pages))]

    def mount_page_payloads(self, pages, payloads):
        """H2D restore of a WHOLE restored span in one donated jitted
        scatter (`mount_page_payload` batched, jitted per span length):
        every pool leaf takes its [L, n, ps, ...] stacked values at the
        n page ids in one `.at[:, pids].set`. Like the single-page
        mount, the dispatch does not block — jax's functional pool
        threading orders every later horizon after the writes — but an
        n-block restore now pays ONE dispatch instead of n."""
        n = len(pages)
        if n == 1:
            return self.mount_page_payload(pages[0], payloads[0])
        fn = self._mount_multi.get(n)
        if fn is None:
            def mnt(kp, vp, pids, kvals, vvals):
                def setp(pool, vals):
                    leaves = pool if isinstance(pool, tuple) else (pool,)
                    out = [leaf.at[:, pids].set(v)
                           for leaf, v in zip(leaves, vals)]
                    return tuple(out) if isinstance(pool, tuple) \
                        else out[0]
                return setp(kp, kvals), setp(vp, vvals)
            fn = self._mount_multi[n] = jax.jit(mnt,
                                                donate_argnums=(0, 1))

        def stack(part):
            n_leaves = len(payloads[0][part])
            return tuple(jnp.asarray(np.stack(
                [np.asarray(p[part][i]) for p in payloads], axis=1))
                for i in range(n_leaves))

        self.k_pages, self.v_pages = fn(
            self.k_pages, self.v_pages,
            jnp.asarray([int(p) for p in pages], jnp.int32),
            stack("k"), stack("v"))

    def mount_page_payload(self, page, payload):
        """H2D restore of a spilled page (`fetch_page_payload`'s
        inverse): scatter the payload leaves into page `page` of every
        pool leaf. One jitted donated update, dispatched WITHOUT
        blocking — jax's functional pool threading orders every later
        horizon after this write (the restored pool IS its input), so
        the H2D overlaps whatever the host does next and no reader can
        observe a half-mounted page."""
        if self._mount is None:
            def mnt(kp, vp, pid, kvals, vvals):
                def setp(pool, vals):
                    leaves = pool if isinstance(pool, tuple) else (pool,)
                    out = [leaf.at[:, pid].set(v)
                           for leaf, v in zip(leaves, vals)]
                    return tuple(out) if isinstance(pool, tuple) \
                        else out[0]
                return setp(kp, kvals), setp(vp, vvals)
            self._mount = jax.jit(mnt, donate_argnums=(0, 1))
        self.k_pages, self.v_pages = self._mount(
            self.k_pages, self.v_pages, jnp.asarray(int(page), jnp.int32),
            tuple(jnp.asarray(x) for x in payload["k"]),
            tuple(jnp.asarray(x) for x in payload["v"]))

    def pool_state(self):
        """Checkpointable KV-pool state: the page arrays (and, for an
        int8 pool, their scale planes) plus the quant config that
        produced them. `load_pool_state` refuses a mismatched config —
        int8 bytes interpreted as bf16 (or the reverse) would decode
        garbage tokens with no error anywhere downstream."""
        return {"kv_quant": self.kv_quant or "",
                "k_pages": self.k_pages, "v_pages": self.v_pages}

    def load_pool_state(self, state):
        """Restore a `pool_state()` snapshot into this decoder's pool.
        The stored quant config, leaf dtypes and shapes must all match
        this decoder's pool layout exactly — and no attached engine may
        hold pages over the pool: swapping the bytes under a slot
        table, a referenced PrefixCache entry, OR a parked one would
        orphan the page ledger with no error anywhere downstream (a
        parked entry outlives a drain, and its next hit would mount
        the checkpoint's bytes as if they were the chain key's
        write-time KV). Rebuild the decoder+cache pair instead —
        `PrefixCache.load` does exactly that."""
        for eng in list(self._engines):
            held = sum(len(p) for p in getattr(eng, "_slot_pages", ()))
            cache = getattr(eng, "cache", None)
            tracked = len(cache._entries) if cache is not None else 0
            if held or tracked:
                raise RuntimeError(
                    f"cannot load pool state: a live "
                    f"{type(eng).__name__} holds {held} slot page(s) "
                    f"and its prefix cache tracks {tracked} page(s) "
                    "over this pool — swapping the page bytes now "
                    "would orphan its ledger (a parked entry's next "
                    "hit would mount checkpoint bytes under the old "
                    "chain key); drain the engine and rebuild the "
                    "decoder+cache pair (PrefixCache.load) instead")
        quant = state.get("kv_quant", "") or None
        if quant != self.kv_quant:
            raise ValueError(
                f"KV pool quant config mismatch: this decoder stores "
                f"{self.kv_quant or 'unquantized (' + str(jnp.dtype(self.compute_dtype)) + ')'} "
                f"pages but the checkpointed pool was written "
                f"{quant or 'unquantized'} — reinterpreting the bytes "
                "would decode garbage tokens; rebuild the decoder with "
                f"kv_quant={quant!r} or re-prefill from tokens")
        for name in ("k_pages", "v_pages"):
            have = getattr(self, name)
            want = state[name]
            h_leaves = jax.tree_util.tree_leaves(have)
            w_leaves = jax.tree_util.tree_leaves(want)
            if len(h_leaves) != len(w_leaves) or any(
                    hl.shape != wl.shape or
                    jnp.dtype(hl.dtype) != jnp.dtype(wl.dtype)
                    for hl, wl in zip(h_leaves, w_leaves)):
                raise ValueError(
                    f"KV pool state mismatch on {name}: expected "
                    f"{[(tuple(l.shape), str(l.dtype)) for l in h_leaves]}, "
                    f"got "
                    f"{[(tuple(getattr(l, 'shape', ())), str(getattr(l, 'dtype', '?'))) for l in w_leaves]}")
        # jnp.array (copy), NOT jnp.asarray: a host numpy leaf can be
        # zero-copied into the CPU backend, and the decode programs
        # DONATE the pool — XLA must own the bytes it recycles
        self.k_pages = jax.tree_util.tree_map(
            lambda l: jnp.array(l), state["k_pages"])
        self.v_pages = jax.tree_util.tree_map(
            lambda l: jnp.array(l), state["v_pages"])

    @property
    def _pool_itemsize(self):
        """Bytes one stored K (or V) element costs in the pool."""
        leaf = self.k_pages[0] if isinstance(self.k_pages, tuple) \
            else self.k_pages
        return jnp.dtype(leaf.dtype).itemsize

    @property
    def kv_token_bytes(self):
        """KV bytes ONE token costs per layer (K and V together,
        scale-plane metadata included for the int8 pool) — the unit of
        every KV byte count this decoder reports (`kv_page_bytes`,
        `step_hbm_bytes`, ServeStats.kv_bytes_per_token)."""
        return pool_token_bytes(self.cfg, kv_quant=self.kv_quant,
                                itemsize=self._pool_itemsize)

    def kv_token_bytes_by_layer(self):
        """Per-LAYER KV bytes one token costs — the pricing hook for
        layer-mixed precision pools. Today every layer stores the same
        width, so this is `kv_token_bytes` repeated num_layers times;
        `step_hbm_bytes` sums THIS list for the live-pool leg, so the
        day a pool mixes widths across layers (e.g. int8 first/last,
        int4 middle) only this method changes and every capacity /
        horizon / admission consumer re-prices automatically."""
        return [self.kv_token_bytes] * self.cfg.num_layers

    @property
    def kv_page_bytes(self):
        """KV bytes one page holds across all layers (K and V, scale
        planes included) — the prefix cache's bytes-saved unit."""
        return int(self.cfg.num_layers * self.page_size *
                   self.kv_token_bytes)

    def cache_fingerprint(self):
        """Model/sampling-invariant identity of this decoder's KV bytes
        — the prefix cache's root-key salt. KV pages depend on the
        weights, architecture, page size, pool dtype and quant mode but
        NOT on temperature/seed, so two decoders may alias cached pages
        exactly when this matches. Weight identity rides on cheap
        content probes over EVERY stacked tensor (per-tensor f32 sums
        — embeddings alone would alias a frozen-embedding fine-tune
        with its base model)."""
        cfg = self.cfg

        def probe(v):
            if isinstance(v, tuple):         # quantized (q, scale)
                return tuple(probe(x) for x in v)
            return float(jnp.sum(v.astype(jnp.float32)))

        probes = tuple(probe(self.weights[k])
                       for k in sorted(self.weights))
        probes += (probe(self.wte), probe(self.wpe),
                   probe(self.lm_head), probe(self.ln_f_w),
                   probe(self.ln_f_b))
        pool_leaf = self.k_pages[0] if isinstance(self.k_pages, tuple) \
            else self.k_pages
        parts = (cfg.num_layers, cfg.hidden_size, cfg.num_heads,
                 cfg.head_dim, cfg.vocab_size, cfg.max_seq_len,
                 self.page_size, str(jnp.dtype(pool_leaf.dtype)),
                 self.quant or "", self.kv_quant or "", probes)
        return repr(parts).encode()

    def analysis_program(self, donate=True, k=None, prefix_w=None,
                         ragged=None, verify_w=None):
        """Graph Doctor view of the compiled decode program: one fresh
        trace with per-argument role capture — weights/embeddings are
        `param` (read-only across steps, NOT donated: that's correct
        for inference), the K/V page pools are `cache` with
        donated=True matching the real donate_argnums=(1,2) (the cache
        is the decode loop's carried state — an undonated cache is the
        MEM-NO-DONATION-KVCACHE lint), everything else is `input`.

        With `k` the FUSED multi-step program (`_decode_multi_step`, K
        device-resident ticks in one lax.scan) is traced instead of the
        single tick — the SERVE-HOST-SYNC-DECODE rule checks it for
        host transfers and kept cache donation. With `prefix_w` the
        chunked-prefill program is traced — PACKED by default
        (`_prefill_packed_step`, one flat stream at total-token bucket
        S*prefix_w; a `packed=False` decoder traces the dense
        `_prefill_suffix_step` window twin) — the prefix-cache
        admission path, gated by the same serving rules plus the
        MEM-PAGE-REFCOUNT ledger audit (`gpt_decode_prefix` PROGRAM
        config). With `ragged=(k, w)` the MIXED ragged horizon program
        is traced — PACKED by default (`_packed_multi_step`: K ticks
        over the flat [t] token stream, t = the pow2 bucket of one
        w-wide chunk row next to S-1 decode rows, w a traced input;
        `packed=False` traces the dense `_ragged_multi_step` twin) —
        the `gpt_decode_ragged` PROGRAM config gates it with
        SERVE-HOST-SYNC-DECODE and (via an engine schedule trace on
        the context) SERVE-PREFILL-STALL. `donate=False` traces the
        defective variant the planted-defect tests lint.

        With `verify_w` the SPECULATIVE verify-window program
        (`_verify_step`, the SpeculativeEngine's target forward over
        the last accepted token + W-1 draft proposals) is traced with
        the window tokens captured as "draft_tokens" — request-
        EXTRINSIC bytes under the Determinism Doctor's provenance
        lattice, so KV-WRITE-NONCANONICAL fires on its pool writes:
        the documented expected red (draft bytes land in real pages
        BEFORE acceptance; the ROADMAP's commit-on-accept work must
        turn this program green)."""
        from ..analysis.lowering import LoweredProgram, tree_arg_infos

        S = self.max_batch
        W_ALL = self._w()        # adapter banks ride along when attached
        kids = jnp.arange(S, dtype=jnp.int32)
        table = jnp.zeros((S, self.max_pages), jnp.int32)
        # with a LoRA bank attached, every traced program additionally
        # takes the per-slot adapter ids (the gpt_decode_mt PROGRAM
        # config traces the adapter-gather horizon through this)
        aid_in = (jnp.zeros((S,), jnp.int32)
                  if self.lora is not None else None)
        aid_tail = () if aid_in is None else (aid_in,)
        if sum(map(bool, (k, prefix_w, ragged, verify_w))) > 1:
            raise ValueError(
                "pass only one of k=, prefix_w=, ragged=, verify_w=")
        if verify_w:
            W = int(verify_w)
            draft = jnp.zeros((S, W), jnp.int32)
            lens = jnp.zeros((S,), jnp.int32)
            inputs = [("draft_tokens", draft), ("lens", lens),
                      ("table", table)]
            fn = jax.jit(self._verify_step,
                         donate_argnums=(1, 2) if donate else ())
            traced = fn.trace(self.weights, self.k_pages, self.v_pages,
                              draft, lens, table)
            name = f"verify_w{W}"
            infos = tree_arg_infos(self.weights, "param")
            infos += tree_arg_infos(self.k_pages, "cache",
                                    prefix="k_pages", donated=donate)
            infos += tree_arg_infos(self.v_pages, "cache",
                                    prefix="v_pages", donated=donate)
            for nm, v in inputs:
                infos += tree_arg_infos(v, "input", prefix=nm)
            return LoweredProgram(traced.lower().as_text(),
                                  jaxpr=traced.jaxpr, name=name,
                                  arg_infos=infos)
        if ragged:
            rk, rw = map(int, ragged)
            P = self.pend_capacity
            tokens = jnp.zeros((S,), jnp.int32)
            lens = jnp.zeros((S,), jnp.int32)
            done = jnp.zeros((S,), bool)
            remaining = jnp.full((S,), rk, jnp.int32)
            eos = jnp.asarray(-1, jnp.int32)
            pend = jnp.zeros((S, P), jnp.int32)
            pend_n = jnp.zeros((S,), jnp.int32)
            inputs = [("tokens", tokens), ("lens", lens),
                      ("table", table), ("kids", kids), ("done", done),
                      ("remaining", remaining), ("eos", eos),
                      ("pend", pend), ("pend_n", pend_n)]
            if aid_in is not None:
                inputs.append(("aids", aid_in))
            if self.packed:
                # the PACKED horizon program: t = the pow2 total-token
                # bucket of one full-chunk prefill row riding next to
                # S-1 decode rows (the canonical mixed tick); w is a
                # TRACED input, not part of the program identity
                t = pow2_at_least(S - 1 + rw)
                w_in = jnp.asarray(rw, jnp.int32)
                inputs.append(("w", w_in))
                fn = jax.jit(functools.partial(self._packed_multi_step,
                                               k=rk, t=t),
                             donate_argnums=(1, 2) if donate else ())
                traced = fn.trace(W_ALL, self.k_pages,
                                  self.v_pages, tokens, lens, table,
                                  kids, done, remaining, eos, pend,
                                  pend_n, w_in, *aid_tail)
                name = f"ragged_packed_k{rk}_t{t}"
            else:
                fn = jax.jit(functools.partial(self._ragged_multi_step,
                                               k=rk, w=rw),
                             donate_argnums=(1, 2) if donate else ())
                traced = fn.trace(W_ALL, self.k_pages,
                                  self.v_pages, tokens, lens, table,
                                  kids, done, remaining, eos, pend,
                                  pend_n, *aid_tail)
                name = f"ragged_multi_k{rk}_w{rw}"
        elif prefix_w:
            W = int(prefix_w)
            if self.packed:
                # the PACKED prefill program: one flat stream covering
                # a full admission batch at suffix bucket W — the
                # total-token bucket S*W replaces the (W, nb) grid
                t = pow2_at_least(S * W)
                ptok = jnp.zeros((t,), jnp.int32)
                pos = jnp.zeros((t,), jnp.int32)
                rows = jnp.zeros((t,), jnp.int32)
                ok = jnp.zeros((t,), bool)
                last_idx = jnp.zeros((S,), jnp.int32)
                spos = jnp.zeros((S,), jnp.int32)
                live = jnp.ones((S,), bool)
                inputs = [("ptok", ptok), ("pos", pos), ("rows", rows),
                          ("write_ok", ok), ("table", table),
                          ("last_idx", last_idx), ("sample_pos", spos),
                          ("kids", kids), ("live", live)]
                if aid_in is not None:
                    inputs.append(("aids", aid_in))
                fn = jax.jit(self._prefill_packed_step,
                             donate_argnums=(1, 2) if donate else ())
                traced = fn.trace(W_ALL, self.k_pages,
                                  self.v_pages, ptok, pos, rows, ok,
                                  table, last_idx, spos, kids, live,
                                  *aid_tail)
                name = f"prefill_packed_t{t}"
            else:
                ids = jnp.zeros((S, W), jnp.int32)
                start = jnp.zeros((S,), jnp.int32)
                true_len = jnp.ones((S,), jnp.int32)
                inputs = [("ids", ids), ("start", start),
                          ("true_len", true_len), ("table", table),
                          ("kids", kids)]
                if aid_in is not None:
                    inputs.append(("aids", aid_in))
                fn = jax.jit(self._prefill_suffix_step,
                             donate_argnums=(1, 2) if donate else ())
                traced = fn.trace(W_ALL, self.k_pages,
                                  self.v_pages, ids, start, true_len,
                                  table, kids, *aid_tail)
                name = f"prefill_suffix_w{W}"
        elif k:
            tokens = jnp.zeros((S,), jnp.int32)
            lens = jnp.zeros((S,), jnp.int32)
            done = jnp.zeros((S,), bool)
            remaining = jnp.full((S,), int(k), jnp.int32)
            eos = jnp.asarray(-1, jnp.int32)
            inputs = [("tokens", tokens), ("lens", lens),
                      ("table", table), ("kids", kids), ("done", done),
                      ("remaining", remaining), ("eos", eos)]
            if aid_in is not None:
                inputs.append(("aids", aid_in))
            fn = jax.jit(functools.partial(self._decode_multi_step,
                                           k=int(k)),
                         donate_argnums=(1, 2) if donate else ())
            traced = fn.trace(W_ALL, self.k_pages, self.v_pages,
                              tokens, lens, table, kids, done, remaining,
                              eos, *aid_tail)
            name = f"decode_multi_k{int(k)}"
        else:
            tokens = jnp.zeros((S,), jnp.int32)
            lens = jnp.zeros((S,), jnp.int32)
            inputs = [("tokens", tokens), ("lens", lens),
                      ("table", table), ("kids", kids)]
            if aid_in is not None:
                inputs.append(("aids", aid_in))
            fn = jax.jit(self._decode_step,
                         donate_argnums=(1, 2) if donate else ())
            traced = fn.trace(W_ALL, self.k_pages, self.v_pages,
                              tokens, lens, table, kids, *aid_tail)
            name = "decode_step"
        infos = tree_arg_infos(W_ALL, "param")
        infos += tree_arg_infos(self.k_pages, "cache", prefix="k_pages",
                                donated=donate)
        infos += tree_arg_infos(self.v_pages, "cache", prefix="v_pages",
                                donated=donate)
        for nm, v in inputs:
            infos += tree_arg_infos(v, "input", prefix=nm)
        return LoweredProgram(traced.lower().as_text(),
                              jaxpr=traced.jaxpr, name=name,
                              arg_infos=infos)

    def step_hbm_bytes(self, avg_ctx=None, batch=None, kv_quant="pool"):
        """HBM bytes ONE decode tick moves: every weight byte plus each
        slot's KV prefix at `avg_ctx` (default: half the model's max
        sequence). The numerator of the decode tick roofline —
        `cost_model.decode_horizon` prices the default multi-step K
        from it; bench.decode_roofline_tok_s is the tok/s view of the
        same bytes model. An int8 pool reports its TRUE byte stream
        (int8 payload + the f32 per-token scale planes), so the horizon
        K, the ragged chunk budget and the capacity bench all re-price
        automatically when the pool quantizes. `batch` overrides the
        slot count (bench.run_decode_capacity sweeps it to find the
        max slots under a fixed per-token p99). `kv_quant` overrides
        the pool's quant mode for WHAT-IF pricing — e.g.
        ``kv_quant="int4"`` prices the per-group-scale int4 pool
        (packed nibbles + f32 group scales, `pool_token_bytes`) on a
        decoder whose live pool runs another width, so capacity
        planning can rank bf16 vs int8 vs int4 streams from one
        decoder. The live-pool path sums `kv_token_bytes_by_layer`, so
        a future per-layer mixed-precision pool re-prices here with no
        caller changes."""
        cfg = self.cfg
        n = cfg.num_params()
        per = {"a8w8": 1.0, "w4a16": 0.5}.get(self.quant)
        if per is not None:
            h, f = cfg.hidden_size, cfg.ffn_hidden
            lin = cfg.num_layers * (4 * h * h + 2 * h * f)
            w_bytes = lin * per + (n - lin) * 2
        else:
            w_bytes = n * 2
        if avg_ctx is None:
            avg_ctx = max(cfg.max_seq_len // 2, 1)
        if batch is None:
            batch = self.max_batch
        if kv_quant == "pool":
            return int(w_bytes +
                       batch * avg_ctx * sum(self.kv_token_bytes_by_layer()))
        else:
            # what-if override: an UNQUANTIZED what-if must price the
            # compute dtype's width, not the live pool's leaf itemsize
            # (on an int8 pool that is 1 byte, which would rank the
            # "unquantized" stream CHEAPER than int8 — backwards)
            itemsize = self._pool_itemsize if self.kv_quant is None \
                else jnp.dtype(self.compute_dtype).itemsize
            tok_bytes = pool_token_bytes(cfg, kv_quant=kv_quant,
                                         itemsize=itemsize)
        kv = batch * cfg.num_layers * avg_ctx * tok_bytes
        return int(w_bytes + kv)

    def _kids_or_default(self, kids):
        if kids is None:
            return np.arange(self.max_batch, dtype=np.int32)
        return np.asarray(kids, np.int32)

    def decode(self, tokens, lens, table, kids=None, return_probs=False,
               aids=None):
        """One decode step for all slots (greedy, or the configured
        sampling with deterministic per-(seed, kid, position) keys —
        kid defaults to the slot index; the engine passes request ids
        so a request's draws are scheduling-independent).
        return_probs additionally yields the [S, V] distribution the
        token was drawn from (speculative acceptance needs it). `aids`
        [S] selects per-slot LoRA adapters when a bank is attached
        (`attach_adapters`); without one it must stay None."""
        self._draws += 1
        args = (self._w(), self.k_pages, self.v_pages,
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(lens, jnp.int32),
                jnp.asarray(table, jnp.int32),
                jnp.asarray(self._kids_or_default(kids)))
        if self.lora is not None:
            args += (jnp.asarray(self._aids_or_default(aids)),)
        nxt, logits, self.k_pages, self.v_pages = self._decode(*args)
        if return_probs:
            return nxt, self._probs_of(logits)
        return nxt

    def decode_multi(self, tokens, lens, table, k, kids=None, done=None,
                     remaining=None, eos=None, return_logits=False,
                     aids=None):
        """Run `k` decode ticks device-resident: ONE dispatch, zero
        intermediate host syncs (see `_decode_multi_step`). Jitted per
        (k, return_logits); the engine buckets k to powers of two so
        the compile count stays bounded like the prefill buckets.

        All inputs/outputs may stay on device: the engine feeds the
        returned tokens/lens/done/remaining straight into the next
        horizon's call and fetches tokens_block/done_before only at
        sync points. `kids` are per-slot sampling key ids (see
        `_pos_keys`; default slot index), `done` marks slots frozen
        from tick 0 (default none), `remaining` per-slot token budgets
        (default unlimited), `eos` the stop token (default none).
        Returns a MultiDecodeOut;
        `logits_block` is None unless return_logits (speculation wants
        the draft's distributions)."""
        k = int(k)
        S = self.max_batch
        key = (k, bool(return_logits))
        fn = self._multis.get(key)
        if fn is None:
            fn = jax.jit(
                functools.partial(self._decode_multi_step, k=k,
                                  return_logits=bool(return_logits)),
                donate_argnums=(1, 2))
            self._multis[key] = fn
        if done is None:
            done = np.zeros(S, bool)
        if remaining is None:
            remaining = np.full(S, np.iinfo(np.int32).max // 2, np.int32)
        self._draws += k             # dispatch telemetry, not key state
        args = (self._w(), self.k_pages, self.v_pages,
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(lens, jnp.int32),
                jnp.asarray(table, jnp.int32),
                jnp.asarray(self._kids_or_default(kids)),
                jnp.asarray(done, bool),
                jnp.asarray(remaining, jnp.int32),
                jnp.asarray(-1 if eos is None else int(eos), jnp.int32))
        if self.lora is not None:
            args += (jnp.asarray(self._aids_or_default(aids)),)
        out = fn(*args)
        self.k_pages, self.v_pages = out[6], out[7]
        return MultiDecodeOut(out[0], out[1], out[2], out[3], out[4],
                              out[5], out[8] if return_logits else None)

    @property
    def pend_capacity(self):
        """Static width of the ragged horizon's device-resident prompt
        suffix buffer: the pool's per-sequence token capacity (ONE
        compiled shape — no per-prompt-length buckets)."""
        return self.max_pages * self.page_size

    def ragged_multi(self, tokens, lens, table, k, w, pend, pend_n,
                     kids=None, done=None, remaining=None, eos=None,
                     packed=None, t_tokens=None, aids=None):
        """Run `k` MIXED ragged ticks device-resident: decode rows and
        prefill-chunk rows serve together, up to w suffix tokens per
        prefilling slot per tick, ONE dispatch, zero intermediate host
        syncs.

        PACKED (the default, `packed=None` -> the decoder's `packed`
        flag): each tick dispatches the flat [t_tokens] token stream
        (`_packed_multi_step`) — decode rows pay ONE token, not a
        w-wide window — jitted per (k, t_tokens) with w riding as a
        traced scalar, so dispatches bucket by TOTAL token count
        (pow2; the scheduler's `HorizonPlan.t_tokens` prices it) and
        per-dispatch width changes never compile a new variant.
        `t_tokens` must cover the largest per-tick total (live rows +
        chunk shares; defaults to the dense-equivalent S*w bound when
        the caller doesn't supply the tight bucket). `packed=False`
        dispatches the dense [S, w] window twin (`_ragged_multi_step`,
        jitted per (k, w)) — byte-identical streams, kept for A/B
        pad-fraction evidence.

        All inputs/outputs may stay on device; `pend` [S, P] /
        `pend_n` [S] are the carried prompt suffixes
        (P = `pend_capacity`). Returns a RaggedMultiOut."""
        k, w = int(k), int(w)
        S = self.max_batch
        if packed is None:
            packed = self.packed
        if done is None:
            done = np.zeros(S, bool)
        if remaining is None:
            remaining = np.full(S, np.iinfo(np.int32).max // 2, np.int32)
        self._draws += k             # dispatch telemetry, not key state
        args = (jnp.asarray(tokens, jnp.int32),
                jnp.asarray(lens, jnp.int32),
                jnp.asarray(table, jnp.int32),
                jnp.asarray(self._kids_or_default(kids)),
                jnp.asarray(done, bool),
                jnp.asarray(remaining, jnp.int32),
                jnp.asarray(-1 if eos is None else int(eos), jnp.int32),
                jnp.asarray(pend, jnp.int32),
                jnp.asarray(pend_n, jnp.int32))
        if packed:
            if t_tokens is None:
                # safe default: the dense-equivalent total (callers
                # that know the live mix pass the tight pow2 bucket)
                t_tokens = pow2_at_least(S * max(w, 1))
            t = max(int(t_tokens), 1)
            if t < S:
                # every live slot owns at least one stream share; a
                # bucket below S could silently drop rows' tokens
                raise ValueError(
                    f"t_tokens {t} < max_batch {S}: the packed bucket "
                    "must cover at least one token per slot")
            key = (k, t)
            fn = self._packeds.get(key)
            if fn is None:
                fn = jax.jit(
                    functools.partial(self._packed_multi_step, k=k, t=t),
                    donate_argnums=(1, 2))
                self._packeds[key] = fn
            call = args + (jnp.asarray(w, jnp.int32),)
            if self.lora is not None:
                call += (jnp.asarray(self._aids_or_default(aids)),)
            out = fn(self._w(), self.k_pages, self.v_pages, *call)
        else:
            key = (k, w)
            fn = self._raggeds.get(key)
            if fn is None:
                fn = jax.jit(
                    functools.partial(self._ragged_multi_step, k=k, w=w),
                    donate_argnums=(1, 2))
                self._raggeds[key] = fn
            call = args
            if self.lora is not None:
                call += (jnp.asarray(self._aids_or_default(aids)),)
            out = fn(self._w(), self.k_pages, self.v_pages, *call)
        self.k_pages, self.v_pages = out[9], out[10]
        return RaggedMultiOut(*out[:9])
