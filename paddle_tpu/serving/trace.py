"""Flight recorder: a bounded, off-by-default structured event log for
the serving engines and the fused training loop.

The aggregate `ServeStats` counters say WHAT happened (tokens, syncs,
hit rates); the flight recorder says WHY a given horizon was composed
the way it was and what one request experienced:

- **request lifecycle spans** — submit → admit (with prefix-cache
  mount detail) → first token → per-N-token progress → retire, keyed
  by request id;
- **per-tick scheduler decision records** — one event per dispatched
  horizon with its row composition (k, w, decode/prefill rows), the
  roofline-PREDICTED cost (`cost_model.ragged_tick_roofline_s` per
  tick plus one host sync) and the MEASURED wall time, with pool
  events (CoW copies, evictions) folded in;
- **drift accounting** — a rolling predicted-vs-measured ratio per
  dispatch shape (`drift_report()`), the data behind the Graph
  Doctor's `ROOFLINE-DRIFT` rule and `debug.serving_report()`: a
  shape whose measured tick departs from the priced
  max(compute, HBM, wire) by more than a configurable factor is a
  mispriced schedule, surfaced instead of silently absorbed.  The
  tiered-KV path rides the same machinery: "spill" events mark pages
  demoted to the host tier, and restores record ("h2d_restore",)
  ticks whose predicted (`cost_model.kv_restore_s`) vs measured H2D
  feeds this ledger (docs/observability.md).

Non-perturbation is a hard contract: the recorder only ever touches
host-side values the engine already fetched (never a device array),
so streams are byte-identical with tracing on (fuzz-pinned), and with
tracing off every hook is a dead `if engine.trace is not None` branch
— zero allocations per tick (test-pinned via `FlightRecorder.
total_events`). Memory is O(1): events and per-shape drift samples
live in bounded deques.

Timestamps are raw `time.perf_counter()` seconds — the same clock the
`profiler` module stamps `RecordEvent` regions with — so
`export_chrome_trace(path, recorders=..., profiler=...)` merges
request spans, tick records and profiler regions onto ONE
Perfetto-viewable timeline with no re-basing. Token VALUES are never
recorded (counts and ids only): traces are shareable without leaking
prompt content.
"""
import collections
import json
import os
import time

__all__ = ["FlightRecorder", "export_chrome_trace",
           "validate_chrome_trace"]

# bounded windows: a long-lived engine's trace stays O(1) memory
_EVENT_WINDOW = 4096
_DRIFT_WINDOW = 256

# drift verdict default: measured/predicted beyond this factor (either
# direction) marks a dispatch shape as mispriced
DRIFT_FACTOR = 3.0


class FlightRecorder:
    """One engine's (or trainer's) structured event log. Construct and
    pass as `ContinuousBatchingEngine(..., trace=recorder)` (or
    `trace=True` for a default one) / `Trainer.attach_recorder`.

    `events` is a bounded deque of dicts, each carrying `kind`, `ts`
    (perf_counter seconds) and kind-specific fields; `tick` events
    additionally feed the per-shape drift windows. `total_events` is a
    CLASS-level counter of every record() across the process — the
    tracing-off tests pin that a run without a recorder leaves it
    untouched (the hooks must be dead branches, not cheap branches)."""

    total_events = 0          # class-wide: the dead-branch test's probe

    def __init__(self, capacity=_EVENT_WINDOW, drift_window=_DRIFT_WINDOW,
                 drift_factor=DRIFT_FACTOR, progress_every=16):
        self.events = collections.deque(maxlen=int(capacity))
        self.drift_window = int(drift_window)
        self.drift_factor = float(drift_factor)
        self.progress_every = max(1, int(progress_every))
        self.meta = {}                   # engine-stamped context (quant
        # config, k_max, page size): exported once as trace metadata
        self._drift = {}                 # shape tuple -> deque[(pred, meas)]

    # ------------------------------------------------------------ record

    def record(self, kind, ts=None, **fields):
        """Append one structured event; returns the (mutable) event
        dict so two-phase callers (tick_dispatch/tick_complete) can
        fill measured fields in place without a second allocation."""
        ev = {"kind": kind,
              "ts": time.perf_counter() if ts is None else float(ts)}
        ev.update(fields)
        self.events.append(ev)
        FlightRecorder.total_events += 1
        return ev

    # ------------------------------------------------- scheduler ticks

    def tick_dispatch(self, track, shape, predicted_s=None, ts=None,
                      **fields):
        """Open one scheduler decision record at dispatch time.
        `track` names the timeline ("serve"/"train"), `shape` the
        dispatch shape the drift accounting keys on (e.g.
        ("ragged", k, w)), `predicted_s` the roofline-priced horizon
        cost. Complete it with `tick_complete` once the measured wall
        time is known (the engines call complete at block-processing
        time, where the fetch-overlap window closes)."""
        return self.record("tick", ts=ts, track=str(track),
                           shape=list(shape), predicted_s=predicted_s,
                           measured_s=None, **fields)

    def tick_complete(self, ev, measured_s, drift=True, **fields):
        """Close a dispatched tick record with its measured wall
        seconds (and any late fields, e.g. pool-event deltas); feeds
        the per-shape drift window when the dispatch was priced.
        `drift=False` keeps the record but skips the ledger — for
        windows the caller knows are polluted (a prefill landed inside
        the measured span), mirroring the engines' token-percentile
        exclusions.  A `predicted_serial_s` field on the record (the
        SERIAL sum of the priced legs, vs `predicted_s`'s overlapped
        max) rides into the window: `drift_report` uses the band to
        tell a mispriced leg from a serialized schedule."""
        ev["measured_s"] = float(measured_s)
        ev.update(fields)
        pred = ev.get("predicted_s")
        if drift and pred and pred > 0:
            key = tuple(ev["shape"])
            win = self._drift.get(key)
            if win is None:
                win = self._drift[key] = collections.deque(
                    maxlen=self.drift_window)
            serial = ev.get("predicted_serial_s")
            win.append((float(pred), float(measured_s),
                        float(serial) if serial else None))
        return ev

    def tick(self, track, shape, measured_s, predicted_s=None, ts=None,
             drift=True, **fields):
        """One-shot dispatch+complete (the Trainer hook's form);
        `drift=False` records the tick but keeps its window out of the
        ledger (see tick_complete)."""
        return self.tick_complete(
            self.tick_dispatch(track, shape, predicted_s=predicted_s,
                               ts=ts, **fields), measured_s, drift=drift)

    # ------------------------------------------------------------- drift

    def drift_report(self, factor=None):
        """Rolling predicted-vs-measured accounting per dispatch
        shape: [{shape, n, predicted_s, measured_s, ratio, drifting
        [, predicted_serial_s, serial_ratio, verdict]}].
        `ratio` is mean(measured)/mean(predicted) over the shape's
        window; `drifting` marks shapes whose ratio departs from 1 by
        more than `factor` (default: the recorder's drift_factor) in
        either direction — the `ROOFLINE-DRIFT` analyzer consumes
        exactly this list via context extra["roofline_drift"].

        When the ticks also carried `predicted_serial_s` (the serial
        sum of the priced legs — engines and the Trainer stamp it next
        to the overlapped `predicted_s`), an over-drifting shape gets a
        VERDICT: "serialized" when the measured mean still sits within
        `factor` of the serial prediction (the legs are priced right —
        the schedule just never overlapped them; the fix is
        COLL-SERIALIZED's, not a re-fit), else "mispriced" (the
        measured time escapes even the serial sum — some pricing INPUT
        is wrong). Under-drifting shapes stay "overpriced"."""
        factor = self.drift_factor if factor is None else float(factor)
        out = []
        for key in sorted(self._drift, key=str):
            win = self._drift[key]
            if not win:
                continue
            pred = sum(s[0] for s in win) / len(win)
            meas = sum(s[1] for s in win) / len(win)
            ratio = meas / pred if pred > 0 else float("inf")
            drifting = bool(ratio > factor or ratio < 1.0 / factor)
            entry = {"shape": list(key), "n": len(win),
                     "predicted_s": pred, "measured_s": meas,
                     "ratio": ratio, "drifting": drifting}
            serials = [s[2] for s in win
                       if len(s) > 2 and s[2] is not None]
            if serials:
                serial = sum(serials) / len(serials)
                entry["predicted_serial_s"] = serial
                entry["serial_ratio"] = (meas / serial if serial > 0
                                         else float("inf"))
            if drifting:
                if ratio < 1.0:
                    entry["verdict"] = "overpriced"
                elif entry.get("serial_ratio") is not None and \
                        entry["serial_ratio"] <= factor:
                    entry["verdict"] = "serialized"
                else:
                    entry["verdict"] = "mispriced"
            out.append(entry)
        return out

    def summary(self):
        kinds = collections.Counter(ev["kind"] for ev in self.events)
        return {"events": len(self.events), "kinds": dict(kinds),
                "drift": self.drift_report(), **(
                    {"meta": dict(self.meta)} if self.meta else {})}

    # ----------------------------------------------------- chrome trace

    # request-lifecycle milestones -> the span segment each one CLOSES
    _SEGMENTS = (("submit", "admit", "queued"),
                 ("admit", "first_token", "prefill"),
                 ("first_token", "retire", "decode"))

    def chrome_events(self, pid=1, label="serving"):
        """Render this recorder's log as chrome-trace events: request
        spans as per-request "X" slices (tid = request id, one Perfetto
        row per request), progress/preempt/resume marks as instants,
        tick records as "X" slices on a per-track scheduler row with
        predicted vs measured in args. Timestamps are perf_counter
        microseconds — the same base
        `profiler.Profiler.timeline_events()` uses, so the merged
        export needs no re-alignment.

        TENANT grouping (serving.tenancy): requests whose submit
        record carries a `tenant` field render under one pid PER
        TENANT (pids after the tick row, sorted by tenant name), so a
        multi-tenant trace reads as one Perfetto process per tenant;
        untenanted requests keep the base `pid`."""
        out = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"{label} requests",
                         **({"meta": dict(self.meta)} if self.meta
                            else {})}}]
        spans = {}                       # rid -> {milestone: ts}
        ticks = []
        rid_tenant = {}                  # rid -> tenant (span grouping)
        for ev in self.events:
            kind = ev["kind"]
            if kind == "tick":
                ticks.append(ev)
            elif "rid" in ev:
                spans.setdefault(ev["rid"], []).append(ev)
                if "tenant" in ev:
                    rid_tenant.setdefault(ev["rid"], str(ev["tenant"]))
        # tenant pids live PAST the tick row (pid + 1), so adding a
        # tenant never renumbers the tick track
        tenant_pid = {t: pid + 2 + i for i, t in
                      enumerate(sorted(set(rid_tenant.values())))}
        for t, tp in sorted(tenant_pid.items()):
            out.append({"name": "process_name", "ph": "M", "pid": tp,
                        "tid": 0,
                        "args": {"name": f"{label} requests — "
                                 f"tenant={t}"}})
        for rid, evs in sorted(spans.items()):
            rpid = tenant_pid.get(rid_tenant.get(rid), pid)
            marks = {}
            for ev in evs:
                marks.setdefault(ev["kind"], ev)
                if ev["kind"] in ("progress", "preempt", "resume"):
                    args = {k: v for k, v in ev.items()
                            if k not in ("kind", "ts", "rid")}
                    out.append({"name": f"req{rid}:{ev['kind']}",
                                "ph": "i", "s": "t",
                                "ts": ev["ts"] * 1e6, "pid": rpid,
                                "tid": int(rid), "args": args})
            for start, end, seg in self._SEGMENTS:
                if start in marks and end in marks:
                    t0, t1 = marks[start]["ts"], marks[end]["ts"]
                    args = {k: v for k, v in marks[start].items()
                            if k not in ("kind", "ts")}
                    # dur from the CONVERTED endpoints, so consecutive
                    # segments abut exactly in µs (t0*1e6 + (t1-t0)*1e6
                    # can exceed t1*1e6 by ulps and read as overlap)
                    out.append({"name": f"req{rid}:{seg}", "ph": "X",
                                "ts": t0 * 1e6,
                                "dur": max(t1 * 1e6 - t0 * 1e6, 0.0),
                                "pid": rpid, "tid": int(rid),
                                "args": args})
        # MULTIPLE lanes per track: the engines close a tick's
        # measured window AFTER the next horizon is dispatched
        # (fetch-overlap), so consecutive slices genuinely overlap in
        # time — chrome "X" slices on one tid must nest or abut, never
        # partially overlap. Pipelined horizons alone need two lanes,
        # but ONE-SHOT ticks landing between them (h2d_restore, a
        # Trainer tick) can desync any fixed alternation — so lanes
        # are assigned GREEDILY: each slice takes the first lane whose
        # previous slice has ended, growing the lane set only when
        # every lane is still busy (interval-graph coloring; in
        # practice 2, occasionally 3). Lane tids are allocated per
        # track as they appear — sorted tick processing keeps the
        # assignment deterministic.
        tracks = {}                      # track -> [lane_end_ts, ...]
        track_base = {}                  # track -> first tid
        tick_pid = pid + 1
        next_tid = 0
        # ts order, NOT recording order: a one-shot tick (h2d_restore)
        # records mid-round, after the horizon record whose ts is the
        # round START — greedy lane packing needs sorted starts
        for ev in sorted(ticks, key=lambda e: e["ts"]):
            track = ev.get("track", "serve")
            if track not in tracks:
                tracks[track] = []
                # reserve a generous tid block per track so a track
                # growing a third lane never collides with the next
                track_base[track] = next_tid
                next_tid += 16
            lanes = tracks[track]
            ts = ev["ts"] * 1e6
            dur = max(ev.get("measured_s") or 0.0, 0.0) * 1e6
            lane = None
            for li, lane_end in enumerate(lanes):
                # same sub-µs tolerance as the validator's abut rule
                if ts >= lane_end - 0.5:
                    lane = li
                    break
            if lane is None:
                lane = len(lanes)
                lanes.append(0.0)
                out.append({"name": "thread_name", "ph": "M",
                            "pid": tick_pid,
                            "tid": track_base[track] + lane,
                            "args": {"name": f"{label} {track} "
                                     f"ticks/{lane}"}})
            lanes[lane] = max(lanes[lane], ts + dur)
            shape = ev.get("shape") or []
            # per-tick args carry the tick fields only: the constant
            # recorder meta rides the process_name metadata event once,
            # not 4096 times
            args = {k: v for k, v in ev.items() if k not in ("kind", "ts")}
            out.append({"name": "tick " + "x".join(str(s) for s in shape),
                        "ph": "X", "ts": ts, "dur": dur,
                        "pid": tick_pid,
                        "tid": track_base[track] + lane,
                        "args": args})
        return out


def export_chrome_trace(path, recorders=(), profiler=None):
    """Write ONE chrome-trace JSON merging every given recorder's
    request spans + tick records with the active `profiler.Profiler`'s
    host timeline (`RecordEvent` regions and step marks) — all on the
    shared perf_counter time base, sorted so each (pid, tid) track is
    ts-monotonic (the schema `validate_chrome_trace` checks). Load in
    Perfetto / chrome://tracing, or back via
    `profiler.load_profiler_result`.

    `recorders` may be one FlightRecorder, a sequence of them, or a
    LABELED collection — a {label: recorder} dict or (label,
    recorder) pairs. Labels flow into every process_name/thread_name
    the recorder emits, so an N-replica fleet
    (`serving.fleet.FleetRouter.export_trace` passes
    {"replica0": rec0, ...}) lands on ONE Perfetto timeline with
    distinct pids per (replica, tenant): each recorder claims a
    contiguous pid block (requests row, tick track, then one pid per
    tenant), and the next replica's block starts past the largest pid
    the previous one actually emitted."""
    events = []
    if isinstance(recorders, FlightRecorder):
        recorders = (recorders,)
    if hasattr(recorders, "items"):
        recorders = list(recorders.items())
    next_pid = 1
    for item in recorders:
        if isinstance(item, (tuple, list)) and len(item) == 2 and \
                not isinstance(item, FlightRecorder):
            label, rec = item
            evs = rec.chrome_events(pid=next_pid, label=str(label))
        else:
            evs = item.chrome_events(pid=next_pid)
        events.extend(evs)
        # a recorder's pid footprint is variable now (tenant grouping
        # adds one pid per tenant past the tick row) — the next
        # recorder starts after the largest pid actually emitted
        next_pid = 1 + max((int(e.get("pid", next_pid)) for e in evs),
                           default=next_pid)
    if profiler is not None:
        events.extend(profiler.timeline_events())
    meta = [e for e in events if e.get("ph") == "M"]
    rest = sorted((e for e in events if e.get("ph") != "M"),
                  key=lambda e: (e["pid"], e["tid"], e["ts"]))
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": meta + rest,
                   "displayTimeUnit": "ms"}, f)
    return path


def validate_chrome_trace(data):
    """Schema check of an exported trace: returns a list of problem
    strings (empty = well-formed). Checks the chrome-trace contract
    the exporters promise: a `traceEvents` list, required keys per
    event (`name`/`ph`/`pid`/`tid`, numeric `ts` on non-metadata
    events, non-negative `dur` on "X" slices), ts-monotonicity per
    (pid, tid) track, and no PARTIALLY overlapping "X" slices on one
    track ("X" slices must nest or abut — Perfetto infers depth from
    containment and renders partial overlap at wrong depths or drops
    it) — the properties that make Perfetto render slices instead of
    silently mangling them. PREEMPTION instants (tenancy:
    `req<id>:preempt` / `req<id>:resume` "i" events) must fall inside
    their request row's overall span — a preempt stamped outside the
    slices it supposedly interrupted is mis-attributed lifecycle
    bookkeeping. The tier-1 gate runs
    this over a real mixed-ragged export; `data` may be the parsed
    dict or a path."""
    if isinstance(data, (str, os.PathLike)):
        with open(data) as f:
            data = json.load(f)
    problems = []
    if not isinstance(data, dict) or "traceEvents" not in data:
        return ["top-level object must carry a 'traceEvents' list"]
    events = data["traceEvents"]
    if not isinstance(events, list):
        return ["'traceEvents' is not a list"]
    # pre-pass: each track's overall "X" span — preemption instants
    # are checked against it below (they can sort before the slice
    # that covers them, so a single pass can't judge containment)
    span_lo, span_hi = {}, {}
    for ev in events:
        if isinstance(ev, dict) and ev.get("ph") == "X" and \
                isinstance(ev.get("ts"), (int, float)) and \
                isinstance(ev.get("dur"), (int, float)):
            track = (ev.get("pid"), ev.get("tid"))
            span_lo[track] = min(span_lo.get(track, ev["ts"]), ev["ts"])
            span_hi[track] = max(span_hi.get(track,
                                             ev["ts"] + ev["dur"]),
                                 ev["ts"] + ev["dur"])
    last_ts = {}
    open_slices = {}                     # track -> stack of (end, name)
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        for key in ("name", "ph", "pid", "tid"):
            if key not in ev:
                problems.append(f"event {i}: missing required key "
                                f"'{key}'")
        ph = ev.get("ph")
        if ph == "M":
            continue                     # metadata: no timing contract
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i} ({ev.get('name')}): 'ts' must "
                            "be a non-negative number")
            continue
        track = (ev.get("pid"), ev.get("tid"))
        if ph == "i":
            name = str(ev.get("name", ""))
            if name.endswith(":preempt") or name.endswith(":resume"):
                lo, hi = span_lo.get(track), span_hi.get(track)
                # sub-µs tolerance, like the overlap rule below
                if lo is None or ts < lo - 0.5 or ts > hi + 0.5:
                    problems.append(
                        f"event {i} ({name}): preemption instant at "
                        f"ts={ts} lies outside its request row's span "
                        f"[{lo}, {hi}] on track pid={track[0]} "
                        f"tid={track[1]} — preempt/resume must happen "
                        "inside the request's lifecycle")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i} ({ev.get('name')}): 'X' "
                                "event needs a non-negative 'dur'")
            else:
                # same-track "X" slices must nest or abut: a slice
                # starting inside an open one must also END inside it.
                # Sub-µs tolerance: abutting host timestamps can land
                # ulps apart after the seconds→µs conversion, and a
                # <1µs overlap is below the trace's own resolution —
                # the real defect class (pipelined ticks) overlaps by
                # milliseconds
                stack = open_slices.setdefault(track, [])
                while stack and ts >= stack[-1][0] - 0.5:
                    stack.pop()
                if stack and ts + dur > stack[-1][0] + 0.5:
                    problems.append(
                        f"event {i} ({ev.get('name')}): partially "
                        f"overlaps '{stack[-1][1]}' on track "
                        f"pid={track[0]} tid={track[1]} — 'X' slices "
                        "must nest or abut")
                stack.append((ts + dur, ev.get("name")))
        if track in last_ts and ts < last_ts[track]:
            problems.append(f"event {i} ({ev.get('name')}): ts not "
                            f"monotonic on track pid={track[0]} "
                            f"tid={track[1]}")
        last_ts[track] = ts
    return problems
