"""Continuous-batching engines over the paged decoder: slot scheduling,
horizon-fused decode, ragged chunked-prefill admission, prefix-cache
admission, speculative decoding."""
import collections
import time
import weakref

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from .decoder import PagedGPTDecoder, _spec_accept
from .stats import _ENGINES, ServeStats

__all__ = ["ContinuousBatchingEngine", "SpeculativeEngine"]

# bounded schedule-event window: the SERVE-PREFILL-STALL audit reads
# the most recent scheduling decisions, not the process lifetime
_SCHED_WINDOW = 4096


class ContinuousBatchingEngine:
    """Slot-based continuous batching: requests are admitted into free
    slots as soon as capacity allows (iteration-level scheduling), decode
    runs one compiled step for ALL active slots, finished sequences free
    their pages.

    By default `run()` schedules RAGGED horizons (Ragged Paged
    Attention, arxiv 2604.15464): blocks of k device-resident ticks
    (`PagedGPTDecoder.ragged_multi`) in which decode rows emit a token
    per tick while newly admitted prompts stream their uncached
    suffixes in as token-budgeted CHUNKS — admission mounts
    prefix-cache pages and allocates the table row host-side, then
    hands the suffix to the device carry; there is NO host-blocking
    prefill dispatch on the decode critical path, so one long prompt
    costs running slots at most a few slightly-longer ticks instead of
    a monolithic prefill stall (`serving.RaggedScheduler` owns the
    chunk/horizon policy; the SERVE-PREFILL-STALL rule audits the
    scheduling trace). The host syncs only at block boundaries for
    admission/retirement/output append, and each block's fetch is
    overlapped against the NEXT block's dispatch (one-horizon-delayed
    retirement: a slot finishing inside block N stays frozen on device
    through block N+1 — its writes route to the scratch page — and its
    pages are freed exactly once, when block N is processed).
    `ragged=False` keeps the dispatch-separate baseline (`_run_multi`:
    blocking chunked prefill at admission + decode-only
    `decode_multi` horizons — byte-identical streams, used as the
    stall bench's before). `k_max` defaults to
    `cost_model.decode_horizon`'s priced answer; `k_max=1` selects the
    legacy per-tick loop (`step()` is the per-tick API either way).

    With `prefix_cache` (a `PrefixCache`) admission becomes
    content-addressed: each prompt's full token blocks are hashed
    against the cache, fully-cached prefix spans are MOUNTED into the
    request's page-table row host-side (zero device work — the pages
    already hold exactly the KV bytes this prompt's prefill would
    write), and only the uncached suffix runs through the chunked
    prefill (`PagedGPTDecoder.prefill_suffix_batch`). Mounted pages are
    refcounted and immutable: a request about to write into a shared
    page (the first divergent token — only possible when the WHOLE
    prompt was cached and its last position must be re-consumed for
    logits) gets a copy-on-write private copy first. Retirement decrefs
    shared pages instead of freeing them; refcount-0 pages park in the
    cache's LRU and are evicted back to the free list only under pool
    pressure — every page freed exactly once, auditable via
    `page_ledger()`/`audit_pages()` (MEM-PAGE-REFCOUNT)."""

    def __init__(self, decoder: PagedGPTDecoder, eos_token_id=None,
                 max_new_tokens=64, k_max=None, host_sync_s=None,
                 prefix_cache=None, ragged=None, chunk_tokens=None,
                 scheduler=None, trace=None, packed=None,
                 host_tier=None, tier_policy="auto"):
        if max_new_tokens < 1:
            raise ValueError(
                "max_new_tokens must be >= 1 (the prefill forward always "
                f"produces one token), got {max_new_tokens}")
        self.d = decoder
        self.eos = eos_token_id
        self.max_new = max_new_tokens
        # page 0..num_pages-2 allocatable; last page reserved as scratch
        self._free = list(range(decoder.num_pages - 2, -1, -1))
        S = decoder.max_batch
        self._slot_req = [None] * S          # request id per slot
        self._slot_pages = [[] for _ in range(S)]
        # pages a slot holds as SHARED (cache-refcounted, never written)
        self._slot_shared = [set() for _ in range(S)]
        # int32 end to end: decode() feeds these to the kernel as int32,
        # so int64 here would insert a convert_element_type every tick
        self._lens = np.zeros(S, np.int32)
        self._tokens = np.zeros(S, np.int32)
        self._kids = np.zeros(S, np.int32)   # request id per slot: the
        # sampling key id, so a request's draws are independent of
        # which slot/batch/schedule served it
        self._aids = np.zeros(S, np.int32)   # LoRA adapter id per slot
        # (multi-LoRA: only consulted when the decoder carries a bank)
        self._rid_adapter = {}               # rid -> adapter id (!= 0)
        # per-slot admission generation: a block dispatched for an
        # earlier occupancy of the slot must never book-keep against a
        # later one — the rid check alone can't tell them apart once
        # preemption (serving.tenancy) lets the SAME rid re-occupy a
        # slot whose stale block is still in flight
        self._slot_gen = [0] * S
        # rid -> output length at (re-)admission: the "first token of
        # this admission" mark. 0 for fresh requests (so the base
        # engine's behavior is unchanged); a preempted request resumes
        # with its generated prefix already in _outputs, and its first
        # post-resume token must NOT restamp TTFT or republish from
        # scratch
        self._emit_base = {}
        self._table_cache = None             # rebuilt on admit/retire only
        self._queue = []                     # (req_id, ids)
        self._outputs = {}                   # req_id -> [generated ids]
        self._next_id = 0
        self.steps = 0
        self.k_max = max(1, int(k_max)) if k_max is not None else None
        if prefix_cache is True:
            from .prefix_cache import PrefixCache
            prefix_cache = PrefixCache(decoder.page_size,
                                       salt=decoder.cache_fingerprint())
        if prefix_cache is not None and \
                prefix_cache.page_size != decoder.page_size:
            raise ValueError(
                f"prefix cache page_size {prefix_cache.page_size} != "
                f"decoder page_size {decoder.page_size}")
        self.cache = prefix_cache
        # TIERED KV (serving.kv_tier): a host-RAM spill tier behind the
        # prefix cache — refcount-0 pages evicted under pool pressure
        # demote their bytes to a capacity-bounded host LRU instead of
        # vanishing, and admissions whose chain continues onto host
        # entries restore them via H2D when the wire beats the prefill
        # recompute (tier_policy: "auto" = cost_model-priced per
        # admission; "restore"/"recompute" pin the decision — the CPU
        # bench pins "restore" since tiny-model recompute always wins
        # the pricing there). host_tier=True builds a default
        # HostKVTier; a PrefixCache constructed with tier= works too.
        # identity checks, not truthiness: an EMPTY HostKVTier is falsy
        # (__len__ == 0) but very much "tier on"
        if host_tier is not None and host_tier is not False:
            if self.cache is None:
                raise ValueError(
                    "host_tier needs a prefix_cache: the tier is keyed "
                    "by the cache's chain keys (pass prefix_cache=True "
                    "for a default cache)")
            if self.cache.tier is None:
                from .kv_tier import HostKVTier
                self.cache.tier = HostKVTier() if host_tier is True \
                    else host_tier
            elif host_tier is not True and \
                    host_tier is not self.cache.tier:
                # a loaded cache may arrive with a WARM tier — silently
                # replacing it would drop the persisted host entries
                raise ValueError(
                    "prefix_cache already carries a host tier — pass "
                    "host_tier=True to keep it, or attach your tier "
                    "to the cache (PrefixCache.load(tier=...)) "
                    "instead")
        self.tier = self.cache.tier if self.cache is not None else None
        if tier_policy not in ("auto", "restore", "recompute"):
            raise ValueError(f"tier_policy must be auto/restore/"
                             f"recompute, got {tier_policy!r}")
        self.tier_policy = tier_policy
        self._restore_s_pending = 0.0    # priced H2D awaiting a horizon
        if self.cache is not None:
            # a PRELOADED cache (PrefixCache.load) already owns pages:
            # they are parked cache property, not free pool — and bind
            # the decoder so cache.save() can read the pool later
            if self.cache.n_pages:
                # a populated cache's pages live in the pool of the
                # decoder it is bound to (PrefixCache.load and every
                # engine bind one) — with any OTHER decoder (even
                # same-weights: its pool does not hold these pages)
                # the chain keys would still hit and mount garbage KV
                # with no error anywhere
                bound = self.cache._decoder and self.cache._decoder()
                if bound is not decoder:
                    raise ValueError(
                        "prefix_cache holds pages for a different "
                        "decoder — pass the decoder the cache was "
                        "loaded onto, or PrefixCache.load the save "
                        "dir onto THIS decoder")
                owned = set(self.cache.pages())
                self._free = [p for p in self._free if p not in owned]
            self.cache._decoder = weakref.ref(decoder)
        decoder._engines.add(self)
        self._cache_meta = {}                # rid -> (start, keys, n_hit)
        # RAGGED scheduling (default on the multi-step path): prompt
        # suffixes stream into the SAME K-tick horizon as running
        # decode slots, w tokens per tick, with NO host-blocking
        # prefill dispatch on the decode critical path. ragged=False
        # keeps the dispatch-separate baseline (_run_multi: blocking
        # chunked prefill at admission + decode-only horizons).
        if scheduler is None and ragged is not False and \
                (self.k_max is None or self.k_max > 1 or ragged):
            from .scheduler import RaggedScheduler
            # k_max=None lets the SCHEDULER price K with the
            # chunk-aware mixed-tick roofline (decode_horizon's
            # chunk_tokens extension) — a compute-heavy chunk budget
            # correctly prices a smaller K than pure decode would
            scheduler = RaggedScheduler(decoder,
                                        chunk_tokens=chunk_tokens,
                                        k_max=self.k_max,
                                        host_sync_s=host_sync_s)
        self.scheduler = scheduler
        if self.k_max is None:
            if scheduler is not None:
                self.k_max = scheduler.k_max
            else:
                # explicitly non-ragged baseline: price K on the PURE
                # decode tick (no chunk compute leg, no scheduler)
                from ..cost_model import decode_horizon
                self.k_max = decode_horizon(decoder.step_hbm_bytes(),
                                            host_sync_s=host_sync_s)
        self.ragged = bool(self.k_max > 1 if ragged is None else ragged)
        # PACKED token-stream dispatch for the ragged horizons (default:
        # the decoder's layout flag): every tick pays its total token
        # count, bucketed pow2 (`HorizonPlan.t_tokens`) — not the dense
        # [S, w] window grid. packed=False selects the dense A/B twin
        # on THIS engine regardless of the decoder default (the
        # pad-fraction bench runs both off one decoder).
        self.packed = bool(decoder.packed if packed is None else packed)
        self._prompt_len = [0] * S           # admitted prompt length/slot
        # scheduling-decision trace for the SERVE-PREFILL-STALL audit
        self._sched_events = collections.deque(maxlen=_SCHED_WINDOW)
        self.stats = ServeStats(
            engine=type(self).__name__, k_max=self.k_max,
            # num_pages - 1: the reserved scratch page never holds a
            # sequence's KV — capacity counts allocatable pages only
            kv_pool_bytes=(decoder.num_pages - 1) * decoder.kv_page_bytes,
            kv_bytes_per_token=decoder.kv_page_bytes // decoder.page_size)
        if self.tier is not None:
            # a warm-started tier (PrefixCache.load) already holds
            # resident bytes — the gauge must not read 0 until the
            # first spill/restore happens to refresh it
            self.stats.host_tier_bytes = self.tier.bytes_used
        self._submit_t = {}                  # rid -> submit wall time
        # FLIGHT RECORDER (serving.trace.FlightRecorder): off by
        # default; every hook below is a dead `if self.trace is not
        # None` branch, so the untraced engine does zero trace work
        # per tick (test-pinned). trace=True builds a default recorder.
        if trace is True:
            from .trace import FlightRecorder
            trace = FlightRecorder()
        self.trace = trace or None
        self._trace_price = None         # (hbm, flops/token, sync_s)
        self._trace_pool_mark = (0, 0)   # (cow, evictions) marks
        self._trace_warm = set()         # dispatch shapes already compiled
        if self.trace is not None:
            self.trace.meta.update(
                engine=type(self).__name__, k_max=self.k_max,
                ragged=self.ragged, packed=self.packed,
                page_size=decoder.page_size,
                kv_quant=decoder.kv_quant or "none")
        _ENGINES.add(self)

    # ------------------------------------------------- flight recorder

    def _price_horizon(self, k, w, prefill_rows, decode_rows=0,
                       serial=False):
        """Roofline-PREDICTED wall cost of one dispatched horizon: k
        mixed ticks (`cost_model.ragged_tick_legs` priced on the
        tick's TOTAL new-token count — the decode HBM leg plus the
        compute leg of every new token, chunk rows at w each plus one
        per decode row; the packed layout's dispatch unit) plus ONE
        host sync. The tick records pair this with the measured wall
        time; the drift accounting (`FlightRecorder.drift_report` /
        ROOFLINE-DRIFT) is the predicted-vs-measured ledger.
        `serial=True` prices the SERIAL sum of the legs instead of
        their overlapped max — the ticks stamp both, so the ledger's
        verdict can tell a mispriced leg (measured outside even the
        sum) from a serialized schedule (measured at the sum).
        Called only with tracing on."""
        from ..cost_model import measured_host_sync_s, ragged_tick_legs
        if self._trace_price is None:
            sched = self.scheduler
            fpt = (sched.flops_per_token if sched is not None
                   else 2.0 * self.d.cfg.num_params())
            self._trace_price = (self.d.step_hbm_bytes(), fpt,
                                 measured_host_sync_s())
        hbm, fpt, sync = self._trace_price
        hbm_s, compute_s = ragged_tick_legs(
            hbm, w * prefill_rows + decode_rows, fpt)
        tick = (hbm_s + compute_s) if serial else max(hbm_s, compute_s)
        return k * tick + sync

    def _trace_pool_delta(self):
        """Pool events since the previous tick record (CoW copies,
        evictions), folded into each tick so the trace shows WHICH
        horizon paid for cache churn. Called only with tracing on."""
        cow, ev = self.stats.prefix_cow, self.stats.prefix_evictions
        d = {"cow": cow - self._trace_pool_mark[0],
             "evictions": ev - self._trace_pool_mark[1]}
        self._trace_pool_mark = (cow, ev)
        return d

    def _trace_shape_warm(self, key):
        """First dispatch of a compiled-program shape pays its XLA
        compile inside the measured window — its tick is recorded but
        kept OUT of the drift ledger (one compile sample would inflate
        the rolling mean for hundreds of steady ticks). Called only
        with tracing on."""
        warm = key in self._trace_warm
        self._trace_warm.add(key)
        return warm

    def _trace_admits(self, admitted, now):
        """Admit events with the prefix-cache mount detail (cached
        span, hit blocks) — the span segment between a request's
        submit and first_token marks. Called only with tracing on."""
        for slot, rid, ids, _pages in admitted:
            meta = self._cache_meta.get(rid)
            self.trace.record(
                "admit", ts=now, rid=rid, slot=slot,
                prompt_tokens=len(ids),
                cached_tokens=int(meta[0]) if meta else 0,
                hit_blocks=int(meta[2]) if meta else 0)

    def _trace_progress(self, rid):
        """Per-N-token progress mark (N = recorder.progress_every).
        Called only with tracing on, from the token-processing loops."""
        n = len(self._outputs[rid])
        if n % self.trace.progress_every == 0:
            self.trace.record("progress", rid=rid, tokens=n)

    # ------------------------------------------------------- tiered KV

    def _flops_per_token(self):
        """Matmul FLOPs one prompt token costs (the 2x-params GPT rule;
        the scheduler already holds it on ragged engines)."""
        if self.scheduler is not None and \
                hasattr(self.scheduler, "flops_per_token"):
            return self.scheduler.flops_per_token
        return 2.0 * self.d.cfg.num_params()

    def _spill_wave(self, need, exclude=()):
        """Reclaim at least `need` parked pages, demoting the wave to
        the host tier with ONE stacked D2H (`PrefixCache.evict` walks
        the victims while their bytes are still mapped; the transfer
        itself is deferred until the walk ends — the freed pages are
        not handed out, let alone written, before this method returns,
        so the batched read still sees the exact write-time bytes).
        A page whose key already has a host twin (it was itself
        restored, or a recompute refreshed the entry) needs NO D2H —
        the host payload is still valid, only the device-twin backref
        clears. Returns the freed page ids."""
        tier = self.tier
        pending = []                     # (key, page): victims to D2H

        def note(key, page):
            if tier is None:
                return
            if key in tier:
                tier.note_unmounted(key)
                self.stats.host_tier_bytes = tier.bytes_used
                return
            if self.d.kv_page_bytes > tier.capacity_bytes:
                # put() would refuse a payload this size anyway — skip
                # the D2H entirely (the capacity-0 tier-off twin must
                # not pay a device sync on every pool-pressure
                # eviction for nothing)
                return
            pending.append((key, page))

        freed = self.cache.evict(need, exclude=exclude, spill=note)
        if pending:
            payloads = self.d.fetch_page_payloads(
                [p for _, p in pending])
            for (key, page), payload in zip(pending, payloads):
                if tier.put(key, payload):
                    self.stats.tier_spills += 1
                    if self.trace is not None:
                        self.trace.record("spill", page=int(page),
                                          bytes=tier.entry_bytes(key))
            self.stats.host_tier_bytes = tier.bytes_used
        return freed

    def _tier_plan(self, keys, n_dev):
        """How far the chain continues onto the HOST tier past the
        device-resident run, and whether to restore it: (n_tier,
        restore, hold). Policy "auto" prices `cost_model.kv_restore_s`
        (PCIe leg) against the span's prefill recompute
        (`kv_tier.restore_beats_recompute` — one formula for engine
        and tests); a losing wire RECOMPUTES and merely refreshes the
        host entries' recency (their bytes stay valid — write-time
        determinism), keeping the hot set warm for a bigger model or
        a longer span. On a restore decision, `hold` pins the span's
        (key, payload, bytes) triples HERE: this same admission's
        evictions may spill NEW entries into the tier and LRU-evict
        the very entries the plan selected — holding the payload
        objects makes the restore immune to that churn (and touches
        their recency, which the about-to-be-hot entries deserve
        anyway)."""
        tier = self.tier
        if tier is None:
            return 0, False, None
        n_tier = 0
        while n_dev + n_tier < len(keys) and \
                keys[n_dev + n_tier] in tier:
            n_tier += 1
        if not n_tier:
            return 0, False, None
        restore = self.tier_policy == "restore"
        if self.tier_policy == "auto":
            from .kv_tier import restore_beats_recompute
            span = keys[n_dev:n_dev + n_tier]
            restore = restore_beats_recompute(
                sum(tier.entry_bytes(k) for k in span),
                n_tier * self.d.page_size, self._flops_per_token(),
                # the cross-process tier (fleet.SharedHostKVTier) pays
                # a host-RAM read leg before the wire — price it
                shared=getattr(tier, "shared", False))
        hold = None
        if restore:
            try:
                hold = [(k, tier.get(k), tier.entry_bytes(k))
                        for k in keys[n_dev:n_dev + n_tier]]
            except KeyError:
                # shared-tier churn: a sibling replica evicted part of
                # the span between the membership walk and the hold —
                # fall back to recompute (bytes stay correct either way)
                return n_tier, False, None
        return n_tier, restore, hold

    def _tier_recompute(self, keys, lo, n):
        """Host blocks keys[lo:lo+n] will be RE-PREFILLED (the wire
        lost the pricing, or a restore span degraded under pool
        pressure): refresh the entries' recency — the recomputed bytes
        equal the spilled ones by write-time determinism, so the
        payload stays valid and the hot set must not age out — and
        count the decision. Called only once the admission COMMITS
        (counting at plan time would inflate tier_recomputes on every
        head-of-line retry)."""
        for i in range(n):
            self.tier.touch(keys[lo + i])
        self.stats.tier_recomputes += n

    def _tier_restore(self, keys, n_dev, pages, hold, rid):
        """Re-mount `len(pages)` host-resident blocks (keys[n_dev:],
        payloads pinned in `hold` at plan time — tier churn between
        plan and restore cannot invalidate them) into freshly
        allocated device pages: ONE batched H2D scatter for the whole
        span (`mount_page_payloads`, dispatched async — jax's pool
        threading orders every later horizon after the writes; a
        per-page mount paid one dispatch per block), then cache insert
        under the held parent chain and the device-twin backref for
        the ledger audit. Returns [(page, inserted)] — a
        capacity-refused insert leaves that page (and the rest of the
        chain, publish-stop rule) private to the request: bytes still
        correct, just not shareable. The priced H2D is handed to the
        horizon pricing (`note_restore`) and, with tracing on,
        recorded as an ("h2d_restore",) tick whose
        predicted-vs-measured — now the price of the batched transfer
        — feeds the drift ledger."""
        tier = self.tier
        tot_bytes = sum(nbytes for _, _, nbytes in hold[:len(pages)])
        t0 = time.perf_counter()
        self.d.mount_page_payloads(
            list(pages), [hold[i][1] for i in range(len(pages))])
        out = []
        stop = False
        for i, pid in enumerate(pages):
            key = hold[i][0]
            ok = False
            if not stop:
                parent = keys[n_dev + i - 1] if (n_dev + i) else None
                ok = self.cache.insert(key, pid, parent=parent)
                if ok:
                    tier.note_mounted(key, pid)
                else:
                    stop = True
            out.append((pid, ok))
        dt = time.perf_counter() - t0
        from ..cost_model import kv_restore_s
        pred = kv_restore_s(tot_bytes,
                            shared=getattr(tier, "shared", False))
        self.stats.tier_restores += len(pages)
        self.stats.host_tier_bytes = tier.bytes_used
        self._note_restore(pred)
        if self.trace is not None:
            self.trace.tick(
                "serve", ("h2d_restore",), dt, predicted_s=pred,
                drift=self._trace_shape_warm(("h2d_restore",)),
                rid=rid, blocks=len(pages), bytes=tot_bytes)
        return out

    def _note_restore(self, seconds):
        if self.scheduler is not None and \
                hasattr(self.scheduler, "note_restore"):
            self.scheduler.note_restore(seconds)
        else:
            self._restore_s_pending += float(seconds)

    def _take_restore_s(self):
        """Pending restore H2D price, drained once per dispatched
        horizon (the mount lands inside exactly one measured window —
        the next dispatch's — so its price belongs to that window's
        prediction)."""
        if self.scheduler is not None and \
                hasattr(self.scheduler, "take_restore_s"):
            return self.scheduler.take_restore_s()
        s, self._restore_s_pending = self._restore_s_pending, 0.0
        return s

    def submit(self, prompt_ids, adapter=None):
        """Queue one prompt; returns its request id. `adapter` selects
        a LoRA variant by id (1..n over an attached bank,
        `PagedGPTDecoder.attach_adapters`; 0/None = base weights) —
        requests of DIFFERENT adapters batch into the same ragged
        horizons, resolved per token on device."""
        ids = [int(t) for t in np.asarray(
            prompt_ids._value if isinstance(prompt_ids, Tensor)
            else prompt_ids).reshape(-1)]
        if not ids:
            raise ValueError(
                "prompt must contain at least one token (prefill "
                "samples the first generated token after the prompt's "
                "last position — an empty prompt has none)")
        aid = self._check_adapter(adapter)
        total = len(ids) + self.max_new
        need = self._pages_for(total)
        if need > min(self.d.max_pages, self.d.num_pages - 1):
            raise ValueError(
                f"request needs {need} pages (prompt {len(ids)} + "
                f"max_new {self.max_new} tokens) but the pool allows "
                f"{min(self.d.max_pages, self.d.num_pages - 1)}")
        if total > self.d.cfg.max_seq_len:
            raise ValueError(
                f"prompt {len(ids)} + max_new {self.max_new} tokens "
                f"exceeds the model's max_seq_len "
                f"{self.d.cfg.max_seq_len} (positions past it have no "
                "embedding)")
        return self._register_request(ids, adapter=aid)

    def _check_adapter(self, adapter):
        aid = int(adapter or 0)
        if aid and self.d.lora is None:
            raise ValueError(
                f"adapter {aid} requested but the decoder carries no "
                "LoRA bank — attach one with "
                "PagedGPTDecoder.attach_adapters")
        if aid < 0 or aid > self.d.n_adapters:
            raise ValueError(
                f"adapter id {aid} out of range: the attached bank "
                f"serves ids 0 (base) .. {self.d.n_adapters}")
        return aid

    def _register_request(self, ids, adapter=0, trace_fields=None):
        """Queue a VALIDATED request: rid allocation, queue-wait stamp,
        stats — one implementation for both engines' submit()s, and
        called only after validation so a rejected submission can't
        skew stats.requests or leak a _submit_t entry. `trace_fields`
        ride into the trace's submit record (the tenancy engine stamps
        tenant/slo there — the chrome exporter groups spans by it)."""
        rid = self._next_id
        self._next_id += 1
        self._submit_t[rid] = time.perf_counter()
        self.stats.requests += 1
        if adapter:
            self._rid_adapter[rid] = adapter
        self._queue.append((rid, ids))
        if self.trace is not None:
            self.trace.record("submit", ts=self._submit_t[rid], rid=rid,
                              prompt_tokens=len(ids),
                              **(trace_fields or {}))
        return rid

    def _request_max_new(self, rid):
        """Tokens this request may still emit, for admission-time page
        budgeting. A FRESH request may emit max_new; a resumed
        (previously preempted) one already banked len(outputs) of
        them, so its resume prompt (original + generated prefix) plus
        the remainder needs exactly the original page total."""
        return self.max_new - len(self._outputs.get(rid, ()))

    def _pages_for(self, n_tokens):
        return (n_tokens + self.d.page_size - 1) // self.d.page_size

    def _note_queue_wait(self, rid, dt):
        """Queue-wait stamp hook (submit -> admit); the tenancy engine
        additionally banks it per tenant."""
        self.stats.queue_wait_s.append(dt)

    def _note_ttft(self, rid, dt):
        """TTFT stamp hook (submit -> first token); the tenancy engine
        additionally banks it per tenant."""
        self.stats.ttft_s.append(dt)

    def _note_resident(self):
        """Update stats.max_resident_slots from the ONE definition of
        resident — slots currently holding a request (`_slot_req`) —
        so the peak is comparable across the per-tick, fused and
        ragged loops (and any dispatch site added later)."""
        n = sum(r is not None for r in self._slot_req)
        self.stats.max_resident_slots = max(
            self.stats.max_resident_slots, n)

    def _admit(self):
        # gather every admittable request first: same-suffix-bucket
        # prompts then prefill as ONE batched forward (iteration-level
        # batching applies to prefill too, not just decode). Pages freed
        # by EOS-at-prefill become available from the NEXT step's pass.
        # Returns the slots that entered decode (the multi-step run loop
        # merges exactly those into its device carry).
        active0 = sum(r is not None for r in self._slot_req)
        admitted = self._gather_admissions()
        if not admitted:
            return []
        now = time.perf_counter()
        for _, rid, _, _ in admitted:
            t0 = self._submit_t.get(rid)
            if t0 is not None:
                self._note_queue_wait(rid, now - t0)
        if self.trace is not None:
            self._trace_admits(admitted, now)
        self._table_cache = None
        firsts = self._prefill_admitted(admitted)
        self.stats.prefill_syncs += 1
        # the stall the ragged path exists to kill: this prefill
        # dispatch BLOCKED the host while `active0` slots sat decoding
        # (SERVE-PREFILL-STALL audits the trace)
        self._sched_events.append(
            {"kind": "prefill_sync", "decode_active": int(active0),
             "rows": len(admitted)})
        if active0:
            self.stats.prefill_stall_syncs += 1
        self._extra_prefill(admitted)
        done_t = time.perf_counter()
        live = []
        for (slot, rid, ids, pages), first in zip(admitted, firsts):
            # TTFT = submit -> FIRST TOKEN (the token exists right
            # here, so the prefill-sync timestamp is exactly it; the
            # ragged path stamps the same milestone at block
            # processing, so chunked and legacy engines report
            # comparable numbers)
            t0 = self._submit_t.pop(rid, None)
            if t0 is not None:
                self._note_ttft(rid, done_t - t0)
            self._outputs[rid] = [first]
            if self.trace is not None:
                self.trace.record("first_token", ts=done_t, rid=rid)
            self.stats.tokens += 1
            if (self.eos is not None and first == self.eos) \
                    or self.max_new <= 1:
                # finished at prefill: never occupy a decode slot
                self._retire(slot)
                continue
            self._lens[slot] = len(ids)
            self._tokens[slot] = first
            self._kids[slot] = rid
            self._after_admit(slot, len(ids))
            live.append(slot)
        return live

    def _prefill_admitted(self, admitted):
        """Dispatch the admitted requests' prefills: the flash-attention
        full prefill without a prefix cache; the CHUNKED suffix path
        with one (the cached span is mounted host-side — zero device
        work — and only positions start..L-1 compute). Freshly computed
        full blocks are published to the cache afterwards."""
        if self.cache is None:
            # packed=self.packed: the engine-level layout choice covers
            # the admission prefill too — a packed=False engine is the
            # dense twin END TO END, whatever the decoder's default
            return self.d.prefill_suffix_batch(
                [(ids, 0, pages) for _, _, ids, pages in admitted],
                kids=[rid for _, rid, _, _ in admitted],
                packed=self.packed,
                aids=[self._rid_adapter.get(rid, 0)
                      for _, rid, _, _ in admitted])
        reqs = []
        for _, rid, ids, pages in admitted:
            start = self._cache_meta[rid][0]
            reqs.append((ids[start:], start, pages))
        firsts = self.d.prefill_suffix_batch(
            reqs, kids=[rid for _, rid, _, _ in admitted],
            packed=self.packed,
            aids=[self._rid_adapter.get(rid, 0)
                  for _, rid, _, _ in admitted])
        for slot, rid, ids, pages in admitted:
            self._publish_blocks(rid, slot)
        return firsts

    def _publish_blocks(self, rid, slot):
        """Publish a request's freshly computed full blocks to the
        prefix cache: content-addressable from now on (the cache takes
        one reference-managed view; the slot keeps holding the page
        until retirement decrefs it). Called once the blocks' bytes
        are KNOWN-ordered before any future reader — at prefill-sync
        time on the blocking path, at first-token block processing on
        the ragged path (every later mount dispatches after the
        horizon that wrote the pages). A same-batch duplicate whose
        insert is refused keeps its copy private — two requests never
        alias a page they both wrote — and publishing STOPS at the
        first refusal: a deeper block would chain under a parent this
        request neither mounted nor inserted, breaking the
        every-ancestor-referenced invariant the eviction cascade
        relies on (a parked parent could then cascade into a
        still-referenced child)."""
        if self.cache is None:
            return
        meta = self._cache_meta.pop(rid, None)
        if meta is None:
            return
        _start, keys, n_hit = meta
        pages = self._slot_pages[slot]
        for b in range(n_hit, len(keys)):
            parent = keys[b - 1] if b else None
            if not self.cache.insert(keys[b], pages[b], parent=parent):
                break
            self._slot_shared[slot].add(pages[b])

    def _gather_admissions(self):
        if self.cache is not None:
            return self._gather_admissions_cached()
        admitted = []
        blocked = False
        for slot in range(self.d.max_batch):
            if blocked:
                break
            if self._slot_req[slot] is not None or not self._queue:
                continue
            while True:
                rid, ids = self._queue[0]
                need = self._pages_for(len(ids) +
                                       self._request_max_new(rid))
                if need > self.d.max_pages:
                    blocked = True           # permanently oversized head
                    break
                if need > len(self._free):
                    if self._admission_blocked(rid, need):
                        blocked = True       # head-of-line: wait
                        break
                    # tenancy made room (a victim's pages freed):
                    # replan THIS slot — advancing would strand the
                    # latency head un-admitted for a whole horizon
                    # after its victim was already interrupted
                    continue
                self._queue.pop(0)
                pages = [self._free.pop() for _ in range(need)]
                self._occupy(slot, rid)
                self._slot_pages[slot] = pages
                admitted.append((slot, rid, ids, pages))
                break
        return admitted

    def _occupy(self, slot, rid):
        """Bind `rid` to `slot` (both admission paths): the request id,
        its adapter id for the dispatch-side aids row, and the slot
        generation stamp the stale-block check compares."""
        self._slot_req[slot] = rid
        self._aids[slot] = self._rid_adapter.get(rid, 0)
        self._slot_gen[slot] += 1

    def _admission_blocked(self, rid, need):
        """The queue head can't get its pages: True = wait (the base
        head-of-line discipline). The tenancy engine overrides this
        with preemption by page-spill — parking a throughput victim's
        KV in the prefix cache frees/parks enough pages that the
        admission can replan (return False)."""
        return True

    def _gather_admissions_cached(self):
        """Prefix-cache admission: hash the prompt's full blocks, mount
        the longest cached run into the page-table row (incref), evict
        parked refcount-0 pages if the free list can't cover the
        uncached remainder, and record (start, keys, n_hit) for the
        chunked prefill. A FULL-prompt hit still needs the last
        position's logits: its one re-consumed token would write into
        the final mounted page, so that page is copy-on-write'd to a
        private copy first (the recomputed KV bytes are identical — the
        chunked prefill is deterministic and position-local — so the
        copy diverges only once decode appends past the prompt).

        With a host tier, the chain may CONTINUE past the device run
        onto host-resident entries (`_tier_plan`): a priced winner
        RESTORES them into freshly allocated device pages (counted in
        need_new — a restored block costs a device page exactly like a
        computed one; the admission head-of-line check therefore
        accounts in-flight restores) and those blocks join the hit
        span; a priced loser recomputes them as ordinary misses. Pool
        eviction during either path spills through `_spill_wave` (one
        stacked D2H per wave), so pressure demotes instead of
        destroys."""
        admitted = []
        blocked = False
        ps = self.d.page_size
        tok_bytes = self.d.kv_page_bytes // ps
        for slot in range(self.d.max_batch):
            if blocked:
                break
            if self._slot_req[slot] is not None or not self._queue:
                continue
            while True:
                rid, ids = self._queue[0]
                L = len(ids)
                total = self._pages_for(L + self._request_max_new(rid))
                if total > self.d.max_pages:
                    blocked = True       # permanently oversized head
                    break
                keys = self.cache.block_keys(
                    ids, extra_salt=self.d.adapter_salt(
                        self._rid_adapter.get(rid, 0)))
                hits = self.cache.match(keys)
                n_dev = len(hits)
                n_tier, do_restore, hold = self._tier_plan(keys, n_dev)
                span = n_dev + (n_tier if do_restore else 0)
                # pick the largest mounted span the pool can cover: mounted
                # hit pages are excluded from eviction, so on a tight pool
                # a full-span mount can be self-blocking (the parked hit
                # pages ARE the reclaimable ones — e.g. a full-prompt hit
                # whose CoW page cannot be allocated). Degrading the span
                # turns the excess hits back into evictable parked pages,
                # so any request the cache-less engine could admit
                # eventually admits here too (n_hit=0 needs exactly the
                # cache-less page count). Restored blocks degrade FIRST
                # (deepest-span-off): they are the ones that COST free
                # pages.
                chosen = None
                for n_hit in range(span, -1, -1):
                    start = n_hit * ps
                    # full hit: re-consume the last token (n_hit > 0 guard:
                    # an EMPTY prompt trivially satisfies start >= L with
                    # nothing mounted — it prefills like any other miss)
                    cow = n_hit > 0 and start >= L
                    if cow:
                        start = L - 1
                    n_rest = max(0, n_hit - n_dev)
                    need_new = total - n_hit + (1 if cow else 0) + n_rest
                    if need_new <= len(self._free) + self.cache.evictable(
                            exclude=keys[:n_hit]):
                        chosen = (n_hit, start, cow, need_new, n_rest)
                        break
                if chosen is None:
                    if self._admission_blocked(rid, total):
                        blocked = True   # head-of-line: wait for pages
                        break
                    # tenancy made room (a victim's pages parked/
                    # freed): replan THIS slot — the cache contents
                    # changed, so keys re-match from scratch
                    continue
                n_hit, start, cow, need_new, n_rest = chosen
                hits = hits[:n_hit - n_rest]
                self._queue.pop(0)
                if n_tier:
                    # recompute-decided host blocks — plus any restore
                    # span DEGRADED away by the head-of-line loop — are
                    # re-prefilled: count + recency-refresh them (only now
                    # that the admission commits)
                    lo = max(n_hit, n_dev)
                    n_recomp = n_dev + n_tier - lo
                    if n_recomp:
                        self._tier_recompute(keys, lo, n_recomp)
                self.cache.mount(keys[:len(hits)])
                if len(self._free) < need_new:
                    freed = self._spill_wave(need_new - len(self._free))
                    self.stats.prefix_evictions += len(freed)
                    self._free.extend(freed)
                privates = [self._free.pop() for _ in range(need_new)]
                keys_meta = keys
                inserted = {}
                if n_rest:
                    rest_pages = [privates.pop() for _ in range(n_rest)]
                    inserted = dict(self._tier_restore(
                        keys, len(hits), rest_pages, hold, rid))
                    if not all(inserted.values()):
                        # a capacity-refused restore insert breaks the held
                        # chain: publishing deeper blocks would chain under
                        # an unheld parent (the eviction-cascade invariant)
                        # — stop publishing for this request entirely
                        keys_meta = keys[:len(hits)]
                    hits = hits + rest_pages
                shared = list(hits)
                shared_set = set(shared[:n_hit - n_rest]) | \
                    {p for p, ok in inserted.items() if ok}
                if cow:
                    last = shared[-1]
                    if last in shared_set:
                        dst = privates.pop()
                        self.d.copy_page(last, dst)
                        self.cache.release_page(last)
                        self.stats.prefix_cow += 1
                        shared_set.discard(last)
                        shared[-1] = dst
                    else:
                        # the final block is a restore whose cache insert
                        # was refused: the page is ALREADY private — no
                        # copy needed, return the spare CoW page
                        self._free.append(privates.pop())
                pages = shared + privates    # block order: prefix first
                self._occupy(slot, rid)
                self._slot_pages[slot] = pages
                self._slot_shared[slot] = shared_set
                self._cache_meta[rid] = (start, keys_meta, n_hit)
                self.stats.prefix_hits += n_hit
                self.stats.prefix_misses += len(keys) - n_hit
                self.stats.prefix_tokens_saved += start
                self.stats.prefix_bytes_saved += start * tok_bytes
                admitted.append((slot, rid, ids, pages))
                break
        return admitted

    def _extra_prefill(self, admitted):
        pass                                 # SpeculativeEngine: draft

    def _after_admit(self, slot, prompt_len):
        pass                                 # SpeculativeEngine: _dlens

    def _retire(self, slot):
        if self.trace is not None:
            rid = self._slot_req[slot]
            self.trace.record(
                "retire", rid=rid,
                tokens=len(self._outputs.get(rid, ())))
        shared = self._slot_shared[slot]
        for pid in self._slot_pages[slot]:
            if pid in shared:
                # drop this request's reference only: the cache still
                # owns the page (parked at refcount 0, reclaimed by
                # eviction alone) — so a shared page is freed exactly
                # once, by whoever finally unmaps it
                self.cache.release_page(pid)
            else:
                self._free.append(pid)
        rid = self._slot_req[slot]
        self._rid_adapter.pop(rid, None)
        self._emit_base.pop(rid, None)
        self._release_slot(slot)
        self.stats.completed += 1

    def _release_slot(self, slot):
        """Clear every per-slot field — retirement AND preemption
        (tenancy) share this one sequence, so a field added for one
        can never go stale under the other (the generation bump, the
        adapter id and the scheduler retire all ride here)."""
        self._slot_shared[slot] = set()
        self._slot_req[slot] = None
        self._slot_pages[slot] = []
        self._slot_gen[slot] += 1
        self._lens[slot] = 0
        self._tokens[slot] = 0
        self._aids[slot] = 0
        self._prompt_len[slot] = 0
        if self.scheduler is not None:
            self.scheduler.retire(slot)
        self._table_cache = None

    def page_ledger(self):
        """Auditable snapshot of page ownership: every allocatable page
        sits in exactly one of {free list, slot-held}, cache refcounts
        equal the number of slots mounting each shared page, and parked
        (refcount-0) cached pages are held by nobody. The
        MEM-PAGE-REFCOUNT lint (`analysis.memory.audit_page_ledger`)
        consumes this — double-frees, leaks and refcount drift all
        surface as findings."""
        return {
            "num_pages": self.d.num_pages,
            "scratch": self.d.num_pages - 1,
            "free": list(self._free),
            "slots": {s: list(p)
                      for s, p in enumerate(self._slot_pages) if p},
            "shared": {s: sorted(sh)
                       for s, sh in enumerate(self._slot_shared) if sh},
            "cache": self.cache.ledger() if self.cache else {},
            # multi-LoRA rows: each occupied slot's adapter id plus its
            # cache-key salt (hex) — the audit's cross-variant aliasing
            # check: a page shared by slots whose salts differ would
            # mean one variant reads another's KV bytes
            "slot_adapters": {
                s: {"adapter": int(self._aids[s]),
                    "salt": self.d.adapter_salt(
                        int(self._aids[s])).hex()}
                for s in range(self.d.max_batch)
                if self._slot_req[s] is not None
            } if self.d.lora is not None else {},
            # host-tier rows (tiered KV): spilled entries by chain key,
            # with the device-twin backref of restored entries — the
            # audit cross-checks a twin against the free list (a key
            # both host-resident-with-a-device-twin and device-free is
            # a dropped unmount)
            "host": self.tier.ledger() if self.tier is not None else {},
        }

    def audit_pages(self):
        """Run the MEM-PAGE-REFCOUNT audit over the live ledger; returns
        the findings (empty = every page owned exactly once). With an
        int8 KV pool the audit additionally cross-checks the scale
        planes: every held page position carrying quantized bytes must
        carry its write-time scale (a CoW/copy path that moved page
        bytes without the scales dequantizes the copy to garbage)."""
        from ..analysis.memory import (audit_kv_scale_planes,
                                       audit_page_ledger)
        findings = audit_page_ledger(self.page_ledger())
        if self.d.kv_quant:
            held = {p for pg in self._slot_pages for p in pg}
            if self.cache is not None:
                held |= set(self.cache.pages())
            findings += audit_kv_scale_planes(self.d, sorted(held))
        return findings

    def _table(self, pages_per_slot, decoder):
        """Page table with inactive/unused entries routed to the reserved
        scratch page (their masked, discarded KV writes must never land
        in allocatable pages)."""
        t = np.full((decoder.max_batch, decoder.max_pages),
                    decoder.num_pages - 1, np.int32)
        for s, pg in enumerate(pages_per_slot):
            if pg:
                t[s, :len(pg)] = pg
        return t

    def step(self):
        """Admit + one decode tick. Returns number of active slots."""
        self._admit()
        active = [s for s in range(self.d.max_batch)
                  if self._slot_req[s] is not None]
        if not active:
            return 0
        if self._table_cache is None:        # slots changed since last tick
            self._table_cache = self._table(self._slot_pages, self.d)
        nxt = np.asarray(self.d.decode(self._tokens, self._lens,
                                       self._table_cache,
                                       kids=self._kids,
                                       aids=self._aids))
        self.steps += 1
        self.stats.ticks += 1
        self.stats.decode_syncs += 1
        # pad ledger: the tick computed every batch row (one position
        # each); only the active rows' positions were real work
        self.stats.tokens_dispatched += self.d.max_batch
        self.stats.tokens_padded += self.d.max_batch - len(active)
        self.stats.occupancy.append(len(active) / self.d.max_batch)
        self._note_resident()
        for s in active:
            rid = self._slot_req[s]
            tok = int(nxt[s])
            self._outputs[rid].append(tok)
            self.stats.tokens += 1
            if self.trace is not None:
                self._trace_progress(rid)
            self._lens[s] += 1
            self._tokens[s] = tok
            done = (self.eos is not None and tok == self.eos) or \
                len(self._outputs[rid]) >= self.max_new
            if done:
                self._retire(s)
        return len(active)

    def run(self, step_times=None, on_sync=None):
        """Drain the queue; returns {request_id: generated token list}.
        `step_times`, if given, receives wall seconds per host sync —
        per decode tick on the per-tick path (k_max=1), per K-tick
        horizon on the multi-step paths (use `self.stats` for
        per-token percentiles either way). `on_sync(engine)`, if
        given, is called after every processed host sync — outputs are
        current at that point, and the callback may `submit()` new
        requests (the long-prompt-arrives-mid-stream bench drives
        arrival timing with it). The multi-step default is the RAGGED
        loop (prompt chunks ride the decode horizon, no host-blocking
        prefill); `ragged=False` keeps the dispatch-separate
        baseline."""
        if self.ragged:
            # an EXPLICIT ragged=True is honored even at k_max=1 (the
            # horizons are just one tick long): the user asked for
            # no-stall admission, silently downgrading to the
            # blocking-prefill per-tick loop would betray that
            return self._run_ragged(step_times, on_sync)
        if self.k_max <= 1:
            return self._run_per_tick(step_times, on_sync)
        return self._run_multi(step_times, on_sync)

    def serve_schedule(self):
        """The recent scheduling-decision trace (bounded window): one
        event per host-blocking prefill dispatch ("prefill_sync", with
        the decode slots it stalled) and per ragged horizon
        ("horizon", with its k/w and row mix). The
        SERVE-PREFILL-STALL rule (`analysis.analyzers
        .PrefillStallAnalyzer`) audits this — a prefill_sync with
        decode_active > 0 is the stall the ragged path exists to
        kill."""
        return list(self._sched_events)

    def _run_per_tick(self, step_times=None, on_sync=None):
        """Legacy loop: one compiled tick, one host sync per token."""
        while self._queue or any(r is not None for r in self._slot_req):
            t0 = time.perf_counter()
            before = self.stats.tokens
            before_p = self.stats.prefill_syncs
            active = self.step()
            dt = time.perf_counter() - t0
            if step_times is not None:
                step_times.append(dt)
            n = self.stats.tokens - before
            # tiered-KV: drain any restore price — on this blocking
            # path a restore always rides a prefill-polluted window,
            # which the drift ledger excludes anyway
            self._take_restore_s()
            if self.trace is not None and active:
                # a step that contained a blocking prefill is not a
                # decode tick: price it as None so the drift ledger
                # stays a tick-roofline comparison (same exclusion as
                # token_time_s below)
                clean = self.stats.prefill_syncs == before_p
                warm = self._trace_shape_warm(("tick",))
                self.trace.tick(
                    "serve", ("tick", 1, 1), dt, ts=t0,
                    predicted_s=(self._price_horizon(
                        1, 1, 0, decode_rows=active)
                                 if clean else None),
                    predicted_serial_s=(self._price_horizon(
                        1, 1, 0, decode_rows=active, serial=True)
                                 if clean else None),
                    drift=clean and warm, k=1, w=1,
                    decode_rows=active, prefill_rows=0, tokens=n,
                    tokens_dispatched=self.d.max_batch,
                    tokens_padded=self.d.max_batch - active,
                    pool=self._trace_pool_delta())
            # token_time_s is the STEADY-STATE decode latency: a sync
            # that contained a prefill is dominated by it (orders of
            # magnitude more work than a tick) and would turn p99 into
            # a prefill number — keep it out of the percentiles
            if n and self.stats.prefill_syncs == before_p:
                self.stats.token_time_s.extend([dt / n] * n)
            if on_sync is not None:
                on_sync(self)
        return dict(self._outputs)

    def _budget_left(self, slot):
        """Tokens this slot may still emit (host view, excludes ticks
        already dispatched but not yet processed)."""
        return self.max_new - len(self._outputs[self._slot_req[slot]])

    def _horizon(self, slots, inflight):
        """Largest power-of-two tick count ≤ k_max that fits every
        dispatchable slot's remaining budget (powers of two bound the
        decode_multi compile count, like the prefill buckets)."""
        rem = min(self._budget_left(s) - inflight[s] for s in slots)
        k = 1
        while k * 2 <= min(rem, self.k_max):
            k *= 2
        return k

    def _merge_carry(self, carry, admitted):
        """Device-resident decode state for the next horizon. The carry
        never round-trips through the host: newly admitted slots are
        scattered into the in-flight arrays with device ops."""
        S = self.d.max_batch
        if carry is None:
            done = np.array([r is None for r in self._slot_req])
            rem = np.array([self._budget_left(s) if self._slot_req[s]
                            is not None else 0 for s in range(S)],
                           np.int32)
            return (jnp.asarray(self._tokens), jnp.asarray(self._lens),
                    jnp.asarray(done), jnp.asarray(rem))
        if not admitted:
            return carry
        tokens, lens, done, rem = carry
        idx = jnp.asarray(admitted, jnp.int32)
        tokens = tokens.at[idx].set(jnp.asarray(self._tokens[admitted]))
        lens = lens.at[idx].set(jnp.asarray(self._lens[admitted]))
        done = done.at[idx].set(False)
        rem = rem.at[idx].set(jnp.asarray(
            [self._budget_left(s) for s in admitted], jnp.int32))
        return tokens, lens, done, rem

    def _process_block(self, meta, inflight, step_times,
                       prefilled_since=False, trace_ev=None):
        """Fetch + bookkeep one finished horizon. Called AFTER the next
        horizon is dispatched, so the device→host wait overlaps it."""
        block_d, done_before_d, k, rids, t0, had_prefill = meta
        block = np.asarray(block_d)
        done_before = np.asarray(done_before_d)
        self.stats.decode_syncs += 1
        # pad ledger: the fused loop computed k*S positions; frozen
        # rows' ticks (done_before True) were filler — the device mask
        # is the one exact source (EOS freezes mid-horizon)
        disp_toks = k * self.d.max_batch
        pad_toks = int(done_before.sum())
        self.stats.tokens_dispatched += disp_toks
        self.stats.tokens_padded += pad_toks
        emitted = 0
        for s, rid in rids.items():
            inflight[s] = max(0, inflight[s] - k)
            if self._slot_req[s] != rid:
                continue
            for j in range(k):
                if done_before[j, s]:
                    break
                tok = int(block[j, s])
                self._outputs[rid].append(tok)
                self.stats.tokens += 1
                emitted += 1
                if self.trace is not None:
                    self._trace_progress(rid)
                self._lens[s] += 1
                self._tokens[s] = tok
                if (self.eos is not None and tok == self.eos) or \
                        len(self._outputs[rid]) >= self.max_new:
                    self._retire(s)
                    break
        dt = time.perf_counter() - t0
        if step_times is not None:
            step_times.append(dt)
        if trace_ev is not None:
            # a window containing a prefill, the shape's first
            # (compiling) dispatch, or another shape's compile landing
            # inside this still-open window, is excluded from the
            # drift ledger (same pollution rule as the token
            # percentiles)
            self.trace.tick_complete(
                trace_ev, dt, tokens=emitted,
                tokens_dispatched=disp_toks, tokens_padded=pad_toks,
                drift=(not (had_prefill or prefilled_since)
                       and trace_ev.get("warm_shape", True)
                       and not trace_ev.get("compiled_in_window")),
                pool=self._trace_pool_delta())
        # steady-state decode latency only: the block's dt window spans
        # its dispatch iteration AND the next iteration up to this
        # call, so a prefill in either (had_prefill at dispatch,
        # prefilled_since at processing) would make p99 a prefill
        # number — exclude such blocks from the percentiles (see
        # _run_per_tick)
        if emitted and not had_prefill and not prefilled_since:
            self.stats.token_time_s.extend([dt / emitted] * emitted)

    def _run_multi(self, step_times=None, on_sync=None):
        """Horizon-scheduled drain: dispatch a K-tick device-resident
        block, then process the PREVIOUS block while the new one runs.
        Retirement is one horizon delayed — a slot that finishes inside
        block N stays frozen on device through block N+1 (done mask
        carried on device; its K/V writes route to the scratch page)
        and its pages are freed exactly once, when block N's results
        land on the host. Prefix-cache interplay inherits the same
        discipline: a retiring slot's shared pages are DECREF'd at
        block-processing time (parked, not reused), and eviction
        reclaims them only at a later admission — whose prefill writes
        are device-ordered after every in-flight horizon, so a fused
        horizon can never read a page that was re-written under it."""
        S = self.d.max_batch
        pending = None               # the in-flight horizon's meta
        pending_ev = None            # its open tick record (trace on)
        carry = None                 # device (tokens, lens, done, rem)
        inflight = [0] * S           # dispatched-not-yet-processed ticks
        while (self._queue or pending is not None
               or any(r is not None for r in self._slot_req)):
            t0 = time.perf_counter()
            before_p = self.stats.prefill_syncs
            admitted = self._admit()
            # a prefill ran iff the sync counter moved — NOT iff any
            # request entered decode: a round whose every admission
            # finishes AT prefill (EOS on the first token) returns an
            # empty `admitted` but still paid a prefill forward, which
            # must stay out of the steady-state token percentiles
            # (same delta discipline as _run_per_tick)
            prefilled = self.stats.prefill_syncs != before_p
            for s in admitted:
                # a freshly admitted slot starts from a clean device
                # carry (_merge_carry), so ticks still in flight for
                # the slot's PREVIOUS request must not gate its
                # dispatch. Unreachable today (a fresh budget
                # max_new-1 always exceeds the stale count, which is
                # bounded by the retired request's remaining budget
                # minus the processed block), but reset defensively:
                # the rid check skips the old block's tokens and the
                # max(0, ...) clamp absorbs the double subtraction.
                inflight[s] = 0
            carry = self._merge_carry(carry, admitted)
            # invariant: for a live non-admitted slot, the device-side
            # `remaining` equals budget_left - inflight exactly (both
            # count init budget minus dispatched ticks), so a slot
            # excluded here is always already frozen on device — its
            # ticks in another slot's block are filler, never lost
            # tokens
            disp = [s for s in range(S) if self._slot_req[s] is not None
                    and self._budget_left(s) - inflight[s] > 0]
            meta = None
            meta_ev = None
            if disp:
                k = self._horizon(disp, inflight)
                if self._table_cache is None:
                    self._table_cache = self._table(self._slot_pages,
                                                    self.d)
                tokens_d, lens_d, done_d, rem_d = carry
                out = self.d.decode_multi(
                    tokens_d, lens_d, self._table_cache, k,
                    kids=self._kids, done=done_d, remaining=rem_d,
                    eos=self.eos, aids=self._aids)
                carry = (out.tokens, out.lens, out.done, out.remaining)
                self.steps += k
                self.stats.ticks += k
                self.stats.occupancy.append(len(disp) / S)
                self._note_resident()
                for s in disp:
                    inflight[s] += k
                meta = (out.tokens_block, out.done_before, k,
                        {s: self._slot_req[s] for s in disp}, t0,
                        prefilled)
                # tiered-KV: the H2D of any restore dispatched this
                # round lands inside THIS horizon's measured window —
                # its price rides the prediction (drained even when
                # untraced so it can't accumulate)
                restore_s = self._take_restore_s()
                if self.trace is not None:
                    meta_ev = self.trace.tick_dispatch(
                        "serve", ("decode", k, 1), ts=t0,
                        predicted_s=self._price_horizon(
                            k, 1, 0, decode_rows=len(disp)) + restore_s,
                        predicted_serial_s=self._price_horizon(
                            k, 1, 0, decode_rows=len(disp), serial=True)
                        + restore_s,
                        k=k, w=1, decode_rows=len(disp), prefill_rows=0,
                        warm_shape=self._trace_shape_warm(("decode", k)))
                    if pending_ev is not None and \
                            not meta_ev["warm_shape"]:
                        # THIS dispatch's compile ran inside the
                        # PENDING tick's still-open measured window
                        # (processing closes after the next dispatch)
                        pending_ev["compiled_in_window"] = True
            if pending is not None:
                self._process_block(pending, inflight, step_times,
                                    prefilled_since=prefilled,
                                    trace_ev=pending_ev)
                if on_sync is not None:
                    on_sync(self)
            pending = meta
            pending_ev = meta_ev
        return dict(self._outputs)

    # -- ragged scheduling (chunked prefill INSIDE the decode horizon) --

    def _admit_ragged(self):
        """Admission without a prefill dispatch: mount the prefix-cache
        span (zero device work), allocate pages, hand the uncached
        suffix to the SCHEDULER — the suffix streams into the horizon
        w tokens per tick from the device-resident pend carry. Returns
        [(slot, rid, suffix), ...] for the carry merge."""
        admitted = self._gather_admissions()
        if not admitted:
            return []
        now = time.perf_counter()
        for _, rid, _, _ in admitted:
            t0 = self._submit_t.get(rid)
            if t0 is not None:
                self._note_queue_wait(rid, now - t0)
        if self.trace is not None:
            self._trace_admits(admitted, now)
        self._table_cache = None
        plans = []
        for slot, rid, ids, pages in admitted:
            start = self._cache_meta[rid][0] if self.cache is not None \
                else 0
            suffix = ids[start:]
            # setdefault: a RESUMED request (tenancy preemption) keeps
            # its generated prefix — the continuation appends to it
            self._outputs.setdefault(rid, [])
            self._lens[slot] = start
            self._tokens[slot] = 0
            self._kids[slot] = rid
            self._prompt_len[slot] = len(ids)
            self._after_admit(slot, len(ids))
            self.scheduler.admit(slot, len(suffix))
            self.stats.prefill_chunk_tokens += len(suffix)
            plans.append((slot, rid, suffix))
        return plans

    def _first_token(self, rid, slot):
        """A request's FIRST token just landed on the host: stamp TTFT
        (submit -> first token — comparable across the legacy and
        chunked paths, however many horizon boundaries the prefill
        spanned) and publish its freshly computed cache blocks (their
        writes are device-ordered before any future mount's reads)."""
        t0 = self._submit_t.pop(rid, None)
        if t0 is not None:
            self._note_ttft(rid, time.perf_counter() - t0)
        if self.trace is not None:
            self.trace.record("first_token", rid=rid)
        self._publish_blocks(rid, slot)
        # prompt fully consumed; the emitted token is not consumed yet
        self._lens[slot] = self._prompt_len[slot]

    def _merge_carry_ragged(self, carry, plans):
        """Device-resident mixed-horizon state: (tokens, lens, done,
        remaining, pend, pend_n). Newly admitted slots scatter their
        suffix into the pend buffer with device ops — the carry never
        round-trips through the host."""
        S = self.d.max_batch
        P = self.d.pend_capacity
        if carry is None:
            done = np.array([r is None for r in self._slot_req])
            rem = np.array([self._budget_left(s) if self._slot_req[s]
                            is not None else 0 for s in range(S)],
                           np.int32)
            pend = np.zeros((S, P), np.int32)
            pend_n = np.zeros(S, np.int32)
            for slot, _rid, suffix in plans:
                pend[slot, :len(suffix)] = suffix
                pend_n[slot] = len(suffix)
            return (jnp.asarray(self._tokens), jnp.asarray(self._lens),
                    jnp.asarray(done), jnp.asarray(rem),
                    jnp.asarray(pend), jnp.asarray(pend_n))
        if not plans:
            return carry
        tokens, lens, done, rem, pend, pend_n = carry
        idx = jnp.asarray([s for s, _, _ in plans], jnp.int32)
        rows = np.zeros((len(plans), P), np.int32)
        ns = np.zeros(len(plans), np.int32)
        for r, (slot, _rid, suffix) in enumerate(plans):
            rows[r, :len(suffix)] = suffix
            ns[r] = len(suffix)
        slots = [s for s, _, _ in plans]
        tokens = tokens.at[idx].set(jnp.asarray(self._tokens[slots]))
        lens = lens.at[idx].set(jnp.asarray(self._lens[slots]))
        done = done.at[idx].set(False)
        rem = rem.at[idx].set(jnp.asarray(
            [self._budget_left(s) for s in slots], jnp.int32))
        pend = pend.at[idx].set(jnp.asarray(rows))
        pend_n = pend_n.at[idx].set(jnp.asarray(ns))
        return tokens, lens, done, rem, pend, pend_n

    def _process_ragged_block(self, meta, inflight, step_times,
                              trace_ev=None):
        """Fetch + bookkeep one finished mixed horizon (called AFTER
        the next horizon is dispatched, so the device->host wait
        overlaps it). The per-tick `emitted` mask separates real
        tokens from filler ticks AND from mid-prefill chunk ticks; a
        request's first emitted token triggers TTFT + cache
        publishing. No percentile exclusions here: every sync on this
        path is a decode-path sync by construction — chunk ticks are
        budgeted small enough to ride inside it, and their cost
        SHOULD show in the per-token tail (that honesty is what the
        stall bench measures)."""
        block_d, emitted_d, real_d, disp_toks, k, rids, emit_ticks, t0 = \
            meta
        block = np.asarray(block_d)
        emitted = np.asarray(emitted_d)
        # pad ledger: dispatched is the horizon's layout cost (k * the
        # packed t_tokens bucket, or k*S*w dense); real is the device's
        # per-tick consumed-position count — exact even when EOS froze
        # a slot mid-horizon
        pad_toks = disp_toks - int(np.asarray(real_d).sum())
        self.stats.tokens_dispatched += disp_toks
        self.stats.tokens_padded += pad_toks
        self.stats.decode_syncs += 1
        n_emitted = 0
        for s, (rid, gen) in rids.items():
            if self._slot_req[s] != rid or self._slot_gen[s] != gen:
                # stale block of a retired/re-admitted slot: its emit
                # ticks were already DISCARDED by the inflight reset at
                # re-admission — subtracting them again would understate
                # the new request's in-flight emissions, and unlike
                # _run_multi's harmless scheduling slack, here inflight
                # feeds _table_width's correctness-critical position
                # bound. The GENERATION stamp matters beyond the rid:
                # preemption (tenancy) can resume the SAME rid into the
                # same slot while its pre-preemption block is still in
                # flight — those tokens are regenerated post-resume and
                # must not double-append
                continue
            inflight[s] = max(0, inflight[s] - emit_ticks.get(s, 0))
            for j in range(k):
                if not emitted[j, s]:
                    continue
                tok = int(block[j, s])
                if len(self._outputs[rid]) == self._emit_base.get(rid, 0):
                    # first token of THIS admission: TTFT (fresh
                    # requests only — a resume's _submit_t is long
                    # popped), cache publishing, the lens jump to the
                    # admitted prompt length
                    self._first_token(rid, s)
                else:
                    self._lens[s] += 1
                self._outputs[rid].append(tok)
                self.stats.tokens += 1
                n_emitted += 1
                if self.trace is not None:
                    self._trace_progress(rid)
                self._tokens[s] = tok
                if (self.eos is not None and tok == self.eos) or \
                        len(self._outputs[rid]) >= self.max_new:
                    self._retire(s)
                    break
        dt = time.perf_counter() - t0
        if step_times is not None:
            step_times.append(dt)
        if trace_ev is not None:
            # a compiling dispatch (this shape's first, or another
            # shape's compile landing inside this still-open window)
            # stays out of the drift ledger; steady ragged windows ARE
            # the honest tick (chunk cost included by design — see
            # token_time_s above)
            self.trace.tick_complete(
                trace_ev, dt, tokens=n_emitted,
                tokens_dispatched=disp_toks, tokens_padded=pad_toks,
                drift=(trace_ev.get("warm_shape", True)
                       and not trace_ev.get("compiled_in_window")),
                pool=self._trace_pool_delta())
        if n_emitted:
            self.stats.token_time_s.extend([dt / n_emitted] * n_emitted)

    def _table_width(self, live, plan, inflight):
        """Page-table columns this horizon can actually touch: the max
        over live slots of the position bound it may read or write,
        bucketed to a power of two (bounded compile count). Trailing
        table entries hold only causally-masked pages — an EXACT
        no-op in the ragged attention's online softmax (masked logits
        underflow to p = 0.0 and never move the running max), so
        slicing them off is bitwise-identical while making early
        chunk ticks of a long prompt pay a SHORT gather instead of
        the pool-capacity one (on TPU the kernel streams one page per
        grid step anyway; on CPU the reference's gather width is the
        mixed tick's dominant cost)."""
        ps = self.d.page_size
        bound = 1
        for s, rid in live.items():
            if self.scheduler.prefilling(s):
                # suffix_left was already decremented by plan():
                # positions consumed after this horizon, plus k emitted
                # tokens if the prompt finishes inside it
                pos = (self._prompt_len[s]
                       - self.scheduler.suffix_left(s) + plan.k + 1)
            else:
                # NOT host _lens: it lags at the cached start until the
                # first token is PROCESSED, while the device may already
                # sit at prompt_len + in-flight emissions. Outputs are
                # counted from this ADMISSION's base: a resumed
                # request's pre-preemption tokens are already inside
                # _prompt_len (they are the resume prompt's tail) and
                # must not widen the bound twice
                pos = (self._prompt_len[s]
                       + len(self._outputs.get(rid, ()))
                       - self._emit_base.get(rid, 0)
                       + inflight[s] + plan.k + 2)
            bound = max(bound, pos)
        need = min(self.d.max_pages, (bound + ps - 1) // ps + 1)
        width = 1
        while width < need:
            width *= 2
        return min(width, self.d.max_pages)

    def _run_ragged(self, step_times=None, on_sync=None):
        """Mixed-horizon drain: every scheduling round admits queued
        prompts STRAIGHT into the device carry (prefix-cache mount +
        page allocation only — no prefill dispatch, no prefill sync),
        then dispatches one `ragged_multi` block of k ticks in which
        decode rows emit a token per tick while prefilling rows
        consume w prompt tokens per tick, and processes the PREVIOUS
        block while the new one runs. One long prompt therefore
        costs every other slot at most ceil(suffix/w) slightly-longer
        ticks instead of one monolithic prefill stall — the
        throughput-under-load lever the ROADMAP names. Retirement
        keeps the one-horizon-delayed discipline of `_run_multi`
        (pages freed exactly once, at block-processing time; shared
        pages decref'd there, reusable only by later admissions whose
        writes are device-ordered after every in-flight horizon)."""
        S = self.d.max_batch
        sched = self.scheduler
        pending = None               # the in-flight horizon's meta
        pending_ev = None            # its open tick record (trace on)
        carry = None                 # (tokens, lens, done, rem, pend, pend_n)
        inflight = [0] * S           # in-flight EMISSION ticks per slot
        while (self._queue or pending is not None
               or any(r is not None for r in self._slot_req)):
            t0 = time.perf_counter()
            plans = self._admit_ragged()
            for slot, _, _ in plans:
                # fresh request in a recycled slot: stale in-flight
                # ticks belong to the PREVIOUS request (the rid check
                # skips its tokens) and must not gate this one
                inflight[slot] = 0
            carry = self._merge_carry_ragged(carry, plans)
            live = {s: self._slot_req[s] for s in range(S)
                    if self._slot_req[s] is not None}
            meta = None
            meta_ev = None
            plan = sched.plan(live,
                              {s: self._budget_left(s) for s in live},
                              inflight) if live else None
            if plan is not None:
                if self._table_cache is None:
                    self._table_cache = self._table(self._slot_pages,
                                                    self.d)
                tokens_d, lens_d, done_d, rem_d, pend_d, pend_n_d = carry
                width = self._table_width(live, plan, inflight)
                t_tokens = plan.t_tokens
                if self.packed and t_tokens is None:
                    # a custom scheduler may build HorizonPlan without
                    # t_tokens: fall back to the dense-equivalent
                    # bucket here so the dispatch and the pad ledger
                    # below price the SAME layout
                    from .decoder import pow2_at_least
                    t_tokens = pow2_at_least(S * max(plan.w, 1))
                out = self.d.ragged_multi(
                    tokens_d, lens_d, self._table_cache[:, :width],
                    plan.k, plan.w, pend_d, pend_n_d, kids=self._kids,
                    done=done_d, remaining=rem_d, eos=self.eos,
                    packed=self.packed, t_tokens=t_tokens,
                    aids=self._aids)
                carry = (out.tokens, out.lens, out.done, out.remaining,
                         out.pend, out.pend_n)
                self.steps += plan.k
                self.stats.ticks += plan.k
                self.stats.prefill_chunks += plan.n_chunks
                self.stats.occupancy.append(len(live) / S)
                self._note_resident()
                for s, e in plan.emit_ticks.items():
                    inflight[s] += e
                # layout cost of this dispatch: the packed path pays
                # the total-token bucket per tick, the dense twin the
                # full [S, w] window grid
                disp_toks = plan.k * (t_tokens if self.packed
                                      else S * plan.w)
                self._sched_events.append(
                    {"kind": "horizon", "k": plan.k, "w": plan.w,
                     "t_tokens": t_tokens if self.packed else None,
                     "decode_rows": len(live) - plan.prefill_rows,
                     "prefill_rows": plan.prefill_rows})
                meta = (out.tokens_block, out.emitted, out.real,
                        disp_toks, plan.k,
                        {s: (rid, self._slot_gen[s])
                         for s, rid in live.items()},
                        plan.emit_ticks, t0)
                # tiered-KV: restores dispatched at this round's
                # admission are functionally ordered before this
                # horizon's reads — their priced H2D belongs to this
                # window's prediction (drained even when untraced)
                restore_s = self._take_restore_s()
                if self.trace is not None:
                    shape = (("packed", plan.k, t_tokens)
                             if self.packed
                             else ("ragged", plan.k, plan.w))
                    meta_ev = self.trace.tick_dispatch(
                        "serve", shape, ts=t0,
                        predicted_s=self._price_horizon(
                            plan.k, plan.w, plan.prefill_rows,
                            decode_rows=len(live) - plan.prefill_rows)
                        + restore_s,
                        predicted_serial_s=self._price_horizon(
                            plan.k, plan.w, plan.prefill_rows,
                            decode_rows=len(live) - plan.prefill_rows,
                            serial=True) + restore_s,
                        k=plan.k, w=plan.w,
                        decode_rows=len(live) - plan.prefill_rows,
                        prefill_rows=plan.prefill_rows,
                        # the jit key is (k, w-or-t, table width): a
                        # fresh combination compiles inside this window
                        warm_shape=self._trace_shape_warm(
                            shape + (width,)))
                    if pending_ev is not None and \
                            not meta_ev["warm_shape"]:
                        # see _run_multi: the compile lands in the
                        # pending tick's still-open window
                        pending_ev["compiled_in_window"] = True
            if pending is not None:
                self._process_ragged_block(pending, inflight, step_times,
                                           trace_ev=pending_ev)
                if on_sync is not None:
                    on_sync(self)
            pending = meta
            pending_ev = meta_ev
        return dict(self._outputs)


class SpeculativeEngine(ContinuousBatchingEngine):
    """Speculative decoding over the paged engine: a small DRAFT model
    proposes k tokens with k cheap decode ticks; the TARGET model scores
    all of them in ONE verify forward. Greedy configs accept the longest
    matching prefix (+ the target's token at the first mismatch) —
    output is EXACTLY the target's greedy decode; sampled configs (same
    temperature/top-k/top-p on both decoders) use rejection-sampling
    acceptance (_spec_accept), so emitted tokens are distributed exactly
    as target-only sampling. Either way: up to k-times fewer target
    forwards. Paged KV makes rollback free: `lens` is the source of
    truth, rejected positions are simply overwritten.

    Acceptance is capped at k-1 drafts so the draft cache (which holds
    proposals d1..d_{k-1}) never falls behind; when all k drafts match,
    the capped path still emits exactly d1..dk.
    """

    def __init__(self, decoder, draft_decoder, eos_token_id=None,
                 max_new_tokens=64, k=4, trace=None):
        if decoder.sampling != draft_decoder.sampling:
            raise ValueError(
                "speculative decoding needs the SAME sampling config on "
                "target and draft (acceptance compares their masked "
                f"distributions): {decoder.sampling} vs "
                f"{draft_decoder.sampling}")
        if draft_decoder.max_batch != decoder.max_batch or \
                draft_decoder.page_size != decoder.page_size:
            raise ValueError("draft/target max_batch and page_size must match")
        if decoder.lora is not None or draft_decoder.lora is not None:
            # verify() runs the base weights only — silently serving a
            # LoRA request through it would emit base-model tokens
            raise ValueError(
                "SpeculativeEngine does not support LoRA adapter banks "
                "(attach_adapters): the verify window does not gather "
                "adapters — use ContinuousBatchingEngine/TenantEngine")
        if decoder.kv_quant or draft_decoder.kv_quant:
            # out of scope for quantized pools (docs/serving.md):
            # verify windows write up to k positions past the accepted
            # length, and the twin-pool rollback discipline for
            # quantized bytes+scales — per-token int8 planes and
            # packed-nibble int4 group planes alike — is unproven;
            # refuse rather than risk a silent drift between the pools
            quant = decoder.kv_quant or draft_decoder.kv_quant
            raise ValueError(
                f"SpeculativeEngine does not support quantized KV "
                f"pools (kv_quant={quant!r}; int8 and int4 alike): "
                "use ContinuousBatchingEngine, or plain bf16 pools "
                "for speculation")
        # k_max=1: the verify cadence IS this engine's horizon — each
        # step() already moves a k-token window; the draft's ticks are
        # device-resident via decode_multi below. (No prefix_cache:
        # verify windows WRITE up to k positions past the accepted
        # length, which would dirty mounted shared pages — chunked
        # admission for the twin pools is an open item.)
        super().__init__(decoder, eos_token_id, max_new_tokens, k_max=1,
                         trace=trace)
        self.draft = draft_decoder
        self.k = int(k)
        self._draft_free = list(range(draft_decoder.num_pages - 2, -1, -1))
        self._draft_pages = [[] for _ in range(decoder.max_batch)]
        self._dlens = np.zeros(decoder.max_batch, np.int32)
        self.target_calls = 0

    def submit(self, prompt_ids):
        """Same as the base, with a +k margin: a verify window can write
        up to k positions past the final accepted length."""
        ids = np.asarray(prompt_ids._value if isinstance(prompt_ids, Tensor)
                         else prompt_ids).reshape(-1)
        if len(ids) == 0:
            raise ValueError(
                "prompt must contain at least one token (prefill "
                "samples the first generated token after the prompt's "
                "last position — an empty prompt has none)")
        total = len(ids) + self.max_new + self.k
        need = self._pages_for(total)
        limit = min(self.d.max_pages, self.draft.max_pages,
                    self.d.num_pages - 1, self.draft.num_pages - 1)
        if need > limit:
            raise ValueError(
                f"request needs {need} pages (prompt {len(ids)} + max_new "
                f"{self.max_new} + speculation margin {self.k}) but the "
                f"pools allow {limit}")
        if total > min(self.d.cfg.max_seq_len, self.draft.cfg.max_seq_len):
            raise ValueError(
                f"prompt {len(ids)} + max_new {self.max_new} + margin "
                f"{self.k} exceeds max_seq_len "
                f"{min(self.d.cfg.max_seq_len, self.draft.cfg.max_seq_len)}")
        return self._register_request([int(t) for t in ids])

    def _gather_admissions(self):
        admitted = []
        for slot in range(self.d.max_batch):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            rid, ids = self._queue[0]
            # +k margin: a verify window may write up to k positions past
            # the final accepted length
            need = self._pages_for(len(ids) + self.max_new + self.k)
            if need > len(self._free) or need > len(self._draft_free) \
                    or need > self.d.max_pages \
                    or need > self.draft.max_pages:
                break
            self._queue.pop(0)
            pages = [self._free.pop() for _ in range(need)]
            dpages = [self._draft_free.pop() for _ in range(need)]
            self._occupy(slot, rid)
            self._slot_pages[slot] = pages
            self._draft_pages[slot] = dpages
            admitted.append((slot, rid, ids, pages))
        return admitted

    def _extra_prefill(self, admitted):
        self.draft.prefill_batch(           # draft's guesses discarded
            [(ids, self._draft_pages[slot])
             for slot, _, ids, _ in admitted],
            kids=[rid for _, rid, _, _ in admitted])

    def _after_admit(self, slot, prompt_len):
        self._dlens[slot] = prompt_len

    def _retire(self, slot):
        self._draft_free.extend(self._draft_pages[slot])
        self._draft_pages[slot] = []
        self._dlens[slot] = 0
        super()._retire(slot)

    def step(self):
        self._admit()
        active = [s for s in range(self.d.max_batch)
                  if self._slot_req[s] is not None]
        if not active:
            return 0
        k = self.k
        if self._table_cache is None:        # slots changed since last tick
            self._table_cache = (self._table(self._slot_pages, self.d),
                                 self._table(self._draft_pages, self.draft))
        ttable, dtable = self._table_cache

        sampled = self.d.sampling is not None

        # draft proposes k tokens: K DEVICE-RESIDENT ticks in ONE
        # compiled loop (decode_multi) — the proposal chain feeds back
        # on device, so the k cheap ticks cost one dispatch + one fetch
        # instead of k host round-trips
        qrows = None
        out = self.draft.decode_multi(self._tokens, self._dlens, dtable,
                                      k, kids=self._kids,
                                      return_logits=sampled)
        proposals = np.asarray(out.tokens_block).T.astype(np.int32)
        if sampled and k > 1:
            # the k-th draft's distribution is never judged (acceptance
            # is capped at k-1): skip its transfer
            qp = self.draft._probs_of(out.logits_block[:k - 1])
            qrows = np.moveaxis(qp, 0, 1)          # [S, k-1, V]
        self.stats.ticks += k
        self.stats.decode_syncs += 1

        # target verifies [cur, d1..dk] in one forward
        window = np.concatenate(
            [self._tokens[:, None], proposals[:, :k]], axis=1)  # [S, k+1]
        if sampled:
            tgt, prows = self.d.verify(window, self._lens, ttable,
                                       return_probs=True)
        else:
            tgt = self.d.verify(window, self._lens, ttable)     # [S, k+1]
        self.target_calls += 1
        self.steps += 1
        self.stats.ticks += 1
        self.stats.decode_syncs += 1
        # pad ledger: one spec step computes k draft positions plus a
        # (k+1)-wide verify window per batch row; rows with no request
        # were padding (speculated-then-rejected drafts are real work,
        # not padding — they're the engine's gamble, not the layout's)
        S_all = self.d.max_batch
        self.stats.tokens_dispatched += S_all * (2 * k + 1)
        self.stats.tokens_padded += (S_all - len(active)) * (2 * k + 1)
        self.stats.occupancy.append(len(active) / self.d.max_batch)
        self._note_resident()

        for s in active:
            rid = self._slot_req[s]
            if sampled:
                rng = np.random.default_rng(
                    (self.d.seed * 1000003 + self.target_calls) * 4093 + s)
                a, tok = _spec_accept(
                    prows[s, :k],
                    qrows[s] if qrows is not None else
                    np.zeros((0, prows.shape[-1])),
                    proposals[s, :k - 1], rng)
                emitted = [int(t) for t in proposals[s, :a]] + [tok]
            else:
                a = 0
                while a < k - 1 and proposals[s, a] == tgt[s, a]:
                    a += 1
                emitted = [int(t) for t in proposals[s, :a]] + \
                    [int(tgt[s, a])]
            L = int(self._lens[s])
            self._lens[s] = L + a + 1
            self._dlens[s] = L + a + 1
            self._tokens[s] = emitted[-1]
            done = False
            for t in emitted:
                self._outputs[rid].append(t)
                self.stats.tokens += 1
                if self.trace is not None:
                    self._trace_progress(rid)
                if (self.eos is not None and t == self.eos) or \
                        len(self._outputs[rid]) >= self.max_new:
                    done = True      # tokens speculated past the stop
                    break            # point are simply never appended
            if done:
                self._retire(s)
        return len(active)

    def _price_horizon(self, k, w, prefill_rows, decode_rows=0,
                       serial=False):
        """One SPEC step's roofline price, overriding the plain decode
        tick: k device-resident draft ticks (draft pool HBM leg) + one
        (k+1)-position verify forward over the target (HBM vs window
        compute) + the step's TWO host syncs (draft fetch, verify
        fetch). Without this the per-tick loop would price a spec step
        as one target tick and the drift ledger would flag a correctly
        performing engine ~k-fold 'underpriced'. `serial=True` sums
        the verify legs instead of taking their max (the
        serialized-vs-mispriced verdict band, like the base engine)."""
        from ..cost_model import (decode_tick_roofline_s,
                                  measured_host_sync_s,
                                  ragged_tick_legs)
        if self._trace_price is None:
            self._trace_price = (self.d.step_hbm_bytes(),
                                 2.0 * self.d.cfg.num_params(),
                                 measured_host_sync_s())
            self._trace_draft_hbm = self.draft.step_hbm_bytes()
        hbm, fpt, sync = self._trace_price
        draft = self.k * decode_tick_roofline_s(self._trace_draft_hbm)
        hbm_s, compute_s = ragged_tick_legs(hbm, self.k + 1, fpt)
        verify = (hbm_s + compute_s) if serial else max(hbm_s, compute_s)
        return draft + verify + 2 * sync
