"""Continuous-batching engines over the paged decoder: slot scheduling,
horizon-fused decode, prefix-cache admission, speculative decoding."""
import time

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from .decoder import PagedGPTDecoder, _spec_accept
from .stats import _ENGINES, ServeStats

__all__ = ["ContinuousBatchingEngine", "SpeculativeEngine"]


class ContinuousBatchingEngine:
    """Slot-based continuous batching: requests are admitted into free
    slots as soon as capacity allows (iteration-level scheduling), decode
    runs one compiled step for ALL active slots, finished sequences free
    their pages.

    By default `run()` schedules in HORIZONS: blocks of
    `k = min(k_max, smallest remaining budget)` device-resident decode
    ticks (`PagedGPTDecoder.decode_multi`), with the host syncing only
    at block boundaries for admission/retirement/output append, and each
    block's fetch overlapped against the NEXT block's dispatch
    (one-horizon-delayed retirement: a slot finishing inside block N
    stays frozen on device through block N+1 — its writes route to the
    scratch page — and its pages are freed exactly once, when block N is
    processed). `k_max` defaults to `cost_model.decode_horizon`'s priced
    answer; `k_max=1` selects the legacy per-tick loop (`step()` is the
    per-tick API either way).

    With `prefix_cache` (a `PrefixCache`) admission becomes
    content-addressed: each prompt's full token blocks are hashed
    against the cache, fully-cached prefix spans are MOUNTED into the
    request's page-table row host-side (zero device work — the pages
    already hold exactly the KV bytes this prompt's prefill would
    write), and only the uncached suffix runs through the chunked
    prefill (`PagedGPTDecoder.prefill_suffix_batch`). Mounted pages are
    refcounted and immutable: a request about to write into a shared
    page (the first divergent token — only possible when the WHOLE
    prompt was cached and its last position must be re-consumed for
    logits) gets a copy-on-write private copy first. Retirement decrefs
    shared pages instead of freeing them; refcount-0 pages park in the
    cache's LRU and are evicted back to the free list only under pool
    pressure — every page freed exactly once, auditable via
    `page_ledger()`/`audit_pages()` (MEM-PAGE-REFCOUNT)."""

    def __init__(self, decoder: PagedGPTDecoder, eos_token_id=None,
                 max_new_tokens=64, k_max=None, host_sync_s=None,
                 prefix_cache=None):
        if max_new_tokens < 1:
            raise ValueError(
                "max_new_tokens must be >= 1 (the prefill forward always "
                f"produces one token), got {max_new_tokens}")
        self.d = decoder
        self.eos = eos_token_id
        self.max_new = max_new_tokens
        # page 0..num_pages-2 allocatable; last page reserved as scratch
        self._free = list(range(decoder.num_pages - 2, -1, -1))
        S = decoder.max_batch
        self._slot_req = [None] * S          # request id per slot
        self._slot_pages = [[] for _ in range(S)]
        # pages a slot holds as SHARED (cache-refcounted, never written)
        self._slot_shared = [set() for _ in range(S)]
        # int32 end to end: decode() feeds these to the kernel as int32,
        # so int64 here would insert a convert_element_type every tick
        self._lens = np.zeros(S, np.int32)
        self._tokens = np.zeros(S, np.int32)
        self._kids = np.zeros(S, np.int32)   # request id per slot: the
        # sampling key id, so a request's draws are independent of
        # which slot/batch/schedule served it
        self._table_cache = None             # rebuilt on admit/retire only
        self._queue = []                     # (req_id, ids)
        self._outputs = {}                   # req_id -> [generated ids]
        self._next_id = 0
        self.steps = 0
        if k_max is None:
            from ..cost_model import decode_horizon
            k_max = decode_horizon(decoder.step_hbm_bytes(),
                                   host_sync_s=host_sync_s)
        self.k_max = max(1, int(k_max))
        if prefix_cache is True:
            from .prefix_cache import PrefixCache
            prefix_cache = PrefixCache(decoder.page_size,
                                       salt=decoder.cache_fingerprint())
        if prefix_cache is not None and \
                prefix_cache.page_size != decoder.page_size:
            raise ValueError(
                f"prefix cache page_size {prefix_cache.page_size} != "
                f"decoder page_size {decoder.page_size}")
        self.cache = prefix_cache
        self._cache_meta = {}                # rid -> (start, keys, n_hit)
        self.stats = ServeStats(engine=type(self).__name__,
                                k_max=self.k_max)
        self._submit_t = {}                  # rid -> submit wall time
        _ENGINES.add(self)

    def submit(self, prompt_ids):
        ids = [int(t) for t in np.asarray(
            prompt_ids._value if isinstance(prompt_ids, Tensor)
            else prompt_ids).reshape(-1)]
        if not ids:
            raise ValueError(
                "prompt must contain at least one token (prefill "
                "samples the first generated token after the prompt's "
                "last position — an empty prompt has none)")
        total = len(ids) + self.max_new
        need = self._pages_for(total)
        if need > min(self.d.max_pages, self.d.num_pages - 1):
            raise ValueError(
                f"request needs {need} pages (prompt {len(ids)} + "
                f"max_new {self.max_new} tokens) but the pool allows "
                f"{min(self.d.max_pages, self.d.num_pages - 1)}")
        if total > self.d.cfg.max_seq_len:
            raise ValueError(
                f"prompt {len(ids)} + max_new {self.max_new} tokens "
                f"exceeds the model's max_seq_len "
                f"{self.d.cfg.max_seq_len} (positions past it have no "
                "embedding)")
        return self._register_request(ids)

    def _register_request(self, ids):
        """Queue a VALIDATED request: rid allocation, queue-wait stamp,
        stats — one implementation for both engines' submit()s, and
        called only after validation so a rejected submission can't
        skew stats.requests or leak a _submit_t entry."""
        rid = self._next_id
        self._next_id += 1
        self._submit_t[rid] = time.perf_counter()
        self.stats.requests += 1
        self._queue.append((rid, ids))
        return rid

    def _pages_for(self, n_tokens):
        return (n_tokens + self.d.page_size - 1) // self.d.page_size

    def _admit(self):
        # gather every admittable request first: same-length-bucket
        # prompts then prefill as ONE batched forward (iteration-level
        # batching applies to prefill too, not just decode). Pages freed
        # by EOS-at-prefill become available from the NEXT step's pass.
        # Returns the slots that entered decode (the multi-step run loop
        # merges exactly those into its device carry).
        admitted = self._gather_admissions()
        if not admitted:
            return []
        now = time.perf_counter()
        t0s = {}
        for _, rid, _, _ in admitted:
            t0 = self._submit_t.pop(rid, None)
            if t0 is not None:
                self.stats.queue_wait_s.append(now - t0)
                t0s[rid] = t0
        self._table_cache = None
        firsts = self._prefill_admitted(admitted)
        self.stats.prefill_syncs += 1
        self._extra_prefill(admitted)
        done_t = time.perf_counter()
        live = []
        for (slot, rid, ids, pages), first in zip(admitted, firsts):
            if rid in t0s:
                self.stats.ttft_s.append(done_t - t0s[rid])
            self._outputs[rid] = [first]
            self.stats.tokens += 1
            if (self.eos is not None and first == self.eos) \
                    or self.max_new <= 1:
                # finished at prefill: never occupy a decode slot
                self._retire(slot)
                continue
            self._lens[slot] = len(ids)
            self._tokens[slot] = first
            self._kids[slot] = rid
            self._after_admit(slot, len(ids))
            live.append(slot)
        return live

    def _prefill_admitted(self, admitted):
        """Dispatch the admitted requests' prefills: the flash-attention
        full prefill without a prefix cache; the CHUNKED suffix path
        with one (the cached span is mounted host-side — zero device
        work — and only positions start..L-1 compute). Freshly computed
        full blocks are published to the cache afterwards."""
        if self.cache is None:
            return self.d.prefill_batch(
                [(ids, pages) for _, _, ids, pages in admitted],
                kids=[rid for _, rid, _, _ in admitted])
        reqs = []
        for _, rid, ids, pages in admitted:
            start = self._cache_meta[rid][0]
            reqs.append((ids[start:], start, pages))
        firsts = self.d.prefill_suffix_batch(
            reqs, kids=[rid for _, rid, _, _ in admitted])
        # publish newly computed full blocks: content-addressable from
        # now on (the cache takes one reference-managed view; the slot
        # keeps holding the page until retirement decrefs it). A
        # same-batch duplicate whose insert is refused keeps its copy
        # private — two requests never alias a page they both wrote —
        # and publishing STOPS at the first refusal: a deeper block
        # would chain under a parent this request neither mounted nor
        # inserted, breaking the every-ancestor-referenced invariant
        # the eviction cascade relies on (a parked parent could then
        # cascade into a still-referenced child).
        for slot, rid, ids, pages in admitted:
            start, keys, n_hit = self._cache_meta.pop(rid)
            for b in range(n_hit, len(keys)):
                parent = keys[b - 1] if b else None
                if not self.cache.insert(keys[b], pages[b],
                                         parent=parent):
                    break
                self._slot_shared[slot].add(pages[b])
        return firsts

    def _gather_admissions(self):
        if self.cache is not None:
            return self._gather_admissions_cached()
        admitted = []
        for slot in range(self.d.max_batch):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            rid, ids = self._queue[0]
            need = self._pages_for(len(ids) + self.max_new)
            if need > len(self._free) or need > self.d.max_pages:
                break                        # head-of-line: wait for pages
            self._queue.pop(0)
            pages = [self._free.pop() for _ in range(need)]
            self._slot_req[slot] = rid
            self._slot_pages[slot] = pages
            admitted.append((slot, rid, ids, pages))
        return admitted

    def _gather_admissions_cached(self):
        """Prefix-cache admission: hash the prompt's full blocks, mount
        the longest cached run into the page-table row (incref), evict
        parked refcount-0 pages if the free list can't cover the
        uncached remainder, and record (start, keys, n_hit) for the
        chunked prefill. A FULL-prompt hit still needs the last
        position's logits: its one re-consumed token would write into
        the final mounted page, so that page is copy-on-write'd to a
        private copy first (the recomputed KV bytes are identical — the
        chunked prefill is deterministic and position-local — so the
        copy diverges only once decode appends past the prompt)."""
        admitted = []
        ps = self.d.page_size
        tok_bytes = self.d.kv_page_bytes // ps
        for slot in range(self.d.max_batch):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            rid, ids = self._queue[0]
            L = len(ids)
            total = self._pages_for(L + self.max_new)
            if total > self.d.max_pages:
                break
            keys = self.cache.block_keys(ids)
            hits = self.cache.match(keys)
            # pick the largest mounted span the pool can cover: mounted
            # hit pages are excluded from eviction, so on a tight pool
            # a full-span mount can be self-blocking (the parked hit
            # pages ARE the reclaimable ones — e.g. a full-prompt hit
            # whose CoW page cannot be allocated). Degrading the span
            # turns the excess hits back into evictable parked pages,
            # so any request the cache-less engine could admit
            # eventually admits here too (n_hit=0 needs exactly the
            # cache-less page count).
            chosen = None
            for n_hit in range(len(hits), -1, -1):
                start = n_hit * ps
                # full hit: re-consume the last token (n_hit > 0 guard:
                # an EMPTY prompt trivially satisfies start >= L with
                # nothing mounted — it prefills like any other miss)
                cow = n_hit > 0 and start >= L
                if cow:
                    start = L - 1
                need_new = total - n_hit + (1 if cow else 0)
                if need_new <= len(self._free) + self.cache.evictable(
                        exclude=keys[:n_hit]):
                    chosen = (n_hit, start, cow, need_new)
                    break
            if chosen is None:
                break                    # head-of-line: wait for pages
            n_hit, start, cow, need_new = chosen
            hits = hits[:n_hit]
            self._queue.pop(0)
            self.cache.mount(keys[:n_hit])
            if len(self._free) < need_new:
                freed = self.cache.evict(need_new - len(self._free))
                self.stats.prefix_evictions += len(freed)
                self._free.extend(freed)
            privates = [self._free.pop() for _ in range(need_new)]
            shared = list(hits)
            if cow:
                dst = privates.pop()
                self.d.copy_page(shared[-1], dst)
                self.cache.release_page(shared[-1])
                self.stats.prefix_cow += 1
                shared_set = set(shared[:-1])
                shared[-1] = dst
            else:
                shared_set = set(shared)
            pages = shared + privates    # block order: prefix first
            self._slot_req[slot] = rid
            self._slot_pages[slot] = pages
            self._slot_shared[slot] = shared_set
            self._cache_meta[rid] = (start, keys, n_hit)
            self.stats.prefix_hits += n_hit
            self.stats.prefix_misses += len(keys) - n_hit
            self.stats.prefix_tokens_saved += start
            self.stats.prefix_bytes_saved += start * tok_bytes
            admitted.append((slot, rid, ids, pages))
        return admitted

    def _extra_prefill(self, admitted):
        pass                                 # SpeculativeEngine: draft

    def _after_admit(self, slot, prompt_len):
        pass                                 # SpeculativeEngine: _dlens

    def _retire(self, slot):
        shared = self._slot_shared[slot]
        for pid in self._slot_pages[slot]:
            if pid in shared:
                # drop this request's reference only: the cache still
                # owns the page (parked at refcount 0, reclaimed by
                # eviction alone) — so a shared page is freed exactly
                # once, by whoever finally unmaps it
                self.cache.release_page(pid)
            else:
                self._free.append(pid)
        self._slot_shared[slot] = set()
        self._slot_req[slot] = None
        self._slot_pages[slot] = []
        self._lens[slot] = 0
        self._tokens[slot] = 0
        self._table_cache = None
        self.stats.completed += 1

    def page_ledger(self):
        """Auditable snapshot of page ownership: every allocatable page
        sits in exactly one of {free list, slot-held}, cache refcounts
        equal the number of slots mounting each shared page, and parked
        (refcount-0) cached pages are held by nobody. The
        MEM-PAGE-REFCOUNT lint (`analysis.memory.audit_page_ledger`)
        consumes this — double-frees, leaks and refcount drift all
        surface as findings."""
        return {
            "num_pages": self.d.num_pages,
            "scratch": self.d.num_pages - 1,
            "free": list(self._free),
            "slots": {s: list(p)
                      for s, p in enumerate(self._slot_pages) if p},
            "shared": {s: sorted(sh)
                       for s, sh in enumerate(self._slot_shared) if sh},
            "cache": self.cache.ledger() if self.cache else {},
        }

    def audit_pages(self):
        """Run the MEM-PAGE-REFCOUNT audit over the live ledger; returns
        the findings (empty = every page owned exactly once)."""
        from ..analysis.memory import audit_page_ledger
        return audit_page_ledger(self.page_ledger())

    def _table(self, pages_per_slot, decoder):
        """Page table with inactive/unused entries routed to the reserved
        scratch page (their masked, discarded KV writes must never land
        in allocatable pages)."""
        t = np.full((decoder.max_batch, decoder.max_pages),
                    decoder.num_pages - 1, np.int32)
        for s, pg in enumerate(pages_per_slot):
            if pg:
                t[s, :len(pg)] = pg
        return t

    def step(self):
        """Admit + one decode tick. Returns number of active slots."""
        self._admit()
        active = [s for s in range(self.d.max_batch)
                  if self._slot_req[s] is not None]
        if not active:
            return 0
        if self._table_cache is None:        # slots changed since last tick
            self._table_cache = self._table(self._slot_pages, self.d)
        nxt = np.asarray(self.d.decode(self._tokens, self._lens,
                                       self._table_cache,
                                       kids=self._kids))
        self.steps += 1
        self.stats.ticks += 1
        self.stats.decode_syncs += 1
        self.stats.occupancy.append(len(active) / self.d.max_batch)
        for s in active:
            rid = self._slot_req[s]
            tok = int(nxt[s])
            self._outputs[rid].append(tok)
            self.stats.tokens += 1
            self._lens[s] += 1
            self._tokens[s] = tok
            done = (self.eos is not None and tok == self.eos) or \
                len(self._outputs[rid]) >= self.max_new
            if done:
                self._retire(s)
        return len(active)

    def run(self, step_times=None):
        """Drain the queue; returns {request_id: generated token list}.
        `step_times`, if given, receives wall seconds per host sync —
        per decode tick on the per-tick path (k_max=1), per K-tick
        horizon on the multi-step path (use `self.stats` for per-token
        percentiles either way)."""
        if self.k_max <= 1:
            return self._run_per_tick(step_times)
        return self._run_multi(step_times)

    def _run_per_tick(self, step_times=None):
        """Legacy loop: one compiled tick, one host sync per token."""
        while self._queue or any(r is not None for r in self._slot_req):
            t0 = time.perf_counter()
            before = self.stats.tokens
            before_p = self.stats.prefill_syncs
            self.step()
            dt = time.perf_counter() - t0
            if step_times is not None:
                step_times.append(dt)
            n = self.stats.tokens - before
            # token_time_s is the STEADY-STATE decode latency: a sync
            # that contained a prefill is dominated by it (orders of
            # magnitude more work than a tick) and would turn p99 into
            # a prefill number — keep it out of the percentiles
            if n and self.stats.prefill_syncs == before_p:
                self.stats.token_time_s.extend([dt / n] * n)
        return dict(self._outputs)

    def _budget_left(self, slot):
        """Tokens this slot may still emit (host view, excludes ticks
        already dispatched but not yet processed)."""
        return self.max_new - len(self._outputs[self._slot_req[slot]])

    def _horizon(self, slots, inflight):
        """Largest power-of-two tick count ≤ k_max that fits every
        dispatchable slot's remaining budget (powers of two bound the
        decode_multi compile count, like the prefill buckets)."""
        rem = min(self._budget_left(s) - inflight[s] for s in slots)
        k = 1
        while k * 2 <= min(rem, self.k_max):
            k *= 2
        return k

    def _merge_carry(self, carry, admitted):
        """Device-resident decode state for the next horizon. The carry
        never round-trips through the host: newly admitted slots are
        scattered into the in-flight arrays with device ops."""
        S = self.d.max_batch
        if carry is None:
            done = np.array([r is None for r in self._slot_req])
            rem = np.array([self._budget_left(s) if self._slot_req[s]
                            is not None else 0 for s in range(S)],
                           np.int32)
            return (jnp.asarray(self._tokens), jnp.asarray(self._lens),
                    jnp.asarray(done), jnp.asarray(rem))
        if not admitted:
            return carry
        tokens, lens, done, rem = carry
        idx = jnp.asarray(admitted, jnp.int32)
        tokens = tokens.at[idx].set(jnp.asarray(self._tokens[admitted]))
        lens = lens.at[idx].set(jnp.asarray(self._lens[admitted]))
        done = done.at[idx].set(False)
        rem = rem.at[idx].set(jnp.asarray(
            [self._budget_left(s) for s in admitted], jnp.int32))
        return tokens, lens, done, rem

    def _process_block(self, meta, inflight, step_times,
                       prefilled_since=False):
        """Fetch + bookkeep one finished horizon. Called AFTER the next
        horizon is dispatched, so the device→host wait overlaps it."""
        block_d, done_before_d, k, rids, t0, had_prefill = meta
        block = np.asarray(block_d)
        done_before = np.asarray(done_before_d)
        self.stats.decode_syncs += 1
        emitted = 0
        for s, rid in rids.items():
            inflight[s] = max(0, inflight[s] - k)
            if self._slot_req[s] != rid:
                continue
            for j in range(k):
                if done_before[j, s]:
                    break
                tok = int(block[j, s])
                self._outputs[rid].append(tok)
                self.stats.tokens += 1
                emitted += 1
                self._lens[s] += 1
                self._tokens[s] = tok
                if (self.eos is not None and tok == self.eos) or \
                        len(self._outputs[rid]) >= self.max_new:
                    self._retire(s)
                    break
        dt = time.perf_counter() - t0
        if step_times is not None:
            step_times.append(dt)
        # steady-state decode latency only: the block's dt window spans
        # its dispatch iteration AND the next iteration up to this
        # call, so a prefill in either (had_prefill at dispatch,
        # prefilled_since at processing) would make p99 a prefill
        # number — exclude such blocks from the percentiles (see
        # _run_per_tick)
        if emitted and not had_prefill and not prefilled_since:
            self.stats.token_time_s.extend([dt / emitted] * emitted)

    def _run_multi(self, step_times=None):
        """Horizon-scheduled drain: dispatch a K-tick device-resident
        block, then process the PREVIOUS block while the new one runs.
        Retirement is one horizon delayed — a slot that finishes inside
        block N stays frozen on device through block N+1 (done mask
        carried on device; its K/V writes route to the scratch page)
        and its pages are freed exactly once, when block N's results
        land on the host. Prefix-cache interplay inherits the same
        discipline: a retiring slot's shared pages are DECREF'd at
        block-processing time (parked, not reused), and eviction
        reclaims them only at a later admission — whose prefill writes
        are device-ordered after every in-flight horizon, so a fused
        horizon can never read a page that was re-written under it."""
        S = self.d.max_batch
        pending = None               # the in-flight horizon's meta
        carry = None                 # device (tokens, lens, done, rem)
        inflight = [0] * S           # dispatched-not-yet-processed ticks
        while (self._queue or pending is not None
               or any(r is not None for r in self._slot_req)):
            t0 = time.perf_counter()
            before_p = self.stats.prefill_syncs
            admitted = self._admit()
            # a prefill ran iff the sync counter moved — NOT iff any
            # request entered decode: a round whose every admission
            # finishes AT prefill (EOS on the first token) returns an
            # empty `admitted` but still paid a prefill forward, which
            # must stay out of the steady-state token percentiles
            # (same delta discipline as _run_per_tick)
            prefilled = self.stats.prefill_syncs != before_p
            for s in admitted:
                # a freshly admitted slot starts from a clean device
                # carry (_merge_carry), so ticks still in flight for
                # the slot's PREVIOUS request must not gate its
                # dispatch. Unreachable today (a fresh budget
                # max_new-1 always exceeds the stale count, which is
                # bounded by the retired request's remaining budget
                # minus the processed block), but reset defensively:
                # the rid check skips the old block's tokens and the
                # max(0, ...) clamp absorbs the double subtraction.
                inflight[s] = 0
            carry = self._merge_carry(carry, admitted)
            # invariant: for a live non-admitted slot, the device-side
            # `remaining` equals budget_left - inflight exactly (both
            # count init budget minus dispatched ticks), so a slot
            # excluded here is always already frozen on device — its
            # ticks in another slot's block are filler, never lost
            # tokens
            disp = [s for s in range(S) if self._slot_req[s] is not None
                    and self._budget_left(s) - inflight[s] > 0]
            meta = None
            if disp:
                k = self._horizon(disp, inflight)
                if self._table_cache is None:
                    self._table_cache = self._table(self._slot_pages,
                                                    self.d)
                tokens_d, lens_d, done_d, rem_d = carry
                out = self.d.decode_multi(
                    tokens_d, lens_d, self._table_cache, k,
                    kids=self._kids, done=done_d, remaining=rem_d,
                    eos=self.eos)
                carry = (out.tokens, out.lens, out.done, out.remaining)
                self.steps += k
                self.stats.ticks += k
                self.stats.occupancy.append(len(disp) / S)
                for s in disp:
                    inflight[s] += k
                meta = (out.tokens_block, out.done_before, k,
                        {s: self._slot_req[s] for s in disp}, t0,
                        prefilled)
            if pending is not None:
                self._process_block(pending, inflight, step_times,
                                    prefilled_since=prefilled)
            pending = meta
        return dict(self._outputs)


class SpeculativeEngine(ContinuousBatchingEngine):
    """Speculative decoding over the paged engine: a small DRAFT model
    proposes k tokens with k cheap decode ticks; the TARGET model scores
    all of them in ONE verify forward. Greedy configs accept the longest
    matching prefix (+ the target's token at the first mismatch) —
    output is EXACTLY the target's greedy decode; sampled configs (same
    temperature/top-k/top-p on both decoders) use rejection-sampling
    acceptance (_spec_accept), so emitted tokens are distributed exactly
    as target-only sampling. Either way: up to k-times fewer target
    forwards. Paged KV makes rollback free: `lens` is the source of
    truth, rejected positions are simply overwritten.

    Acceptance is capped at k-1 drafts so the draft cache (which holds
    proposals d1..d_{k-1}) never falls behind; when all k drafts match,
    the capped path still emits exactly d1..dk.
    """

    def __init__(self, decoder, draft_decoder, eos_token_id=None,
                 max_new_tokens=64, k=4):
        if decoder.sampling != draft_decoder.sampling:
            raise ValueError(
                "speculative decoding needs the SAME sampling config on "
                "target and draft (acceptance compares their masked "
                f"distributions): {decoder.sampling} vs "
                f"{draft_decoder.sampling}")
        if draft_decoder.max_batch != decoder.max_batch or \
                draft_decoder.page_size != decoder.page_size:
            raise ValueError("draft/target max_batch and page_size must match")
        # k_max=1: the verify cadence IS this engine's horizon — each
        # step() already moves a k-token window; the draft's ticks are
        # device-resident via decode_multi below. (No prefix_cache:
        # verify windows WRITE up to k positions past the accepted
        # length, which would dirty mounted shared pages — chunked
        # admission for the twin pools is an open item.)
        super().__init__(decoder, eos_token_id, max_new_tokens, k_max=1)
        self.draft = draft_decoder
        self.k = int(k)
        self._draft_free = list(range(draft_decoder.num_pages - 2, -1, -1))
        self._draft_pages = [[] for _ in range(decoder.max_batch)]
        self._dlens = np.zeros(decoder.max_batch, np.int32)
        self.target_calls = 0

    def submit(self, prompt_ids):
        """Same as the base, with a +k margin: a verify window can write
        up to k positions past the final accepted length."""
        ids = np.asarray(prompt_ids._value if isinstance(prompt_ids, Tensor)
                         else prompt_ids).reshape(-1)
        if len(ids) == 0:
            raise ValueError(
                "prompt must contain at least one token (prefill "
                "samples the first generated token after the prompt's "
                "last position — an empty prompt has none)")
        total = len(ids) + self.max_new + self.k
        need = self._pages_for(total)
        limit = min(self.d.max_pages, self.draft.max_pages,
                    self.d.num_pages - 1, self.draft.num_pages - 1)
        if need > limit:
            raise ValueError(
                f"request needs {need} pages (prompt {len(ids)} + max_new "
                f"{self.max_new} + speculation margin {self.k}) but the "
                f"pools allow {limit}")
        if total > min(self.d.cfg.max_seq_len, self.draft.cfg.max_seq_len):
            raise ValueError(
                f"prompt {len(ids)} + max_new {self.max_new} + margin "
                f"{self.k} exceeds max_seq_len "
                f"{min(self.d.cfg.max_seq_len, self.draft.cfg.max_seq_len)}")
        return self._register_request([int(t) for t in ids])

    def _gather_admissions(self):
        admitted = []
        for slot in range(self.d.max_batch):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            rid, ids = self._queue[0]
            # +k margin: a verify window may write up to k positions past
            # the final accepted length
            need = self._pages_for(len(ids) + self.max_new + self.k)
            if need > len(self._free) or need > len(self._draft_free) \
                    or need > self.d.max_pages \
                    or need > self.draft.max_pages:
                break
            self._queue.pop(0)
            pages = [self._free.pop() for _ in range(need)]
            dpages = [self._draft_free.pop() for _ in range(need)]
            self._slot_req[slot] = rid
            self._slot_pages[slot] = pages
            self._draft_pages[slot] = dpages
            admitted.append((slot, rid, ids, pages))
        return admitted

    def _extra_prefill(self, admitted):
        self.draft.prefill_batch(           # draft's guesses discarded
            [(ids, self._draft_pages[slot])
             for slot, _, ids, _ in admitted],
            kids=[rid for _, rid, _, _ in admitted])

    def _after_admit(self, slot, prompt_len):
        self._dlens[slot] = prompt_len

    def _retire(self, slot):
        self._draft_free.extend(self._draft_pages[slot])
        self._draft_pages[slot] = []
        self._dlens[slot] = 0
        super()._retire(slot)

    def step(self):
        self._admit()
        active = [s for s in range(self.d.max_batch)
                  if self._slot_req[s] is not None]
        if not active:
            return 0
        k = self.k
        if self._table_cache is None:        # slots changed since last tick
            self._table_cache = (self._table(self._slot_pages, self.d),
                                 self._table(self._draft_pages, self.draft))
        ttable, dtable = self._table_cache

        sampled = self.d.sampling is not None

        # draft proposes k tokens: K DEVICE-RESIDENT ticks in ONE
        # compiled loop (decode_multi) — the proposal chain feeds back
        # on device, so the k cheap ticks cost one dispatch + one fetch
        # instead of k host round-trips
        qrows = None
        out = self.draft.decode_multi(self._tokens, self._dlens, dtable,
                                      k, kids=self._kids,
                                      return_logits=sampled)
        proposals = np.asarray(out.tokens_block).T.astype(np.int32)
        if sampled and k > 1:
            # the k-th draft's distribution is never judged (acceptance
            # is capped at k-1): skip its transfer
            qp = self.draft._probs_of(out.logits_block[:k - 1])
            qrows = np.moveaxis(qp, 0, 1)          # [S, k-1, V]
        self.stats.ticks += k
        self.stats.decode_syncs += 1

        # target verifies [cur, d1..dk] in one forward
        window = np.concatenate(
            [self._tokens[:, None], proposals[:, :k]], axis=1)  # [S, k+1]
        if sampled:
            tgt, prows = self.d.verify(window, self._lens, ttable,
                                       return_probs=True)
        else:
            tgt = self.d.verify(window, self._lens, ttable)     # [S, k+1]
        self.target_calls += 1
        self.steps += 1
        self.stats.ticks += 1
        self.stats.decode_syncs += 1
        self.stats.occupancy.append(len(active) / self.d.max_batch)

        for s in active:
            rid = self._slot_req[s]
            if sampled:
                rng = np.random.default_rng(
                    (self.d.seed * 1000003 + self.target_calls) * 4093 + s)
                a, tok = _spec_accept(
                    prows[s, :k],
                    qrows[s] if qrows is not None else
                    np.zeros((0, prows.shape[-1])),
                    proposals[s, :k - 1], rng)
                emitted = [int(t) for t in proposals[s, :a]] + [tok]
            else:
                a = 0
                while a < k - 1 and proposals[s, a] == tgt[s, a]:
                    a += 1
                emitted = [int(t) for t in proposals[s, :a]] + \
                    [int(tgt[s, a])]
            L = int(self._lens[s])
            self._lens[s] = L + a + 1
            self._dlens[s] = L + a + 1
            self._tokens[s] = emitted[-1]
            done = False
            for t in emitted:
                self._outputs[rid].append(t)
                self.stats.tokens += 1
                if (self.eos is not None and t == self.eos) or \
                        len(self._outputs[rid]) >= self.max_new:
                    done = True      # tokens speculated past the stop
                    break            # point are simply never appended
            if done:
                self._retire(s)
        return len(active)
