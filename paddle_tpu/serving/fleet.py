"""Fleet-scale serving: a shared host KV tier and a prefix-affinity
router over N engine replicas.

One engine process is not a service. Production traffic lands on a
HOST running several engine replicas (the multi-replica granularity
the Gemma-on-TPU serving comparison is framed at, PAPERS.md arxiv
2605.25645), and the single-process stack built in PRs 8-15 leaves
exactly two things on the table at that scale: every replica warms its
own host tier from scratch (N copies of one warm set), and requests
land on replicas blind to where their prefix is already cached. This
module closes both, and it can do so CHEAPLY because of an invariant
the repo has been defending since PR 8 and statically proves since
PR 18 (the Determinism Doctor): a KV page's bytes are a pure function
of (request, position) — schedule-, batch-, slot- and PROCESS-
independent. A KV page is therefore a wire format for free:

- **`SharedHostKVTier`** — the PR 13 `HostKVTier` payloads re-homed
  onto a file-backed store (shm-friendly: point `path` at /dev/shm)
  keyed by the same chain keys + a `cache_fingerprint` digest, one
  entry per spilled page in the exact `PrefixCache.save/load` byte
  format (`pack_array`/`unpack_array`: raw uint8 + JSON shape/dtype
  meta). One warm set serves every replica on the host; a preempted
  or killed replica's spilled working set warms its siblings and its
  own respawn (kill/respawn warm-start, test-pinned). Mutations hold
  the in-process `threading.RLock` AND an `fcntl.flock` on the store
  (in that order, always), index updates publish via atomic
  `os.replace` — the lock discipline `analysis/threads.py` certifies
  (SERVE-UNLOCKED-SHARED / SERVE-LOCK-ORDER). Restores out of the
  shared store pay a host-RAM read leg BEFORE the PCIe DMA, so
  `shared = True` routes the engine's pricing through
  `cost_model.kv_restore_s(shared=True)` (`ChipSpec.host_read_bw` —
  the column that keeps `restore_beats_recompute` honest
  cross-process).
- **`FleetRouter`** — a front end over N `TenantEngine` replicas that
  routes by PREFIX AFFINITY: the prompt's first chain blocks hash to
  a home replica (the prefix cache's own content-addressed keys ARE
  the routing key — no second hash scheme to keep consistent), with
  an SLO-aware least-loaded escape (a latency-class request facing a
  deep affinity backlog reroutes to the least-loaded replica) and a
  least-loaded fallback for prompts too short to key. Admission and
  retirement ride the existing `run(on_sync=)` hook: each replica
  drains in its own thread, and churn submitted mid-run (the
  callback may call `router.submit`) is parked on the router and
  drained into the owning replica FROM ITS OWN THREAD at its next
  sync — engine internals are only ever touched by their own thread.
- **Byte identity across fleet sizes.** The router owns request
  identity: one GLOBAL rid counter, assigned in submission order and
  stamped into the owning engine (`_next_id`) right before its
  `submit`. Sampling keys are (seed, rid, position) and KV bytes are
  (request, position)-pure, so an N-replica fleet emits streams
  byte-identical to the 1-replica twin — routing, thread
  interleaving and shared-tier churn included (fuzz-pinned in
  tests/test_fleet_serving.py, 3 seeds, sampled + EOS + prefix cache
  + int8 pools).
- **Fleet observability.** `ServeStats.merge` (replica-ordered, the
  `(engine, replica, engine_id)` contract), a fleet-wide
  `tenancy_summary` pooled through the SAME `summarize_tenancy` math
  as the single engine, and `export_trace` → ONE Perfetto timeline
  with distinct pids per (replica, tenant)
  (`export_chrome_trace(recorders={"replica0": ...})`).

Scope: ONE HOST. The store is a file/shm path and the lock is an
fcntl flock — both host-local by design (the tier's payloads are
priced at host-RAM-read + PCIe, not DCN). Cross-host KV movement and
disaggregated prefill/decode are the next ROADMAP rung and ride this
module's machinery unchanged (the store path just stops being local).
"""
import hashlib
import json
import os
import threading
import time

import numpy as np

from .kv_tier import DEFAULT_CAPACITY_BYTES, _TierEntry, payload_bytes
from .prefix_cache import pack_array, unpack_array
from .stats import ServeStats
from .tenancy import SLO_LATENCY, SLO_THROUGHPUT, TenantStats, \
    summarize_tenancy

try:
    import fcntl
except ImportError:          # non-POSIX: in-process locking only
    fcntl = None

__all__ = ["SharedHostKVTier", "FleetRouter"]


class SharedHostKVTier:
    """Cross-process host KV tier: `HostKVTier`'s contract (the duck
    type the engine and `PrefixCache.save` consume) over a file-backed
    store shared by every replica on the host.

    Layout under `path` (point it at /dev/shm for an shm-backed
    store): `tier.json` (fingerprint digest + nominal capacity),
    `index.json` (recency sequence + per-entry bytes — the LRU state,
    published by atomic `os.replace` so unlocked readers see a
    complete old or new index, never a torn one), `lock` (the flock
    file), and `entries/<chain key hex>.npz` — one spilled page per
    file in the exact `PrefixCache.save/load` byte format
    (`pack_array` raw-uint8 leaves + JSON shape/dtype meta), so a
    restored payload is bit-identical to the spilled one and the
    byte-identical-stream invariant survives the process boundary.

    Lock discipline (what `analysis/threads.py` certifies): every
    mutation takes the in-process `self._lock` (RLock) FIRST, then
    the cross-process flock, releases in reverse — one global order,
    no ABBA. Queries take `self._lock` only (the atomic index publish
    makes unlocked file reads safe; the RLock still serializes the
    in-process stat cache).

    `fingerprint` (bytes, or a decoder exposing `cache_fingerprint`)
    pins the store to one model/pool config: a mismatched attach
    REFUSES, exactly like `PrefixCache.load`. Chain keys are already
    fingerprint-salted so cross-model entries could never alias — the
    check turns silent 0-hit sharing into a loud error.

    Device-twin backrefs (`note_mounted`) are deliberately NOT kept:
    a shared entry may have twins in MANY replicas' pools at once, so
    a single backref is ill-defined — `ledger()` rows carry
    `"page": None` and the MEM-PAGE-REFCOUNT audit's twin cross-check
    simply has nothing to flag (the per-process `HostKVTier` keeps
    that audit). `capacity_bytes=0` refuses every put — the same
    tier-off twin semantics as `HostKVTier`."""

    # restores pay host-RAM read + PCIe: the engine reads this into
    # restore_beats_recompute(shared=True) / kv_restore_s(shared=True)
    shared = True

    def __init__(self, path, capacity_bytes=DEFAULT_CAPACITY_BYTES,
                 fingerprint=None):
        self.path = os.path.abspath(path)
        self.capacity_bytes = int(capacity_bytes)
        self.puts = 0            # accepted spills (this attach)
        self.evictions = 0       # entries this attach LRU'd out
        self._lock = threading.RLock()
        self._stat_cache = None  # (index stat signature, parsed index)
        self._entries_dir = os.path.join(self.path, "entries")
        self._index_path = os.path.join(self.path, "index.json")
        os.makedirs(self._entries_dir, exist_ok=True)
        self._lock_fd = os.open(os.path.join(self.path, "lock"),
                                os.O_RDWR | os.O_CREAT, 0o644)
        fp_hex = None
        if fingerprint is not None:
            fp = fingerprint.cache_fingerprint() \
                if hasattr(fingerprint, "cache_fingerprint") \
                else bytes(fingerprint)
            fp_hex = hashlib.blake2b(fp, digest_size=16).hexdigest()
        with self._lock:
            self._flock()
            try:
                meta_path = os.path.join(self.path, "tier.json")
                if os.path.exists(meta_path):
                    with open(meta_path) as f:
                        meta = json.load(f)
                    want = meta.get("fingerprint")
                    if fp_hex is not None and want is not None and \
                            want != fp_hex:
                        raise ValueError(
                            f"shared KV tier at {self.path!r} was "
                            f"created for fingerprint {want} but this "
                            f"attach is {fp_hex} — different weights/"
                            "arch/pool config would share garbage KV; "
                            "use a different path or rebuild the "
                            "matching decoder")
                else:
                    self._write_json(meta_path, {
                        "fingerprint": fp_hex,
                        "capacity_bytes": self.capacity_bytes})
                if not os.path.exists(self._index_path):
                    self._write_json(self._index_path,
                                     {"seq": 0, "entries": {}})
            finally:
                self._funlock()

    def close(self):
        fd, self._lock_fd = self._lock_fd, None
        if fd is not None:
            os.close(fd)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # --------------------------------------------------- lock + files

    def _flock(self):
        """Cross-process leg. Callers already hold `self._lock`, so
        one fd per process is safe: flock is per-fd, and the RLock
        serializes this process's threads onto it."""
        if fcntl is not None and self._lock_fd is not None:
            fcntl.flock(self._lock_fd, fcntl.LOCK_EX)

    def _funlock(self):
        if fcntl is not None and self._lock_fd is not None:
            fcntl.flock(self._lock_fd, fcntl.LOCK_UN)

    def _write_json(self, path, obj):
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)

    def _load_index(self):
        """Fresh parse, for mutators (caller holds lock + flock)."""
        try:
            with open(self._index_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"seq": 0, "entries": {}}

    def _publish_index(self, idx):
        self._write_json(self._index_path, idx)
        self._stat_cache = None

    def _index(self):
        """Parsed index for queries (caller holds `self._lock`),
        cached on the file's stat signature — hot-path membership
        checks (`_tier_plan` walks the chain per admission) re-parse
        only when another process actually published."""
        try:
            st = os.stat(self._index_path)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            return {"seq": 0, "entries": {}}
        if self._stat_cache is not None and self._stat_cache[0] == sig:
            return self._stat_cache[1]
        idx = self._load_index()
        self._stat_cache = (sig, idx)
        return idx

    def _entry_path(self, hexkey):
        return os.path.join(self._entries_dir, hexkey + ".npz")

    def _write_entry(self, hexkey, arrays):
        tmp = os.path.join(self._entries_dir,
                           f".{hexkey}.{os.getpid()}.tmp.npz")
        np.savez(tmp, **arrays)
        os.replace(tmp, self._entry_path(hexkey))

    def _read_entry(self, hexkey):
        with np.load(self._entry_path(hexkey)) as data:
            meta = json.loads(bytes(data["__meta__"]).decode("utf-8"))
            return {part: tuple(
                unpack_array(data[f"{part}.{i}"],
                             meta["arrays"][f"{part}.{i}"])
                for i in range(meta["leaves"][part]))
                for part in ("k", "v")}

    @staticmethod
    def _encode(payload, nbytes):
        """One spilled page -> npz arrays in the PrefixCache.save
        byte format: raw-uint8 leaves + a JSON meta array carrying
        shape/dtype (npz can't serialize bf16 leaves directly)."""
        arrays, ameta, leaves = {}, {}, {}
        for part in ("k", "v"):
            leaves[part] = len(payload[part])
            for i, leaf in enumerate(payload[part]):
                arrays[f"{part}.{i}"], ameta[f"{part}.{i}"] = \
                    pack_array(leaf)
        arrays["__meta__"] = np.frombuffer(
            json.dumps({"arrays": ameta, "leaves": leaves,
                        "nbytes": int(nbytes)}).encode("utf-8"),
            np.uint8)
        return arrays

    # ------------------------------------------------------------ query

    def __contains__(self, key):
        with self._lock:
            return key.hex() in self._index()["entries"]

    def __len__(self):
        with self._lock:
            return len(self._index()["entries"])

    @property
    def n_entries(self):
        return len(self)

    @property
    def bytes_used(self):
        with self._lock:
            return sum(int(e["bytes"]) for e in
                       self._index()["entries"].values())

    def entry_bytes(self, key):
        with self._lock:
            return int(self._index()["entries"][key.hex()]["bytes"])

    def items(self):
        """(key, entry-with-.payload) pairs in LRU order (oldest
        first) — the persistence walk (`PrefixCache.save`) reads
        `.payload`, so this READS every entry file; it is the
        snapshot path, not a hot path."""
        with self._lock:
            self._flock()
            try:
                idx = self._index()
                out = []
                for hexkey, e in sorted(idx["entries"].items(),
                                        key=lambda kv: kv[1]["seq"]):
                    key = bytes.fromhex(hexkey)
                    out.append((key, _TierEntry(
                        key, self._read_entry(hexkey),
                        int(e["bytes"]))))
                return out
            finally:
                self._funlock()

    # ----------------------------------------------------------- insert

    def put(self, key, payload, page=None):
        """Spill one page's payload under `key`; False when the
        capacity bound refuses it (entry bigger than the whole tier,
        or capacity 0 — the tier-off twin). Evicts LRU entries (never
        the one being put) to fit; a re-put refreshes payload +
        recency. The entry file lands BEFORE the index row: a crash
        between the two leaves an orphan file, never a dangling
        index row."""
        nbytes = int(payload_bytes(payload))
        if nbytes > self.capacity_bytes:
            return False
        arrays = self._encode(payload, nbytes)
        hexkey = key.hex()
        with self._lock:
            self._flock()
            try:
                idx = self._load_index()
                entries = idx["entries"]
                entries.pop(hexkey, None)
                self._write_entry(hexkey, arrays)
                entries[hexkey] = {"bytes": nbytes,
                                   "seq": int(idx["seq"])}
                idx["seq"] = int(idx["seq"]) + 1
                used = sum(int(e["bytes"]) for e in entries.values())
                while used > self.capacity_bytes and len(entries) > 1:
                    victim = min(
                        (h for h in entries if h != hexkey),
                        key=lambda h: entries[h]["seq"])
                    used -= int(entries[victim]["bytes"])
                    del entries[victim]
                    try:
                        os.remove(self._entry_path(victim))
                    except OSError:
                        pass
                    self.evictions += 1
                self._publish_index(idx)
            finally:
                self._funlock()
            self.puts += 1
        return True

    def get(self, key):
        """Payload of `key` (touches recency — the cross-process LRU
        sequence bumps under the flock). KeyError when absent:
        callers gate on `key in tier`, and the engine's plan-time
        hold tolerates a sibling evicting between the two."""
        hexkey = key.hex()
        with self._lock:
            self._flock()
            try:
                idx = self._load_index()
                e = idx["entries"].get(hexkey)
                if e is None:
                    raise KeyError(key)
                payload = self._read_entry(hexkey)
                e["seq"] = int(idx["seq"])
                idx["seq"] = int(idx["seq"]) + 1
                self._publish_index(idx)
            finally:
                self._funlock()
        return payload

    def touch(self, key):
        """Refresh recency without reading (the recompute-refresh
        path); absent keys are a no-op."""
        hexkey = key.hex()
        with self._lock:
            self._flock()
            try:
                idx = self._load_index()
                e = idx["entries"].get(hexkey)
                if e is not None:
                    e["seq"] = int(idx["seq"])
                    idx["seq"] = int(idx["seq"]) + 1
                    self._publish_index(idx)
            finally:
                self._funlock()

    # ------------------------------------------- device-twin bookkeeping

    def note_mounted(self, key, page):
        """No-op by design: a shared entry may be mounted in many
        replicas' pools at once, so the single-backref audit the
        per-process tier supports is ill-defined here. Recency was
        already refreshed by the plan-time `get`."""

    def note_unmounted(self, key):
        """The local device twin was evicted; the host payload is
        still the exact write-time bytes — refresh recency (the entry
        is hot again), matching `HostKVTier` semantics."""
        self.touch(key)

    # ------------------------------------------------------------ ledger

    def ledger(self):
        """{key hex: {"bytes": n, "page": None}} in LRU order — the
        host rows of `page_ledger()`. `page` is always None (no
        cross-replica backref; see class docstring)."""
        with self._lock:
            idx = self._index()
            return {h: {"bytes": int(e["bytes"]), "page": None}
                    for h, e in sorted(idx["entries"].items(),
                                       key=lambda kv: kv[1]["seq"])}


class FleetRouter:
    """Prefix-affinity front end over N engine replicas (normally
    `TenantEngine`s sharing one `SharedHostKVTier`).

    Routing: the prompt's first `affinity_blocks` chain blocks hash
    to a home replica — the prefix cache's content-addressed keys ARE
    the routing key, so two requests sharing a template land where
    that template's pages already live. A latency-SLO request facing
    an affinity backlog `max_batch`+ deeper than the least-loaded
    replica reroutes there (SLO class + least-loaded tiebreak);
    prompts too short to key (< one full block) go least-loaded.
    Routing never affects stream BYTES — sampling keys are (seed,
    rid, position) and the router owns rid: one global counter
    assigned in submission order, stamped into the owning engine
    right before its `submit`, so an N-replica fleet is
    byte-identical to the 1-replica twin serving the same submission
    sequence.

    `run(parallel=True)` drains each replica in its own thread
    through the engine's `run(on_sync=)` hook; `on_sync(router,
    replica, engine)`, if given, fires at every replica sync under
    the router lock and may `router.submit` more work (admission
    churn) — churn parks on the router and is drained into the
    owning replica from that replica's OWN thread at its next sync,
    so engine internals are single-threaded by construction.
    `parallel=False` drains replicas round-robin on the calling
    thread — the deterministic mode the analysis captures and
    byte-identity tests drive. Submit through the router only: a
    direct `engine.submit` would collide with the global rid space.

    `respawn(i, engine)` swaps a dead replica for a fresh engine
    (same decoder config, same shared tier): the global rid counter
    keeps advancing, and the respawned replica warm-starts from the
    shared tier — its prefix hit rate recovers to the pre-kill
    steady state with zero prefill recompute for restored spans
    (test-pinned)."""

    def __init__(self, engines, affinity_blocks=2):
        engines = list(engines)
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        if len({id(e) for e in engines}) != len(engines):
            raise ValueError("FleetRouter replicas must be distinct "
                             "engine objects (one pool each)")
        self.engines = engines
        self.affinity_blocks = max(1, int(affinity_blocks))
        self._lock = threading.RLock()
        self._next_rid = 0           # global rid: THE sampling identity
        self._rid_replica = {}       # gid -> replica index
        self._pending = []           # (replica, gid, ids, tenant, slo,
        #                              adapter): churn awaiting the
        #                              owner replica's next sync
        self._outputs = {}           # gid -> generated tokens
        self._running = set()        # replicas currently inside run()
        self._serving = False        # inside router.run()
        self._errors = []
        for i, eng in enumerate(engines):
            eng.stats.replica = i

    # ------------------------------------------------------- submission

    def submit(self, prompt_ids, tenant="default", slo=SLO_THROUGHPUT,
               adapter=None):
        """Route + queue one prompt; returns its GLOBAL request id
        (the rid every stream byte is keyed by). Safe to call from
        `on_sync` churn callbacks mid-run: the submission parks on
        the router and the owning replica drains it at its next
        sync."""
        ids = [int(t) for t in np.asarray(
            prompt_ids._value if hasattr(prompt_ids, "_value")
            else prompt_ids).reshape(-1)]
        with self._lock:
            gid = self._next_rid
            self._next_rid = gid + 1
            i = self._route(ids, slo, adapter)
            self._rid_replica[gid] = i
            if self._serving:
                self._pending.append((i, gid, ids, tenant, slo,
                                      adapter))
            else:
                self._submit_to(i, gid, ids, tenant, slo, adapter)
        return gid

    def replica_of(self, gid):
        """Replica index a request was routed to (raises KeyError for
        unknown rids)."""
        with self._lock:
            return self._rid_replica[gid]

    def _route(self, ids, slo, adapter):
        """Affinity first, load as the escape hatch (caller holds the
        lock). Load reads are racy against running replicas — they
        only steer placement, never bytes."""
        n = len(self.engines)
        if n == 1:
            return 0
        eng0 = self.engines[0]
        target = None
        if eng0.cache is not None:
            keys = eng0.cache.block_keys(
                ids, extra_salt=eng0.d.adapter_salt(int(adapter or 0)))
            if keys:
                akey = keys[min(self.affinity_blocks, len(keys)) - 1]
                target = int.from_bytes(akey[:8], "big") % n
        loads = [self._load(j) for j in range(n)]
        least = min(range(n), key=lambda j: (loads[j], j))
        if target is None:
            return least
        if slo == SLO_LATENCY and loads[target] - loads[least] >= \
                self.engines[target].d.max_batch:
            # the affinity home is a full batch deeper than the
            # least-loaded replica: re-prefilling elsewhere beats
            # queueing behind the backlog for the latency tier
            return least
        return target

    def _load(self, j):
        eng = self.engines[j]
        return len(eng._queue) + sum(r is not None
                                     for r in eng._slot_req)

    def _submit_to(self, i, gid, ids, tenant, slo, adapter):
        """Hand one routed request to its engine, stamping the global
        rid into the engine's allocator first — rid IS the sampling
        key id, so fleet streams match the single-engine twin's.
        Called from the engine's own thread only (direct submit
        before run, or the owner's sync drain during it)."""
        eng = self.engines[i]
        eng._next_id = gid
        if hasattr(eng, "_submit_meta"):     # TenantEngine
            eng.submit(ids, tenant=tenant, slo=slo, adapter=adapter)
        else:
            eng.submit(ids, adapter=adapter)

    def _drain_pending(self, i):
        """Move replica `i`'s parked churn into its engine (called
        from that replica's own thread)."""
        with self._lock:
            mine = [p for p in self._pending if p[0] == i]
            if mine:
                self._pending = [p for p in self._pending
                                 if p[0] != i]
        for _, gid, ids, tenant, slo, adapter in mine:
            self._submit_to(i, gid, ids, tenant, slo, adapter)

    # ---------------------------------------------------------- serving

    def _hook(self, i, on_sync):
        """The per-replica `run(on_sync=)` wrapper: user churn under
        the router lock, then drain whatever was routed here."""
        def hook(eng):
            if on_sync is not None:
                with self._lock:
                    on_sync(self, i, eng)
            self._drain_pending(i)
        return hook

    def run(self, on_sync=None, parallel=True):
        """Drain the whole fleet; returns {global rid: generated
        token list} for every request retired during this call.
        `on_sync(router, replica, engine)` fires at every replica
        sync (under the router lock) and may `router.submit` churn.
        `parallel=True` gives each replica its own thread (aggregate
        throughput — jitted horizons release the GIL);
        `parallel=False` drains replicas round-robin on the calling
        thread (deterministic order — the analysis-capture mode)."""
        with self._lock:
            self._outputs = {}
            self._errors = []
            self._serving = True
        try:
            if parallel and len(self.engines) > 1:
                threads = [threading.Thread(
                    target=self._serve_replica, args=(i, on_sync),
                    name=f"fleet-replica{i}")
                    for i in range(len(self.engines))]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                if self._errors:
                    raise self._errors[0]
            else:
                self._serve_round_robin(on_sync)
        finally:
            with self._lock:
                self._serving = False
        with self._lock:
            return dict(self._outputs)

    def _serve_replica(self, i, on_sync):
        """One replica's drain loop (worker thread): run whenever the
        engine has queued work, then wait for routed churn until the
        whole fleet is quiescent."""
        eng = self.engines[i]
        hook = self._hook(i, on_sync)
        try:
            while True:
                self._drain_pending(i)
                if eng._queue:
                    with self._lock:
                        self._running.add(i)
                    try:
                        out = eng.run(on_sync=hook)
                    finally:
                        with self._lock:
                            self._running.discard(i)
                    with self._lock:
                        self._outputs.update(out)
                    continue
                if self._quiescent():
                    return
                time.sleep(0.0005)
        except BaseException as e:           # surfaced after join
            with self._lock:
                self._errors.append(e)
                self._running.discard(i)

    def _quiescent(self):
        """No parked churn, no replica mid-run, every queue and slot
        empty — only then may a drain loop exit (a running sibling
        may still route work here)."""
        with self._lock:
            if self._pending or self._running or self._errors:
                return bool(self._errors)
            return all(not e._queue and
                       all(r is None for r in e._slot_req)
                       for e in self.engines)

    def _serve_round_robin(self, on_sync):
        """Deterministic single-thread drain: replicas run to
        completion in index order, looped until no churn remains."""
        while True:
            progressed = False
            for i in range(len(self.engines)):
                self._drain_pending(i)
                eng = self.engines[i]
                if eng._queue:
                    out = eng.run(on_sync=self._hook(i, on_sync))
                    with self._lock:
                        self._outputs.update(out)
                    progressed = True
            with self._lock:
                if not self._pending and not progressed:
                    return

    # ------------------------------------------------------ replica ops

    def respawn(self, i, engine):
        """Swap replica `i` for a fresh engine (kill/respawn): the
        new engine inherits the replica id and, when built over the
        same `SharedHostKVTier`, warm-starts from the fleet's shared
        working set. Call between runs (the dead replica must not be
        mid-drain)."""
        with self._lock:
            if self._serving and i in self._running:
                raise RuntimeError(
                    f"replica {i} is mid-run — drain or kill it "
                    "before respawning")
            engine.stats.replica = i
            self.engines[i] = engine

    # ---------------------------------------------------- observability

    def merged_stats(self):
        """One fleet-wide `ServeStats` (`ServeStats.merge` over the
        replicas in replica order)."""
        return ServeStats.merge([e.stats for e in self.engines])

    def summary(self):
        return self.merged_stats().summary()

    def tenancy_summary(self):
        """Fleet-wide tenancy view: per-replica `TenantStats` merge
        per (tenant, slo) — counters sum, windows pool in replica
        order — then the SAME `summarize_tenancy` math as the single
        engine (a 1-replica fleet reproduces its engine's summary
        bit-for-bit)."""
        merged = {}
        for eng in self.engines:
            for key, ts in getattr(eng, "_tenants", {}).items():
                m = merged.get(key)
                if m is None:
                    m = merged[key] = TenantStats(tenant=ts.tenant,
                                                  slo=ts.slo)
                m.requests += ts.requests
                m.completed += ts.completed
                m.tokens += ts.tokens
                m.preemptions += ts.preemptions
                m.resumes += ts.resumes
                m.queue_wait_s.extend(ts.queue_wait_s)
                m.ttft_s.extend(ts.ttft_s)
                m.occupancy.extend(ts.occupancy)
        targets = next(
            (eng.scheduler.slo_targets_s for eng in self.engines
             if hasattr(eng.scheduler, "slo_targets_s")), None)
        return summarize_tenancy(
            merged, slo_targets_s=targets,
            preemptions=sum(e.stats.preemptions for e in self.engines),
            resumes=sum(e.stats.resumes for e in self.engines))

    def page_ledgers(self):
        """One auditable page ledger per replica (replica order) —
        each feeds `analysis.memory.audit_page_ledger` exactly like a
        single engine's."""
        return [eng.page_ledger() for eng in self.engines]

    def export_trace(self, path, profiler=None):
        """ONE Perfetto timeline for the whole fleet: every traced
        replica's recorder under its own labeled pid block
        ("replica<i> requests" / tick track / one pid per tenant), so
        N replicas x T tenants read as distinct processes on a shared
        perf_counter time base."""
        from .trace import export_chrome_trace
        recs = [(f"replica{i}", eng.trace)
                for i, eng in enumerate(self.engines)
                if eng.trace is not None]
        if not recs:
            raise ValueError(
                "no replica carries a FlightRecorder — construct the "
                "engines with trace=True to export a fleet timeline")
        return export_chrome_trace(path, recorders=recs,
                                   profiler=profiler)
