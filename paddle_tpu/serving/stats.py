"""Serving telemetry: per-engine `ServeStats` and the process-wide
engine registry behind `debug.serving_stats()`.

Counters are lifetime totals; every latency/occupancy distribution is a
bounded sliding window (deque maxlen) so a long-lived engine's
telemetry stays O(1) memory and O(window) to summarize.
"""
import collections
import itertools
import weakref
from dataclasses import dataclass, field

import numpy as np

__all__ = ["ServeStats", "serving_stats"]

# monotone per-process id: ServeStats instances (and therefore engines)
# get a stable creation-order identity, so `serving_stats()` output is
# deterministically ordered across runs (the WeakSet iterates in hash
# order, which is not)
_STATS_SEQ = itertools.count()


# every live engine, for debug.serving_stats() (mirrors the prefetcher
# registry in io/prefetch.py: observability without plumbing handles)
_ENGINES = weakref.WeakSet()


# sample window of the per-token / queue-wait / occupancy percentiles:
# counters run forever, distributions cover the most recent samples so
# a long-lived engine's telemetry stays O(1) memory and O(window) to
# summarize
_STATS_WINDOW = 4096


def _window():
    return collections.deque(maxlen=_STATS_WINDOW)


@dataclass
class ServeStats:
    """Serving telemetry of one engine: how often the host interposes
    on the decode loop and what the client observes. `decode_syncs` is
    the number under optimization — the per-tick engine pays one host
    sync per generated token; the multi-step engine one per K.
    Counters are lifetime totals; the latency/occupancy distributions
    are bounded sliding windows (last `_STATS_WINDOW` samples).

    The `prefix_*` counters are the prefix-cache ledger (block = one KV
    page of tokens): `prefix_hits`/`prefix_misses` count block lookups
    at admission, `prefix_tokens_saved` the prompt positions whose
    prefill was skipped entirely (pages mounted host-side),
    `prefix_bytes_saved` the KV bytes those positions would have
    written, `prefix_cow` copy-on-write page copies (a request about to
    write into a page it mounted shared), `prefix_evictions` refcount-0
    pages reclaimed from the cache under pool pressure."""
    engine: str = ""
    engine_id: int = -1          # creation order (set in __post_init__)
    # fleet position (serving.fleet.FleetRouter stamps it; -1 = not a
    # fleet member). `engine_id` alone orders engines within ONE
    # process — across processes the per-process counters collide, so
    # the merge/ordering contract is (engine, replica, engine_id):
    # the replica id is the cross-process leg of the identity
    replica: int = -1
    k_max: int = 1
    requests: int = 0            # submitted
    completed: int = 0           # retired with output
    tokens: int = 0              # generated tokens (prefill's included)
    ticks: int = 0               # device decode ticks dispatched
    decode_syncs: int = 0        # host fetches of decode results
    prefill_syncs: int = 0       # host-blocking prefill rounds
    prefill_stall_syncs: int = 0  # blocking prefills with decode slots
    # live at dispatch time — the stall the ragged path eliminates
    prefill_chunks: int = 0      # prompt chunks consumed inside horizons
    prefill_chunk_tokens: int = 0  # prompt tokens streamed via chunks
    # pad ledger (lifetime counters, every engine's HORIZON/TICK
    # dispatch paths — per-tick, fused, ragged, speculative): how many
    # token POSITIONS the dispatched layouts computed vs how many of
    # them were padding (window columns of decode rows on the dense
    # [S, w] layout, frozen/empty rows' filler, packed-bucket slack).
    # Blocking-path prefill dispatches (ragged=False admission) are
    # NOT in the ledger — the ragged default has none. pad_fraction =
    # padded/dispatched is the packed-ragged-layout headline: pay for
    # tokens, not windows.
    tokens_dispatched: int = 0   # token positions computed by dispatches
    tokens_padded: int = 0       # of those, padding (discarded work)
    prefix_hits: int = 0         # cached full blocks mounted at admission
    prefix_misses: int = 0       # cacheable blocks that had to prefill
    prefix_evictions: int = 0    # refcount-0 pages evicted under pressure
    prefix_cow: int = 0          # copy-on-write page copies
    prefix_tokens_saved: int = 0  # prompt positions whose prefill was skipped
    prefix_bytes_saved: int = 0  # KV bytes not recomputed (mounted pages)
    # tiered-KV ledger (serving.kv_tier): the host-RAM spill tier
    # behind the prefix cache. Counters are lifetime; host_tier_bytes
    # is a gauge (current host residency). tier_restores/tier_
    # recomputes make the priced restore-vs-recompute decision
    # OBSERVABLE: blocks found host-resident at admission either
    # re-mounted over the wire (restore) or re-prefilled because the
    # MXU beat the PCIe leg (recompute — the host entry is refreshed,
    # its bytes stay valid by write-time determinism).
    tier_spills: int = 0         # pages demoted to the host tier
    tier_restores: int = 0       # host blocks re-mounted via H2D
    tier_recomputes: int = 0     # host blocks re-prefilled (wire lost)
    host_tier_bytes: int = 0     # current host-tier residency (gauge)
    # tenancy ledger (serving.tenancy.TenantEngine): preemption by
    # page-spill. A preemption parks the victim's full KV blocks in
    # the prefix cache (whence pool pressure spills them through the
    # host tier) and requeues the request; a resume re-admits it with
    # its generated prefix as prompt — streams stay byte-identical
    # preempt-on vs preempt-off (the (request, position) write-time
    # discipline; fuzz-pinned in tests/test_tenancy.py).
    preemptions: int = 0         # victims preempted by page-spill
    resumes: int = 0             # preempted requests re-admitted
    # capacity ledger (set once at engine construction from the
    # decoder's pool layout; scale-plane metadata included for int8
    # pools): the observable side of the KV-quant capacity claim —
    # halve kv_bytes_per_token and the same pool feeds ~2x the slots
    kv_pool_bytes: int = 0       # whole paged pool, all layers
    kv_bytes_per_token: int = 0  # KV bytes one context token costs
    max_resident_slots: int = 0  # peak concurrently-occupied slots
    queue_wait_s: collections.deque = field(      # submit -> admit
        default_factory=_window)
    occupancy: collections.deque = field(         # active/slots per block
        default_factory=_window)
    ttft_s: collections.deque = field(            # submit -> first token
        default_factory=_window)
    token_time_s: collections.deque = field(
        # wall per token, steady-state decode syncs only (syncs that
        # contained a prefill are excluded, or p99 becomes a prefill
        # number)
        default_factory=_window)

    def __post_init__(self):
        if self.engine_id < 0:
            self.engine_id = next(_STATS_SEQ)

    # ordering contract of every multi-engine view (live_engines,
    # merge, the fleet's summaries): name, then fleet replica, then
    # per-process creation id. engine_id alone is only unique within
    # one process — the replica id disambiguates across them
    def order_key(self):
        return (self.engine, self.replica, self.engine_id)

    @classmethod
    def merge(cls, stats_list):
        """One fleet-wide ServeStats from N engines' (possibly
        N processes') ledgers: counters sum, the sliding windows pool
        in `order_key` order into windows of the SAME bound (oldest
        samples fall off exactly like a single long-lived engine's
        would — the merged view stays O(window)), and percentile math
        on a 1-engine merge reproduces the single engine's numbers
        bit-for-bit (same samples, same deque).

        Gauges need care: `host_tier_bytes` merges by MAX, not sum —
        the fleet's replicas share ONE host tier
        (serving.fleet.SharedHostKVTier), so every replica's gauge
        reads the same store and summing would count one warm set N
        times. `kv_pool_bytes`/`max_resident_slots` DO sum (each
        replica owns its device pool and slots); `kv_bytes_per_token`
        and `k_max` merge by max (homogeneous fleets agree on them
        anyway)."""
        stats = sorted(stats_list, key=lambda s: s.order_key())
        if not stats:
            return cls(engine="fleet[0]")
        names = sorted({s.engine for s in stats})
        out = cls(engine=(names[0] if len(names) == 1
                          else "+".join(names)))
        # a merge is a pure function of the stats SET: the fresh
        # per-process engine_id the ctor drew would make two merges of
        # the same set compare unequal — inherit the smallest input id
        out.engine_id = min(s.engine_id for s in stats)
        for f in ("requests", "completed", "tokens", "ticks",
                  "decode_syncs", "prefill_syncs", "prefill_stall_syncs",
                  "prefill_chunks", "prefill_chunk_tokens",
                  "tokens_dispatched", "tokens_padded", "prefix_hits",
                  "prefix_misses", "prefix_evictions", "prefix_cow",
                  "prefix_tokens_saved", "prefix_bytes_saved",
                  "tier_spills", "tier_restores", "tier_recomputes",
                  "preemptions", "resumes", "kv_pool_bytes",
                  "max_resident_slots"):
            setattr(out, f, sum(getattr(s, f) for s in stats))
        for f in ("k_max", "kv_bytes_per_token", "host_tier_bytes"):
            setattr(out, f, max(getattr(s, f) for s in stats))
        for f in ("queue_wait_s", "occupancy", "ttft_s",
                  "token_time_s"):
            win = getattr(out, f)
            for s in stats:
                win.extend(getattr(s, f))
        return out

    @property
    def host_syncs_per_token(self):
        return self.decode_syncs / self.tokens if self.tokens else 0.0

    @property
    def prefix_hit_rate(self):
        """Fraction of cacheable prompt blocks served from the cache."""
        n = self.prefix_hits + self.prefix_misses
        return self.prefix_hits / n if n else 0.0

    @property
    def pad_fraction(self):
        """Fraction of dispatched token positions that were padding."""
        return self.tokens_padded / self.tokens_dispatched \
            if self.tokens_dispatched else 0.0

    def summary(self):
        d = {"engine": self.engine, "engine_id": self.engine_id,
             **({"replica": self.replica} if self.replica >= 0 else {}),
             "k_max": self.k_max,
             "requests": self.requests, "completed": self.completed,
             "tokens": self.tokens, "ticks": self.ticks,
             "decode_syncs": self.decode_syncs,
             "prefill_syncs": self.prefill_syncs,
             "host_syncs_per_token": round(self.host_syncs_per_token, 4)}
        if self.prefill_stall_syncs:
            d["prefill_stall_syncs"] = self.prefill_stall_syncs
        if self.prefill_chunks:
            d["prefill_chunks"] = self.prefill_chunks
            d["prefill_chunk_tokens"] = self.prefill_chunk_tokens
        if self.tokens_dispatched:
            d["tokens_dispatched"] = self.tokens_dispatched
            d["tokens_padded"] = self.tokens_padded
            d["pad_fraction"] = round(self.pad_fraction, 4)
        if self.prefix_hits or self.prefix_misses:
            d["prefix_hit_rate"] = round(self.prefix_hit_rate, 4)
            d["prefix_hits"] = self.prefix_hits
            d["prefix_misses"] = self.prefix_misses
            d["prefix_evictions"] = self.prefix_evictions
            d["prefix_cow"] = self.prefix_cow
            d["prefix_tokens_saved"] = self.prefix_tokens_saved
            d["prefix_bytes_saved"] = self.prefix_bytes_saved
        if self.tier_spills or self.tier_restores or \
                self.tier_recomputes or self.host_tier_bytes:
            d["tier_spills"] = self.tier_spills
            d["tier_restores"] = self.tier_restores
            d["tier_recomputes"] = self.tier_recomputes
            d["host_tier_bytes"] = self.host_tier_bytes
        if self.preemptions or self.resumes:
            d["preemptions"] = self.preemptions
            d["resumes"] = self.resumes
        if self.kv_pool_bytes:
            d["kv_pool_bytes"] = self.kv_pool_bytes
            d["kv_bytes_per_token"] = self.kv_bytes_per_token
        if self.max_resident_slots:
            d["max_resident_slots"] = self.max_resident_slots
        if self.occupancy:
            d["mean_slot_occupancy"] = round(
                float(np.mean(self.occupancy)), 4)
        # queue wait and TTFT report p50 AND p99: tail TTFT is the
        # latency-tier SLO number (a mean-friendly p50 hides exactly
        # the admission stalls an SLO class must bound)
        if self.queue_wait_s:
            d["queue_wait_p50_ms"] = round(
                float(np.percentile(self.queue_wait_s, 50)) * 1e3, 3)
            d["queue_wait_p99_ms"] = round(
                float(np.percentile(self.queue_wait_s, 99)) * 1e3, 3)
        if self.ttft_s:
            d["ttft_p50_ms"] = round(
                float(np.percentile(self.ttft_s, 50)) * 1e3, 3)
            d["ttft_p99_ms"] = round(
                float(np.percentile(self.ttft_s, 99)) * 1e3, 3)
        if self.token_time_s:
            tot = float(np.sum(self.token_time_s))
            d["tokens_per_sec"] = round(len(self.token_time_s) / tot, 1) \
                if tot else 0.0
            d["token_p50_ms"] = round(
                float(np.percentile(self.token_time_s, 50)) * 1e3, 3)
            d["token_p99_ms"] = round(
                float(np.percentile(self.token_time_s, 99)) * 1e3, 3)
        return d


def live_engines():
    """Every live engine, deterministically ordered by (engine name,
    fleet replica, creation id) — THE ordering contract for serving
    telemetry front doors (`serving_stats`, `debug.serving_report`,
    `ServeStats.merge`): the WeakSet iterates in hash order, which
    would make logs and doctests flap across runs, and `engine_id`
    alone is only unique within one process — the replica id
    (`serving.fleet.FleetRouter` stamps it) is the cross-process leg
    of the identity."""
    return sorted(_ENGINES, key=lambda e: e.stats.order_key())


def serving_stats():
    """ServeStats summaries of every live engine (debug.serving_stats
    front door), deterministically ordered (`live_engines`)."""
    return [e.stats.summary() for e in live_engines()]
