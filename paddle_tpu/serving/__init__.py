"""Continuous-batching decode engine over the paged KV cache.

Reference role: the fluid inference API's batched decode serving path
(paddle/fluid/inference/api/paddle_inference_api.h + PaddleNLP FasterGPT
decoding).  TPU-native design, split across this package:

- `decoder.py` — ONE compiled decode step for a fixed slot count:
  [max_batch] tokens in, [max_batch] next tokens out (greedy, or seeded
  temperature/top-k/top-p sampling).  Slots hold independent sequences
  at different lengths; position/page state rides in arrays, so
  admission and retirement never recompile.  KV lives in paged pools
  [L, P, page_size, H, D] (ops/paged_attention); decode attention
  gathers each slot's pages (optionally via the scalar-prefetch Pallas
  kernel); page allocation is host-side.  Prefill is a second compiled
  program per prompt-length bucket (powers of two) writing the prompt's
  K/V straight into the pages; the CHUNKED prefill
  (`prefill_suffix_batch`) consumes only a prompt's uncached suffix,
  attending against already-mounted prefix pages.  Multi-step decode
  (`decode_multi`) fuses K decode ticks into ONE compiled `lax.scan` —
  sampled tokens feed back on device, per-slot done masks freeze
  finished slots — so the engine syncs the host once per K tokens
  instead of once per token (cf. Ragged Paged Attention, arXiv
  2604.15464; T3's overlap analysis, arXiv 2401.16677).  Mixed
  horizons and the chunked prefill dispatch the PACKED
  [total_new_tokens] token-stream layout by default (per-token row
  ids, pow2 total-token buckets — docs/serving.md "Packed ragged
  layout"); `packed=False` keeps the dense [S, w] window twin for
  byte-identity A/B.
- `engine.py` — `ContinuousBatchingEngine.run()` schedules horizons of
  `k = min(K_max, smallest remaining budget)` ticks and overlaps each
  block's host fetch with the NEXT block's dispatch (one-horizon-
  delayed retirement); `cost_model.decode_horizon` prices the default
  K from the chip's tick roofline vs the measured host sync cost.
  `SpeculativeEngine` layers draft-propose/target-verify decoding on
  top.
- `prefix_cache.py` — content-addressed KV page sharing: hash (token
  block chain, model-invariant config) -> page id with refcounts,
  copy-on-write on the first divergent-token write, and LRU eviction of
  refcount-0 pages under pool pressure.  Requests sharing a system
  prompt / few-shot prefix skip prefill for the shared span entirely
  (the Gemma-on-TPU serving comparison, PAPERS.md, leans on exactly
  this page-level reuse).
- `kv_tier.py` — the memory hierarchy BEHIND the prefix cache:
  refcount-0 pages evicted under pool pressure spill their bytes to a
  capacity-bounded pinned-host-RAM LRU (`HostKVTier`; int8 pools spill
  quantized — half the host bytes), and admissions whose chain
  continues onto host entries restore via H2D only when
  `cost_model.kv_restore_s` beats the span's prefill recompute.
  `PrefixCache.save(dir)`/`load(dir, decoder)` persist the cache
  across engine restarts, keyed by `cache_fingerprint()` (mismatch
  refuses).  docs/serving.md "Tiered KV".
- `tenancy.py` — multi-tenant serving over the same machinery:
  per-request SLO classes (`TenantEngine`: latency-tier requests admit
  ahead of the throughput backlog; `TenantScheduler` composes horizons
  per class through `cost_model.slo_horizon`), preemption by
  page-spill (a latency admission out of slots/pages parks a
  throughput victim's KV blocks into the prefix cache — whence the
  host tier — and the victim resumes byte-identically), and
  multi-LoRA (per-token adapter gathers over shared base weights —
  `PagedGPTDecoder.attach_adapters` — with per-adapter chain-key salts
  so pages never alias across variants).  docs/serving.md
  "Multi-tenant serving".
- `fleet.py` — fleet-scale serving on one host: `SharedHostKVTier`
  re-homes the host tier onto a file/shm-backed store every replica
  on the host shares (same chain keys, same `PrefixCache.save` byte
  format, flock + atomic-replace discipline; restores price a
  host-RAM read leg via `cost_model.kv_restore_s(shared=True)`), and
  `FleetRouter` fronts N `TenantEngine` replicas with prefix-affinity
  routing (the cache's chain keys ARE the routing key) + SLO-aware
  least-loaded escape, global rid allocation (N-replica streams are
  byte-identical to the 1-replica twin), `run(on_sync=)` admission
  churn, kill/respawn warm-start, and fleet-wide observability
  (`ServeStats.merge`, pooled `tenancy_summary`, one Perfetto
  timeline with per-(replica, tenant) pids).  docs/serving.md
  "Fleet serving".
- `stats.py` — per-engine `ServeStats` (host syncs/token, prefix-cache
  hit/evict/bytes-saved counters, tiered-KV spill/restore/recompute
  counters, tenancy preemption/resume counters, TTFT/queue-wait/
  occupancy windows) behind `debug.serving_stats()`; per-tenant
  `TenantStats` behind `TenantEngine.tenancy_summary()`.

quant="a8w8": per-(layer, out-channel) int8 weights with dynamic
per-row int8 activations — matmuls run int8xint8->int32 on the MXU
(same recipe as quantization.QuantizedLinearA8W8).  quant="w4a16":
weight-only int4 (ops/w4_matmul.py): nibbles unpack in VMEM, bf16
activations — half the weight HBM traffic of a8w8.

The engine applies to GPT-family models (uniform pre-LN blocks); weights
are extracted once into stacked per-layer arrays and the model object is
no longer needed — pair with jit.load-style artifacts for serving.
"""
from .decoder import (MultiDecodeOut, PagedGPTDecoder, RaggedMultiOut,
                      _kv_set, _ln, _mm, _mm_heads, _quantize_kv,
                      _quantize_w, _sample_tokens,
                      _spec_accept)
from .engine import ContinuousBatchingEngine, SpeculativeEngine
from .fleet import FleetRouter, SharedHostKVTier
from .kv_tier import HostKVTier, restore_beats_recompute
from .prefix_cache import PrefixCache
from .scheduler import RaggedScheduler
from .stats import _ENGINES, _STATS_WINDOW, ServeStats, serving_stats
from .tenancy import (SLO_LATENCY, SLO_THROUGHPUT,
                      PrecisionRoutedEngine, TenantEngine,
                      TenantScheduler, TenantStats, make_lora_bank)
from .trace import (FlightRecorder, export_chrome_trace,
                    validate_chrome_trace)

__all__ = ["PagedGPTDecoder", "ContinuousBatchingEngine",
           "SpeculativeEngine", "ServeStats", "serving_stats",
           "PrefixCache", "HostKVTier", "restore_beats_recompute",
           "SharedHostKVTier", "FleetRouter",
           "MultiDecodeOut", "RaggedMultiOut",
           "RaggedScheduler", "FlightRecorder", "export_chrome_trace",
           "validate_chrome_trace",
           "SLO_LATENCY", "SLO_THROUGHPUT", "TenantEngine",
           "PrecisionRoutedEngine",
           "TenantScheduler", "TenantStats", "make_lora_bank"]
