"""Content-addressed prefix cache over the paged KV pool.

Real serving fleets overwhelmingly share prompt prefixes (system
prompts, few-shot templates); the Gemma-on-TPU serving comparison
(PAPERS.md, arxiv 2605.25645) attributes a large share of its TPU
serving win to page-level prefix reuse, and the paged KV pool
(`serving.decoder.PagedGPTDecoder`) already gives the page-granular
indirection the Ragged Paged Attention design assumes (arxiv
2604.15464).  This module adds the missing piece: a host-side,
content-addressed index over that pool so requests sharing a prefix
skip prefill for the shared span entirely.

Design (vLLM-style hash-block caching, TPU-native pool):

- **Chain keys.**  A prompt is split into full `page_size`-token
  blocks; block ``j``'s key is ``H(key_{j-1} || tokens_j)`` with the
  root key salted by a model/sampling-invariant decoder fingerprint.
  Position and full prefix content are therefore implicit in the key —
  two requests map to the same page iff their ENTIRE token prefix up to
  that block matches (and was produced by an equivalent decoder
  config), so a mounted page's KV bytes are exactly the bytes the
  request's own prefill would have written (prefill is deterministic
  and per-position computations are batch-independent).
- **Refcounts.**  ``refs`` counts live requests mounting a page.  The
  cache itself holds pages beyond ``refs == 0``: they park in an LRU
  and are reclaimed (evicted back to the engine's free list) only
  under pool pressure.  A page is never freed while referenced, and
  freed exactly once — the engine's page ledger is auditable
  (`analysis.memory.audit_page_ledger`, rule MEM-PAGE-REFCOUNT).
- **Copy-on-write.**  The cache never hands out writable shared pages;
  the ENGINE copies a page before the first divergent-token write
  lands in it (the full-hit branch of
  `ContinuousBatchingEngine._gather_admissions_cached`, via
  `PagedGPTDecoder.copy_page`) and releases its reference on the
  original.  The cache only tracks the refcounts that make the "is
  this page shared" question answerable.
- **Eviction.**  LRU over parked (refcount-0) entries.  Keys chain, so
  an evicted block's parked descendants are unreachable (a lookup must
  match block 0..j-1 before j) and are evicted in the same sweep —
  no stranded pages.
"""
import collections
import hashlib
from dataclasses import dataclass, field

import numpy as np

__all__ = ["PrefixCache"]


@dataclass
class _Entry:
    key: bytes
    page: int
    parent: bytes = None         # chain parent key (None for block 0)
    refs: int = 0                # live requests mounting this page
    children: set = field(default_factory=set)


class PrefixCache:
    """Content-addressed, refcounted page index: chain key -> page id.

    `page_size` is the token-block granularity (one KV page).  `salt`
    folds the decoder's model/sampling-invariant fingerprint into the
    root key so two decoders with different weights or quantization
    never alias.  `capacity` bounds the number of cached pages
    (None = bounded only by the pool; 0 = caching disabled — every
    lookup misses and inserts are refused, which is the exact
    "caching off" twin the equivalence tests compare against)."""

    def __init__(self, page_size, salt=b"", capacity=None):
        self.page_size = int(page_size)
        self.salt = salt if isinstance(salt, bytes) else str(salt).encode()
        self.capacity = capacity
        self._entries = {}               # key -> _Entry
        self._by_page = {}               # page id -> key
        self._lru = collections.OrderedDict()   # key -> None (refs == 0)

    # ------------------------------------------------------------ keys

    def block_keys(self, tokens):
        """Chain keys of every FULL `page_size`-token block of `tokens`
        (a trailing partial block is never cacheable — its page will
        keep growing)."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        n = len(toks) // self.page_size
        keys, prev = [], self.salt
        for b in range(n):
            block = toks[b * self.page_size:(b + 1) * self.page_size]
            h = hashlib.blake2b(digest_size=16)
            h.update(prev)
            h.update(block.tobytes())
            prev = h.digest()
            keys.append(prev)
        return keys

    # ---------------------------------------------------------- lookup

    def match(self, keys):
        """Page ids of the longest cached run of `keys` from block 0
        (peek only — no refcount change)."""
        pages = []
        for k in keys:
            e = self._entries.get(k)
            if e is None:
                break
            pages.append(e.page)
        return pages

    def mount(self, keys):
        """Incref every entry in `keys` (a request is now holding its
        page); revives parked entries out of the LRU."""
        for k in keys:
            e = self._entries[k]
            e.refs += 1
            self._lru.pop(k, None)

    # ---------------------------------------------------------- insert

    def insert(self, key, page, parent=None):
        """Register a freshly prefilled full-block page under `key`
        with one reference (the inserting request).  Returns False —
        and takes no ownership — when the key is already cached (a
        same-batch duplicate computed its own copy; it keeps the page
        private) or the capacity bound refuses new entries.

        Caller contract: only insert a child under a `parent` the
        caller currently HOLDS (mounted or inserted this admission) —
        the engine stops publishing a chain at the first refused
        insert.  Otherwise a still-referenced child could sit under a
        refcount-0 parent, and the eviction cascade (which relies on
        child-referenced => every-ancestor-referenced) would trip its
        refcount guard."""
        if key in self._entries:
            return False
        if self.capacity is not None and len(self._entries) >= self.capacity:
            # full: insert() never evicts (freed pages belong to the
            # ENGINE's free list; only admission-time evict() may
            # reclaim) — the block simply stays private to its request
            return False
        e = _Entry(key=key, page=int(page), parent=parent, refs=1)
        self._entries[key] = e
        self._by_page[int(page)] = key
        if parent is not None and parent in self._entries:
            self._entries[parent].children.add(key)
        return True

    # --------------------------------------------------------- release

    def release_page(self, page):
        """One request stopped referencing `page` (retirement or CoW).
        At refcount 0 the page PARKS in the LRU — still cached, still
        owned by the cache — instead of returning to the free list;
        only eviction frees it (exactly once)."""
        key = self._by_page[int(page)]
        e = self._entries[key]
        if e.refs <= 0:
            raise RuntimeError(
                f"refcount underflow on page {page} (double release)")
        e.refs -= 1
        if e.refs == 0:
            self._lru[key] = None       # most-recently parked = last out

    def is_cached_page(self, page):
        return int(page) in self._by_page

    def refs_of_page(self, page):
        return self._entries[self._by_page[int(page)]].refs

    # -------------------------------------------------------- eviction

    def evictable(self, exclude=()):
        """How many parked pages could be reclaimed right now (the
        admission head-of-line check adds this to the free list before
        deciding to wait). `exclude` keys are about to be mounted —
        their whole ancestor chain is also in the hit set, so excluding
        the hits themselves suffices."""
        ex = set(exclude)
        return sum(1 for k in self._lru if k not in ex)

    def evict(self, n, exclude=()):
        """Reclaim at least `n` parked pages (LRU-first), cascading to
        each victim's parked descendants (their chain keys are
        unreachable once an ancestor is gone).  Returns the freed page
        ids — the caller (engine) owns them again."""
        ex = set(exclude)
        freed = []
        while len(freed) < n:
            victim = next((k for k in self._lru if k not in ex), None)
            if victim is None:
                break
            freed.extend(self._evict_subtree(victim))
        return freed

    def _evict_subtree(self, key):
        freed = []
        stack = [key]
        while stack:
            k = stack.pop()
            e = self._entries.pop(k, None)
            if e is None:
                continue
            if e.refs:
                raise RuntimeError(
                    f"evicting page {e.page} with refcount {e.refs}")
            stack.extend(e.children)
            self._lru.pop(k, None)
            del self._by_page[e.page]
            if e.parent is not None and e.parent in self._entries:
                self._entries[e.parent].children.discard(k)
            freed.append(e.page)
        return freed

    # ------------------------------------------------------------ view

    @property
    def n_pages(self):
        """Pages the cache currently owns or tracks (mounted + parked)."""
        return len(self._entries)

    @property
    def n_parked(self):
        return len(self._lru)

    def pages(self):
        """Page ids the cache currently tracks (mounted + parked) — the
        engine's audit walks these next to the slot-held pages."""
        return list(self._by_page)

    def ledger(self):
        """{page id: {"refs": r, "parked": bool}} — the audit view the
        MEM-PAGE-REFCOUNT lint consumes via the engine's page ledger."""
        return {e.page: {"refs": e.refs, "parked": e.refs == 0}
                for e in self._entries.values()}
