"""Content-addressed prefix cache over the paged KV pool.

Real serving fleets overwhelmingly share prompt prefixes (system
prompts, few-shot templates); the Gemma-on-TPU serving comparison
(PAPERS.md, arxiv 2605.25645) attributes a large share of its TPU
serving win to page-level prefix reuse, and the paged KV pool
(`serving.decoder.PagedGPTDecoder`) already gives the page-granular
indirection the Ragged Paged Attention design assumes (arxiv
2604.15464).  This module adds the missing piece: a host-side,
content-addressed index over that pool so requests sharing a prefix
skip prefill for the shared span entirely.

Design (vLLM-style hash-block caching, TPU-native pool):

- **Chain keys.**  A prompt is split into full `page_size`-token
  blocks; block ``j``'s key is ``H(key_{j-1} || tokens_j)`` with the
  root key salted by a model/sampling-invariant decoder fingerprint.
  Position and full prefix content are therefore implicit in the key —
  two requests map to the same page iff their ENTIRE token prefix up to
  that block matches (and was produced by an equivalent decoder
  config), so a mounted page's KV bytes are exactly the bytes the
  request's own prefill would have written (prefill is deterministic
  and per-position computations are batch-independent).
- **Refcounts.**  ``refs`` counts live requests mounting a page.  The
  cache itself holds pages beyond ``refs == 0``: they park in an LRU
  and are reclaimed (evicted back to the engine's free list) only
  under pool pressure.  A page is never freed while referenced, and
  freed exactly once — the engine's page ledger is auditable
  (`analysis.memory.audit_page_ledger`, rule MEM-PAGE-REFCOUNT).
- **Copy-on-write.**  The cache never hands out writable shared pages;
  the ENGINE copies a page before the first divergent-token write
  lands in it (the full-hit branch of
  `ContinuousBatchingEngine._gather_admissions_cached`, via
  `PagedGPTDecoder.copy_page`) and releases its reference on the
  original.  The cache only tracks the refcounts that make the "is
  this page shared" question answerable.
- **Eviction.**  LRU over parked (refcount-0) entries.  Keys chain, so
  an evicted block's parked descendants are unreachable (a lookup must
  match block 0..j-1 before j) and are evicted in the same sweep —
  no stranded pages.
"""
import collections
import hashlib
from dataclasses import dataclass, field

import numpy as np

__all__ = ["PrefixCache", "pack_array", "unpack_array"]


def pack_array(arr):
    """(raw uint8 view, {"shape","dtype"} meta) of one pool/payload
    leaf — THE persisted byte format: npz can't serialize ml_dtypes
    (bf16) leaves directly, so every array is stored as its raw bytes
    with shape+dtype carried out-of-band in JSON. `save()` below and
    the cross-process shared tier (`serving.fleet.SharedHostKVTier`)
    both write exactly this encoding, so a spilled page is one wire
    format everywhere it lands (disk snapshot or shm/file store)."""
    arr = np.asarray(arr)
    return (np.frombuffer(arr.tobytes(), np.uint8),
            {"shape": list(arr.shape), "dtype": str(arr.dtype)})


def unpack_array(raw, meta):
    """Inverse of `pack_array`. The `.copy()` matters: frombuffer
    views are read-only and may be ZERO-copied into device buffers by
    the CPU backend — which the decode programs then DONATE (XLA
    recycling memory it doesn't own). A writable owned copy keeps the
    decoded leaf safely donatable/mountable."""
    return np.frombuffer(
        np.asarray(raw).tobytes(), np.dtype(meta["dtype"])
    ).reshape(meta["shape"]).copy()


@dataclass
class _Entry:
    key: bytes
    page: int
    parent: bytes = None         # chain parent key (None for block 0)
    refs: int = 0                # live requests mounting this page
    children: set = field(default_factory=set)


class PrefixCache:
    """Content-addressed, refcounted page index: chain key -> page id.

    `page_size` is the token-block granularity (one KV page).  `salt`
    folds the decoder's model/sampling-invariant fingerprint into the
    root key so two decoders with different weights or quantization
    never alias.  `capacity` bounds the number of cached pages
    (None = bounded only by the pool; 0 = caching disabled — every
    lookup misses and inserts are refused, which is the exact
    "caching off" twin the equivalence tests compare against)."""

    def __init__(self, page_size, salt=b"", capacity=None, tier=None):
        self.page_size = int(page_size)
        self.salt = salt if isinstance(salt, bytes) else str(salt).encode()
        self.capacity = capacity
        # optional HOST spill tier (serving.kv_tier.HostKVTier): pages
        # evicted under pool pressure spill their bytes to pinned host
        # RAM instead of vanishing, and admissions whose chain
        # continues onto host entries may restore them (the engine owns
        # the spill/restore I/O and the pricing; the cache only chains
        # the keys). None = the single-level cache of PR 8.
        self.tier = tier
        self._decoder = None             # weakref set by the engine —
        # save() reads the pool through it when no decoder is passed
        self._entries = {}               # key -> _Entry
        self._by_page = {}               # page id -> key
        self._lru = collections.OrderedDict()   # key -> None (refs == 0)

    # ------------------------------------------------------------ keys

    def block_keys(self, tokens, extra_salt=b""):
        """Chain keys of every FULL `page_size`-token block of `tokens`
        (a trailing partial block is never cacheable — its page will
        keep growing). `extra_salt` folds a per-REQUEST identity into
        the root key on top of the cache's decoder salt — the
        multi-LoRA engine passes the request's adapter fingerprint
        (`PagedGPTDecoder.adapter_salt`), so two variants' KV pages
        never alias even when their token prefixes match (the bytes
        differ: the adapter's low-rank delta is part of the write)."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        n = len(toks) // self.page_size
        keys, prev = [], self.salt + extra_salt
        for b in range(n):
            block = toks[b * self.page_size:(b + 1) * self.page_size]
            h = hashlib.blake2b(digest_size=16)
            h.update(prev)
            h.update(block.tobytes())
            prev = h.digest()
            keys.append(prev)
        return keys

    # ---------------------------------------------------------- lookup

    def match(self, keys):
        """Page ids of the longest cached run of `keys` from block 0
        (peek only — no refcount change)."""
        pages = []
        for k in keys:
            e = self._entries.get(k)
            if e is None:
                break
            pages.append(e.page)
        return pages

    def mount(self, keys):
        """Incref every entry in `keys` (a request is now holding its
        page); revives parked entries out of the LRU."""
        for k in keys:
            e = self._entries[k]
            e.refs += 1
            self._lru.pop(k, None)

    # ---------------------------------------------------------- insert

    def insert(self, key, page, parent=None):
        """Register a freshly prefilled full-block page under `key`
        with one reference (the inserting request).  Returns False —
        and takes no ownership — when the key is already cached (a
        same-batch duplicate computed its own copy; it keeps the page
        private) or the capacity bound refuses new entries.

        Caller contract: only insert a child under a `parent` the
        caller currently HOLDS (mounted or inserted this admission) —
        the engine stops publishing a chain at the first refused
        insert.  Otherwise a still-referenced child could sit under a
        refcount-0 parent, and the eviction cascade (which relies on
        child-referenced => every-ancestor-referenced) would trip its
        refcount guard."""
        if key in self._entries:
            return False
        if self.capacity is not None and len(self._entries) >= self.capacity:
            # full: insert() never evicts (freed pages belong to the
            # ENGINE's free list; only admission-time evict() may
            # reclaim) — the block simply stays private to its request
            return False
        e = _Entry(key=key, page=int(page), parent=parent, refs=1)
        self._entries[key] = e
        self._by_page[int(page)] = key
        if parent is not None and parent in self._entries:
            self._entries[parent].children.add(key)
        return True

    # --------------------------------------------------------- release

    def release_page(self, page):
        """One request stopped referencing `page` (retirement or CoW).
        At refcount 0 the page PARKS in the LRU — still cached, still
        owned by the cache — instead of returning to the free list;
        only eviction frees it (exactly once)."""
        key = self._by_page[int(page)]
        e = self._entries[key]
        if e.refs <= 0:
            raise RuntimeError(
                f"refcount underflow on page {page} (double release)")
        e.refs -= 1
        if e.refs == 0:
            self._lru[key] = None       # most-recently parked = last out

    def is_cached_page(self, page):
        return int(page) in self._by_page

    def refs_of_page(self, page):
        return self._entries[self._by_page[int(page)]].refs

    # -------------------------------------------------------- eviction

    def evictable(self, exclude=()):
        """How many parked pages could be reclaimed right now (the
        admission head-of-line check adds this to the free list before
        deciding to wait). `exclude` keys are about to be mounted —
        their whole ancestor chain is also in the hit set, so excluding
        the hits themselves suffices."""
        ex = set(exclude)
        return sum(1 for k in self._lru if k not in ex)

    def evict(self, n, exclude=(), spill=None):
        """Reclaim at least `n` parked pages (LRU-first), cascading to
        each victim's parked descendants (their chain keys are
        unreachable once an ancestor is gone).  Returns the freed page
        ids — the caller (engine) owns them again.  `spill(key, page)`,
        if given, runs for every victim BEFORE its page is unmapped —
        the host-tier hook.  The engine's hook (`_spill_wave.note`)
        only RECORDS the victims here and performs ONE batched D2H
        after evict() returns; that is safe because the engine defers
        handing out (and a fortiori writing) the freed pages until the
        batched fetch has completed — a caller that recycles freed
        pages before reading their bytes would corrupt the spill."""
        ex = set(exclude)
        freed = []
        while len(freed) < n:
            victim = next((k for k in self._lru if k not in ex), None)
            if victim is None:
                break
            freed.extend(self._evict_subtree(victim, spill=spill))
        return freed

    def _evict_subtree(self, key, spill=None):
        freed = []
        stack = [key]
        while stack:
            k = stack.pop()
            e = self._entries.pop(k, None)
            if e is None:
                continue
            if e.refs:
                raise RuntimeError(
                    f"evicting page {e.page} with refcount {e.refs}")
            if spill is not None:
                # the page's bytes are still valid here AND until the
                # caller reuses the freed ids: nobody writes a parked
                # page, so the hook may read now or batch the read
                # after the walk (the engine's _spill_wave does the
                # latter) — as long as it reads before reuse
                spill(k, e.page)
            stack.extend(e.children)
            self._lru.pop(k, None)
            del self._by_page[e.page]
            if e.parent is not None and e.parent in self._entries:
                self._entries[e.parent].children.discard(k)
            freed.append(e.page)
        return freed

    # ------------------------------------------------------------ view

    @property
    def n_pages(self):
        """Pages the cache currently owns or tracks (mounted + parked)."""
        return len(self._entries)

    @property
    def n_parked(self):
        return len(self._lru)

    def pages(self):
        """Page ids the cache currently tracks (mounted + parked) — the
        engine's audit walks these next to the slot-held pages."""
        return list(self._by_page)

    def ledger(self):
        """{page id: {"refs": r, "parked": bool}} — the audit view the
        MEM-PAGE-REFCOUNT lint consumes via the engine's page ledger."""
        return {e.page: {"refs": e.refs, "parked": e.refs == 0}
                for e in self._entries.values()}

    # ------------------------------------------------------ persistence

    def _fingerprint_hex(self, decoder):
        return hashlib.blake2b(decoder.cache_fingerprint(),
                               digest_size=16).hexdigest()

    def save(self, path, decoder=None):
        """Persist the cache so it outlives the engine: the decoder's
        pool arrays (through the `pool_state` seam — quant config
        included), the chain index (key -> page, parents, LRU order),
        and every host-tier entry's payload, keyed by a digest of
        `decoder.cache_fingerprint()`. `load()` on a decoder with a
        different fingerprint REFUSES (same contract as the
        quant-config check in `load_pool_state`): the cached bytes are
        only valid for the exact weights/arch/pool config that wrote
        them.

        `decoder` defaults to the engine-bound one (the engine attaches
        itself at construction). Every entry must be parked (refs 0) —
        drain the engine first; saving under live requests would
        snapshot pages about to diverge."""
        import json
        import os
        dec = decoder
        if dec is None and self._decoder is not None:
            dec = self._decoder()
        if dec is None:
            raise ValueError(
                "PrefixCache.save needs the decoder whose pool holds "
                "the cached pages — pass decoder=, or attach the cache "
                "to an engine first")
        live = sum(1 for e in self._entries.values() if e.refs)
        if live:
            raise RuntimeError(
                f"cannot save a prefix cache with {live} live-"
                "referenced page(s) — drain the engine (run() to "
                "completion) so every entry is parked first")
        os.makedirs(path, exist_ok=True)
        state = dec.pool_state()
        arrays, meta = {}, {}

        def add(name, arr):
            # raw-byte view + JSON-carried shape/dtype (pack_array —
            # the one persisted byte format, shared with the fleet's
            # cross-process tier)
            arrays[name], meta[name] = pack_array(arr)

        for pool in ("k_pages", "v_pages"):
            leaves = state[pool] if isinstance(state[pool], tuple) \
                else (state[pool],)
            for i, leaf in enumerate(leaves):
                add(f"{pool}.{i}", leaf)
        entries = []                     # LRU order: oldest first, so a
        for k in self._lru:              # loaded cache evicts in the
            e = self._entries[k]         # same sequence
            entries.append([k.hex(), int(e.page),
                            e.parent.hex() if e.parent else None])
        host = []
        if self.tier is not None:
            for j, (k, te) in enumerate(self.tier.items()):
                leaves = {"k": len(te.payload["k"]),
                          "v": len(te.payload["v"])}
                for part in ("k", "v"):
                    for i, leaf in enumerate(te.payload[part]):
                        add(f"host.{j}.{part}.{i}", leaf)
                host.append([k.hex(), leaves])
        index = {"fingerprint": self._fingerprint_hex(dec),
                 "page_size": self.page_size,
                 "kv_quant": state["kv_quant"],
                 # the chain keys were computed under THIS salt — a
                 # load that rebound a different salt would hash every
                 # warm prompt to keys that never match the saved
                 # entries (0 hits, silently)
                 "salt": self.salt.hex(),
                 # bounds round-trip too: reloading a bounded cache /
                 # tier under DEFAULT bounds could silently LRU-drop
                 # part of the persisted warm set during the refill
                 "capacity": self.capacity,
                 "tier_capacity_bytes": (self.tier.capacity_bytes
                                         if self.tier is not None
                                         else None),
                 "entries": entries, "host": host, "arrays": meta}
        np.savez(os.path.join(path, "kv_pool.npz"), **arrays)
        with open(os.path.join(path, "index.json"), "w") as f:
            json.dump(index, f)
        return path

    @classmethod
    def load(cls, path, decoder, tier=None, capacity=None):
        """Rebuild a saved cache onto `decoder`: refuses on fingerprint
        mismatch (different weights, architecture, page size, pool
        dtype or quant config than the decoder that wrote it — mounted
        pages would hold another model's KV), then restores the pool
        through `load_pool_state` (which re-checks quant config and
        shapes, and refuses while any attached engine holds live
        pages), re-parks every entry in its saved LRU order, and
        refills the host tier (`tier`, or a fresh `HostKVTier` when
        the save carried host entries). Returns the cache — hand it to
        `ContinuousBatchingEngine(prefix_cache=...)`, whose free list
        excludes the cache-owned pages."""
        import json
        import os
        with open(os.path.join(path, "index.json")) as f:
            index = json.load(f)
        salt = index.get("salt")
        me = cls(decoder.page_size,
                 # saved salt wins: the persisted chain keys were
                 # hashed under it (pre-salt saves were all written by
                 # fingerprint-salted caches, so the fallback matches)
                 salt=(bytes.fromhex(salt) if salt is not None
                       else decoder.cache_fingerprint()),
                 capacity=(index.get("capacity") if capacity is None
                           else capacity),
                 tier=tier)
        want = index["fingerprint"]
        have = me._fingerprint_hex(decoder)
        if want != have:
            raise ValueError(
                f"cached KV at {path!r} was written by a decoder with "
                f"fingerprint {want} but this decoder is {have} — "
                "different weights/architecture/pool config would "
                "mount garbage KV; delete the cache dir or rebuild "
                "the matching decoder")
        data = np.load(os.path.join(path, "kv_pool.npz"))
        meta = index["arrays"]

        def get(name):
            # unpack_array owns the .copy() that keeps the loaded
            # pool donatable (frombuffer views are read-only)
            return unpack_array(data[name], meta[name])

        def pool(name):
            leaves = tuple(get(f"{name}.{i}")
                           for i in range(len([k for k in meta
                                               if k.startswith(name + ".")
                                               ])))
            return leaves if len(leaves) > 1 else leaves[0]

        decoder.load_pool_state({"kv_quant": index["kv_quant"],
                                 "k_pages": pool("k_pages"),
                                 "v_pages": pool("v_pages")})
        # bind the decoder the pool was just loaded onto: the engine
        # refuses to adopt this cache with any OTHER decoder (same
        # weights or not — its pool does not hold these pages), and
        # save() can read the pool with no engine attached
        import weakref
        me._decoder = weakref.ref(decoder)
        for key_hex, page, parent_hex in index["entries"]:
            k = bytes.fromhex(key_hex)
            parent = bytes.fromhex(parent_hex) if parent_hex else None
            e = _Entry(key=k, page=int(page), parent=parent, refs=0)
            me._entries[k] = e
            me._by_page[int(page)] = k
            me._lru[k] = None
        # children links in a SECOND pass: the saved LRU order can park
        # a child before its parent (the child's holder retired first),
        # and a link dropped here would break the eviction cascade —
        # the parent would evict without cascading to its (now
        # unreachable) descendant, stranding a device page
        for e in me._entries.values():
            if e.parent is not None and e.parent in me._entries:
                me._entries[e.parent].children.add(e.key)
        if index["host"]:
            if me.tier is None:
                from .kv_tier import HostKVTier
                cap = index.get("tier_capacity_bytes")
                me.tier = HostKVTier() if cap is None else \
                    HostKVTier(capacity_bytes=cap)
            for j, (key_hex, leaves) in enumerate(index["host"]):
                payload = {part: tuple(get(f"host.{j}.{part}.{i}")
                                       for i in range(leaves[part]))
                           for part in ("k", "v")}
                me.tier.put(bytes.fromhex(key_hex), payload)
        return me
