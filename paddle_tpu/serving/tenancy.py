"""Tenancy: SLO classes, preemption by page-spill, and multi-LoRA in
one ragged horizon.

The single-tenant stack (PRs 8-13) treats every request identically —
which is not how heavy mixed traffic arrives (the serving-under-real-
traffic axis the Gemma-on-TPU comparison benchmarks engines on,
PAPERS.md arxiv 2605.25645). This module makes the request the unit of
POLICY while reusing every mechanism the stack already has:

- **SLO classes.** Requests carry a `tenant` + `slo` — `"latency"`
  (interactive: the queue-wait/TTFT tail is the product) or
  `"throughput"` (batch: aggregate tokens/s is). `TenantEngine` keeps
  latency requests at the front of the admission queue (throughput
  requests BACKFILL behind them), and `TenantScheduler` composes
  horizons per class: a latency prompt's suffix drains at the FULL
  priced chunk budget with the horizon clamped to the ticks it needs,
  and latency-present horizons cap at `cost_model.slo_horizon` — the
  per-class sync-overhead budget (`SLO_SYNC_FRAC`) priced through the
  SAME mixed-tick roofline as everything else, so the per-class p99
  targets (`slo_p99_target_s`) are roofline-DERIVED, not hand-tuned.
- **Preemption by page-spill.** When a latency admission can't get
  pages, a throughput victim is preempted: its full KV blocks PARK
  into the prefix cache (exactly PR 8's publish/park machinery, reused
  as a scheduler primitive) — whence pool pressure spills them through
  the `HostKVTier` (PR 13's batched spill) — its partial tail frees,
  and the request requeues with its generated prefix as the resume
  prompt. Resume is a PLAIN admission: the parked chain re-mounts (or
  restores from host via the priced `kv_restore_s`-vs-recompute
  decision, or re-prefills — all byte-identical by the write-time
  (request, position) discipline), and generation continues with the
  same (seed, rid, position) sampling keys. A preempted-and-resumed
  request's stream is therefore BYTE-IDENTICAL to its never-preempted
  twin (fuzz-pinned in tests/test_tenancy.py).
- **Multi-LoRA.** Dozens of fine-tuned variants batch into ONE ragged
  horizon: per-row adapter ids gather low-rank qkv deltas over the
  shared base weights per TOKEN (`decoder._lora_delta` — the packed
  layout's `row_ids` idiom applied to weights), so serving k variants
  costs one program, not k engines. Per-adapter `adapter_salt`
  fingerprints fold into the prefix-cache chain keys: pages never
  alias across variants (audited — MEM-PAGE-REFCOUNT's slot_adapters
  rows), while sharing WITHIN a variant stays sound.
- **Accounting.** Per-tenant `TenantStats` (requests/tokens/occupancy/
  preemptions + queue-wait/TTFT windows), per-class pooled p50/p99
  next to the roofline targets, Jain-fairness over token shares
  (`TenantEngine.tenancy_summary`), engine-level
  `ServeStats.preemptions/resumes`, and flight-recorder tenant span
  attribution (submit records carry tenant/slo; `export_chrome_trace`
  groups request rows into one pid per tenant) plus preempt/resume
  instants that `validate_chrome_trace` checks against the request's
  span.
"""
import collections
import math
from dataclasses import dataclass, field

import numpy as np

from .engine import ContinuousBatchingEngine
from .scheduler import RaggedScheduler
from .stats import _window

__all__ = ["SLO_LATENCY", "SLO_THROUGHPUT", "TenantStats",
           "TenantScheduler", "TenantEngine", "PrecisionRoutedEngine",
           "make_lora_bank", "summarize_tenancy"]

SLO_LATENCY = "latency"
SLO_THROUGHPUT = "throughput"


def summarize_tenancy(tenants, slo_targets_s=None, preemptions=0,
                      resumes=0):
    """THE tenancy-summary math, over any {(tenant, slo):
    TenantStats} map: per-tenant ledgers (sorted keys), per-class
    pooled p50/p99 tails next to the roofline-derived targets, and
    Jain fairness over per-tenant token shares. One implementation
    for `TenantEngine.tenancy_summary` (its own `_tenants`) and the
    fleet's pooled view (`serving.fleet.FleetRouter.tenancy_summary`
    merges per-replica TenantStats first, then calls this) — a
    1-replica fleet therefore reproduces the single engine's numbers
    bit-for-bit, by construction rather than by parallel code."""
    rows = [tenants[k].summary() for k in sorted(tenants)]
    classes = {}
    for slo in (SLO_LATENCY, SLO_THROUGHPUT):
        ttft = [v for ts in tenants.values()
                if ts.slo == slo for v in ts.ttft_s]
        qw = [v for ts in tenants.values()
              if ts.slo == slo for v in ts.queue_wait_s]
        row = {}
        if ttft:
            row["ttft_p50_ms"] = round(
                float(np.percentile(ttft, 50)) * 1e3, 3)
            row["ttft_p99_ms"] = round(
                float(np.percentile(ttft, 99)) * 1e3, 3)
        if qw:
            row["queue_wait_p99_ms"] = round(
                float(np.percentile(qw, 99)) * 1e3, 3)
        if slo_targets_s is not None:
            row["roofline_target_ms"] = round(
                slo_targets_s[slo] * 1e3, 4)
        if row:
            classes[slo] = row
    # Jain's index over per-TENANT token shares (a tenant active in
    # both SLO classes is ONE entity — its ledgers merge here):
    # 1.0 = every tenant got an equal share, 1/n = one got it all
    by_tenant = {}
    for ts in tenants.values():
        if ts.requests:
            by_tenant[ts.tenant] = by_tenant.get(ts.tenant, 0) + ts.tokens
    toks = list(by_tenant.values())
    fairness = None
    if toks and sum(toks):
        fairness = round(
            (sum(toks) ** 2) / (len(toks) * sum(t * t
                                                for t in toks)), 4)
    return {"tenants": rows, "classes": classes,
            "fairness_jain": fairness,
            "preemptions": preemptions, "resumes": resumes}


def make_lora_bank(cfg, n_adapters, rank=4, seed=0, scale=0.05):
    """Random low-rank adapter bank for tests and benches: `n_adapters`
    (A [L, h, r], B [L, r, 3*H*D]) pairs over a GPT config — the shape
    `PagedGPTDecoder.attach_adapters` consumes. Deterministic in
    `seed`; `scale` keeps the deltas small enough that adapted streams
    stay coherent but distinct from the base model's."""
    rng = np.random.RandomState(seed)
    L, h = cfg.num_layers, cfg.hidden_size
    hd3 = 3 * cfg.num_heads * cfg.head_dim
    out = []
    for _ in range(int(n_adapters)):
        a = rng.randn(L, h, rank).astype(np.float32) * scale
        b = rng.randn(L, rank, hd3).astype(np.float32) * scale
        out.append((a, b))
    return out


@dataclass
class TenantStats:
    """One tenant's serving ledger (the per-tenant slice of ServeStats;
    counters lifetime, windows bounded like stats._STATS_WINDOW)."""
    tenant: str
    slo: str
    requests: int = 0
    completed: int = 0
    tokens: int = 0              # generated tokens of retired requests
    preemptions: int = 0
    resumes: int = 0
    queue_wait_s: collections.deque = field(default_factory=_window)
    ttft_s: collections.deque = field(default_factory=_window)
    occupancy: collections.deque = field(default_factory=_window)

    def summary(self):
        d = {"tenant": self.tenant, "slo": self.slo,
             "requests": self.requests, "completed": self.completed,
             "tokens": self.tokens}
        if self.preemptions or self.resumes:
            d["preemptions"] = self.preemptions
            d["resumes"] = self.resumes
        if self.occupancy:
            d["mean_slot_share"] = round(
                float(np.mean(self.occupancy)), 4)
        for name, win in (("queue_wait", self.queue_wait_s),
                          ("ttft", self.ttft_s)):
            if win:
                d[f"{name}_p50_ms"] = round(
                    float(np.percentile(win, 50)) * 1e3, 3)
                d[f"{name}_p99_ms"] = round(
                    float(np.percentile(win, 99)) * 1e3, 3)
        return d


class TenantScheduler(RaggedScheduler):
    """Class-aware horizon composition over the base chunk-admission
    scheduler: per-slot SLO classes (`set_slo`), a latency-class
    horizon cap priced by `cost_model.slo_horizon` (the latency tier
    deliberately syncs more often — admission and preemption only
    happen at horizon boundaries), and a width policy where a latency
    prefill drains at the FULL priced chunk budget while throughput
    prefills keep the base min-cover policy. The per-class p99 targets
    (`slo_targets_s`) come from `cost_model.slo_p99_target_s` — the
    same `ragged_tick_roofline_s` pricing as the chunk budget, so
    nothing here is a hand-tuned constant."""

    def __init__(self, decoder, chunk_tokens=None, k_max=None,
                 host_sync_s=None, chip=None):
        super().__init__(decoder, chunk_tokens=chunk_tokens,
                         k_max=k_max, host_sync_s=host_sync_s,
                         chip=chip)
        from ..cost_model import (measured_host_sync_s, slo_horizon,
                                  slo_p99_target_s)
        hbm = decoder.step_hbm_bytes()
        sync = (measured_host_sync_s() if host_sync_s is None
                else host_sync_s)
        k_lat = min(self.k_max, slo_horizon(
            hbm, SLO_LATENCY, host_sync_s=sync, chip=chip,
            chunk_tokens=self.chunk_tokens,
            flops_per_token=self.flops_per_token))
        # pow2-normalize DOWN like plan()'s k bucketing, so the clamp
        # is exactly a dispatchable horizon length
        self.k_latency = 1
        while self.k_latency * 2 <= k_lat:
            self.k_latency *= 2
        self.slo_targets_s = {
            slo: slo_p99_target_s(hbm, slo, host_sync_s=sync, chip=chip,
                                  chunk_tokens=self.chunk_tokens,
                                  flops_per_token=self.flops_per_token)
            for slo in (SLO_LATENCY, SLO_THROUGHPUT)}
        self._slo = {}               # slot -> slo class
        self._lat_queued = False

    def set_slo(self, slot, slo):
        self._slo[slot] = slo

    def retire(self, slot):
        super().retire(slot)
        self._slo.pop(slot, None)

    def note_queue(self, latency_waiting):
        """The engine's per-round signal: a latency request is WAITING
        in the queue — cap the next horizon at the latency-class K so
        its admission boundary arrives within the class target."""
        self._lat_queued = bool(latency_waiting)

    def _compose(self, live):
        lat_live = [s for s in live if self._slo.get(s) == SLO_LATENCY]
        lat_pf = [s for s in lat_live if self._pf_left[s]]
        if lat_pf:
            # latency suffixes pre-empt the chunk budget: w is sized to
            # the LATENCY streams alone (min-cover pow2, capped at the
            # priced budget — a longer throughput suffix no longer
            # stretches the drain), and the horizon clamps to the
            # ticks the latency stream needs so its first token lands
            # at the earliest sync. Throughput prefill rows BACKFILL
            # the same ticks with their min(left, w) shares.
            pf_max = max(int(self._pf_left[s]) for s in lat_pf)
            w = 1
            while w < min(self.chunk_tokens, pf_max):
                w *= 2
            k_limit = min(self.k_latency,
                          max(1, math.ceil(pf_max / w)))
            return w, k_limit
        w, k_limit = super()._compose(live)
        if lat_live or self._lat_queued:
            k_limit = min(k_limit, self.k_latency)
        return w, k_limit


class TenantEngine(ContinuousBatchingEngine):
    """Multi-tenant continuous batching: the base ragged engine with
    per-request (tenant, slo) classes, latency-first admission with
    throughput backfill, preemption by page-spill, per-tenant
    accounting, and multi-LoRA via per-request adapter ids (the
    decoder must carry a bank — `attach_adapters` — for nonzero ids).
    Always ragged: the preemption/resume discipline rides the chunked
    admission path."""

    def __init__(self, decoder, eos_token_id=None, max_new_tokens=64,
                 k_max=None, host_sync_s=None, prefix_cache=None,
                 chunk_tokens=None, scheduler=None, trace=None,
                 packed=None, host_tier=None, tier_policy="auto",
                 preemption=True):
        if scheduler is None:
            scheduler = TenantScheduler(decoder,
                                        chunk_tokens=chunk_tokens,
                                        k_max=k_max,
                                        host_sync_s=host_sync_s)
        super().__init__(decoder, eos_token_id, max_new_tokens,
                         k_max=k_max, host_sync_s=host_sync_s,
                         prefix_cache=prefix_cache, ragged=True,
                         chunk_tokens=chunk_tokens, scheduler=scheduler,
                         trace=trace, packed=packed,
                         host_tier=host_tier, tier_policy=tier_policy)
        self.preemption = bool(preemption)
        self._rid_tenant = {}        # rid -> (tenant, slo)
        self._rid_prompt = {}        # rid -> token list (resume prefix)
        self._tenants = {}           # (tenant, slo) -> TenantStats
        self._resumed = set()        # rids requeued by preemption
        self._freeze_slots = set()   # preempted slots to freeze on dev
        self._submit_meta = ("default", SLO_THROUGHPUT)
        if self.trace is not None:
            self.trace.meta["tenancy"] = True

    # ------------------------------------------------------- submission

    def submit(self, prompt_ids, tenant="default", slo=SLO_THROUGHPUT,
               adapter=None):
        """Queue one prompt under a tenant + SLO class. `slo="latency"`
        requests admit ahead of the throughput backlog (and may
        preempt throughput slots under pool pressure);
        `slo="throughput"` requests backfill. `adapter` selects a LoRA
        variant (see the base engine)."""
        if slo not in (SLO_LATENCY, SLO_THROUGHPUT):
            raise ValueError(
                f"slo must be {SLO_LATENCY!r} or {SLO_THROUGHPUT!r}, "
                f"got {slo!r}")
        self._submit_meta = (str(tenant), slo)
        return super().submit(prompt_ids, adapter=adapter)

    def _register_request(self, ids, adapter=0, trace_fields=None):
        tenant, slo = self._submit_meta
        fields = dict(trace_fields or {})
        fields.update(tenant=tenant, slo=slo)
        rid = super()._register_request(ids, adapter=adapter,
                                        trace_fields=fields)
        self._rid_tenant[rid] = (tenant, slo)
        self._rid_prompt[rid] = list(ids)
        self._tenant(tenant, slo).requests += 1
        if slo == SLO_LATENCY:
            # latency requests queue ahead of the throughput backlog
            # (FIFO among themselves)
            entry = self._queue.pop()
            self._queue.insert(self._latency_cut(), entry)
        return rid

    def _latency_cut(self):
        """Index one past the queue's latency section (latency entries
        are kept contiguous at the front)."""
        i = 0
        while i < len(self._queue) and \
                self._slo_of(self._queue[i][0]) == SLO_LATENCY:
            i += 1
        return i

    def _slo_of(self, rid):
        return self._rid_tenant.get(rid, ("", SLO_THROUGHPUT))[1]

    def _tenant(self, tenant, slo):
        key = (tenant, slo)
        ts = self._tenants.get(key)
        if ts is None:
            ts = self._tenants[key] = TenantStats(tenant=tenant, slo=slo)
        return ts

    def _tenant_of(self, rid):
        tenant, slo = self._rid_tenant.get(rid,
                                           ("default", SLO_THROUGHPUT))
        return self._tenant(tenant, slo)

    # ------------------------------------------------------- accounting

    def _note_queue_wait(self, rid, dt):
        super()._note_queue_wait(rid, dt)
        self._tenant_of(rid).queue_wait_s.append(dt)

    def _note_ttft(self, rid, dt):
        super()._note_ttft(rid, dt)
        self._tenant_of(rid).ttft_s.append(dt)

    def _note_resident(self):
        super()._note_resident()
        S = self.d.max_batch
        counts = {}
        for s in range(S):
            rid = self._slot_req[s]
            if rid is None:
                continue
            key = self._rid_tenant.get(rid)
            if key is not None:
                counts[key] = counts.get(key, 0) + 1
        for key, n in counts.items():
            self._tenant(*key).occupancy.append(n / S)

    def _retire(self, slot):
        rid = self._slot_req[slot]
        if rid is not None:
            ts = self._tenant_of(rid)
            ts.completed += 1
            ts.tokens += len(self._outputs.get(rid, ()))
            self._rid_tenant.pop(rid, None)
            self._rid_prompt.pop(rid, None)
            self._resumed.discard(rid)
        super()._retire(slot)

    def tenancy_summary(self):
        """Per-tenant ledgers + per-class pooled tails next to the
        scheduler's roofline-derived targets + fairness: the
        multi-tenant observability front door (the bench's JSON line
        and debug.serving_report read it). The math lives in
        `summarize_tenancy` — shared with the fleet's pooled view."""
        return summarize_tenancy(
            self._tenants,
            slo_targets_s=getattr(self.scheduler, "slo_targets_s",
                                  None),
            preemptions=self.stats.preemptions,
            resumes=self.stats.resumes)

    # ------------------------------------------------------- scheduling

    def _admit_ragged(self):
        # slot-exhaustion preemption: a latency head facing a fully
        # occupied slot table preempts for the SLOT itself — the
        # page-shortage path (`_admission_blocked`) never runs when
        # the admission loop finds no free slot to try
        if self.preemption and self._queue and \
                self._slo_of(self._queue[0][0]) == SLO_LATENCY and \
                all(r is not None for r in self._slot_req):
            victim = self._pick_victim()
            if victim is not None:
                self._preempt(victim)
        plans = super()._admit_ragged()
        sched = self.scheduler
        for slot, rid, _suffix in plans:
            if hasattr(sched, "set_slo"):
                sched.set_slo(slot, self._slo_of(rid))
            if rid in self._resumed:
                self._resumed.discard(rid)
                self.stats.resumes += 1
                self._tenant_of(rid).resumes += 1
                if self.trace is not None:
                    self.trace.record(
                        "resume", rid=rid, slot=slot,
                        tokens=len(self._outputs.get(rid, ())))
        if hasattr(sched, "note_queue"):
            sched.note_queue(any(self._slo_of(r) == SLO_LATENCY
                                 for r, _ in self._queue))
        return plans

    def _merge_carry_ragged(self, carry, plans):
        if carry is not None and self._freeze_slots:
            # a preempted slot's device row must FREEZE (its writes
            # route to scratch, its filler ticks stop consuming
            # budget) until a new admission revives the slot — applied
            # BEFORE the merge so a same-round re-admission into the
            # slot wins
            import jax.numpy as jnp
            tokens, lens, done, rem, pend, pend_n = carry
            idx = jnp.asarray(sorted(self._freeze_slots), jnp.int32)
            done = done.at[idx].set(True)
            pend_n = pend_n.at[idx].set(0)
            carry = (tokens, lens, done, rem, pend, pend_n)
        self._freeze_slots.clear()
        return super()._merge_carry_ragged(carry, plans)

    # ------------------------------------------------------- preemption

    def _admission_blocked(self, rid, need):
        """A latency head that can't get pages preempts a throughput
        victim (pages park/spill — `_preempt`) and returns False so
        the admission replans; anything else keeps the base
        head-of-line wait."""
        if not self.preemption or self._slo_of(rid) != SLO_LATENCY:
            return True
        victim = self._pick_victim()
        if victim is None:
            return True
        self._preempt(victim)
        return False

    def _pick_victim(self):
        """The throughput-tier slot with the most remaining budget
        (fewest tokens banked — the cheapest stream to re-drive if the
        parked chain degrades), decode-phase only: a mid-prefill
        slot's device-side chunk progress is not host-observable, so
        its parkable span is unknown."""
        best = None
        for s in range(self.d.max_batch):
            rid = self._slot_req[s]
            if rid is None or self._slo_of(rid) != SLO_THROUGHPUT:
                continue
            emitted = len(self._outputs.get(rid, ())) - \
                self._emit_base.get(rid, 0)
            if emitted <= 0:
                continue                 # still prefilling
            rem = self._budget_left(s)
            if rem <= 0:
                continue                 # retiring at the next sync
            if best is None or (rem, s) > best[0]:
                best = ((rem, s), s)
        return None if best is None else best[1]

    def _preempt(self, slot):
        """Preemption by page-spill: park the victim's full KV blocks
        in the prefix cache (insert under their chain keys, then
        release — refcount-0 pages PARK, and pool pressure spills them
        through the host tier exactly like any parked page), free the
        partial tail, requeue the request with prompt+generated as its
        resume prefix, and freeze the slot's device row. The resumed
        request's continuation re-mounts (or restores, or recomputes)
        the same write-time bytes and draws with the same (seed, rid,
        position) keys, so its stream is byte-identical to the
        never-preempted twin."""
        rid = self._slot_req[slot]
        outputs = self._outputs.get(rid, [])
        # _rid_prompt holds the ORIGINAL prompt for the request's whole
        # life — the resume prompt is always original + cumulative
        # outputs, derived fresh here (storing the derived prompt back
        # would duplicate the pre-preemption prefix on a SECOND
        # preemption: full = (P+gen1) + (gen1+gen2) — test-pinned)
        full = self._rid_prompt[rid] + list(outputs)
        L = int(self._lens[slot])        # consumed positions (host)
        ps = self.d.page_size
        n_full = L // ps
        pages = self._slot_pages[slot]
        shared = self._slot_shared[slot]
        parked = 0
        freed = []
        if self.cache is not None:
            keys = self.cache.block_keys(
                full[:L], extra_salt=self.d.adapter_salt(
                    self._rid_adapter.get(rid, 0)))
            # pass 1: INSERT private full blocks under their chain
            # keys while every parent is still held (mounted shared,
            # or inserted just above) — publish-stop at the first
            # refusal, exactly like _publish_blocks
            owned = []                   # pages to release in pass 2
            stopped = False
            for b in range(n_full):
                p = pages[b]
                if p in shared:
                    owned.append(p)
                elif not stopped and self.cache.insert(
                        keys[b], p, parent=keys[b - 1] if b else None):
                    owned.append(p)
                else:
                    stopped = True
                    freed.append(p)
            # pass 2: drop this request's references — every parked
            # block is now reclaimable (and spillable) cache property
            for p in owned:
                self.cache.release_page(p)
            parked = len(owned)
        else:
            freed.extend(pages[:n_full])
        freed.extend(pages[n_full:])     # partial tail: recomputed at
        self._free.extend(freed)         # resume, byte-identically
        # requeue at the front of the throughput section, AFTER any
        # earlier-preempted victims already waiting there (FIFO among
        # victims: first interrupted, first resumed)
        self._emit_base[rid] = len(outputs)
        i = self._latency_cut()
        while i < len(self._queue) and \
                self._queue[i][0] in self._resumed:
            i += 1
        self._resumed.add(rid)
        self._queue.insert(i, (rid, full))
        # release the slot (NOT _retire: the request is not done — no
        # completed count, rid bookkeeping kept) and freeze its device
        # row until a new admission revives it
        self._release_slot(slot)
        self._freeze_slots.add(slot)
        self.stats.preemptions += 1
        ts = self._tenant_of(rid)
        ts.preemptions += 1
        if self.trace is not None:
            self.trace.record("preempt", rid=rid, slot=slot,
                              tenant=self._rid_tenant[rid][0],
                              tokens=len(outputs), parked=parked,
                              freed=len(freed))


class PrecisionRoutedEngine:
    """Per-SLO-class KV precision policy: ONE logical engine whose
    latency and throughput tiers run pools of DIFFERENT quant widths —
    e.g. ``kv_precision={"latency": "int8", "throughput": "int4"}``
    serves interactive traffic from the wider (more accurate) pool
    while the batch tier banks the nibble-packed pool's ~1.65x extra
    KV capacity. KV capacity-vs-quality becomes a scheduler knob, not
    a build flag.

    Mechanics: each distinct precision gets its own `PagedGPTDecoder`
    (its own physical pool) + `PrefixCache` salted by that decoder's
    `cache_fingerprint()` + `TenantEngine` (whose `TenantScheduler`
    prices the class horizon cap and p99 targets from THAT pool's
    `step_hbm_bytes()` — per-class admission capacity reflects the
    real byte stream, not a shared average). Classes sharing a
    precision share one engine. Pages can never alias across
    precision classes: the pools are physically separate arrays AND
    the fingerprint salt differs (`kv_quant` + pool leaf dtype are
    folded in), so even an external shared tier keys them apart.

    Request identity: ONE global rid counter spans the classes and is
    stamped into the owning engine's allocator before each submit
    (the `FleetRouter` idiom) — rid is the sampling-key id, so a
    request's stream is byte-identical to what a single-class engine
    would emit for the same (seed, rid, position) draws."""

    def __init__(self, model, kv_precision=None, eos_token_id=None,
                 max_new_tokens=64, num_pages=32, page_size=16,
                 max_batch=2, k_max=None, chunk_tokens=None,
                 prefix_cache=True, dec_kw=None, eng_kw=None):
        from .decoder import PagedGPTDecoder
        from .prefix_cache import PrefixCache
        kv_precision = dict(kv_precision or {})
        unknown = set(kv_precision) - {SLO_LATENCY, SLO_THROUGHPUT}
        if unknown:
            raise ValueError(
                f"kv_precision keys must be SLO classes "
                f"({SLO_LATENCY!r}/{SLO_THROUGHPUT!r}), got "
                f"{sorted(unknown)!r}")
        for slo in (SLO_LATENCY, SLO_THROUGHPUT):
            kv_precision.setdefault(slo, None)
        self.kv_precision = kv_precision
        self.decoders = {}           # slo -> PagedGPTDecoder
        self.engines = {}            # slo -> TenantEngine
        by_quant = {}                # quant -> engine (shared pools)
        for slo in (SLO_LATENCY, SLO_THROUGHPUT):
            quant = kv_precision[slo]
            if quant in by_quant:
                eng = by_quant[quant]
                self.decoders[slo] = eng.d
                self.engines[slo] = eng
                continue
            dec = PagedGPTDecoder(model, num_pages=num_pages,
                                  page_size=page_size,
                                  max_batch=max_batch, kv_quant=quant,
                                  **(dec_kw or {}))
            cache = PrefixCache(dec.page_size,
                                salt=dec.cache_fingerprint()) \
                if prefix_cache else None
            eng = TenantEngine(dec, eos_token_id=eos_token_id,
                               max_new_tokens=max_new_tokens,
                               k_max=k_max, chunk_tokens=chunk_tokens,
                               prefix_cache=cache, **(eng_kw or {}))
            by_quant[quant] = eng
            self.decoders[slo] = dec
            self.engines[slo] = eng
        self._next_rid = 0           # global rid: THE sampling identity
        self._rid_slo = {}

    def submit(self, prompt_ids, tenant="default", slo=SLO_THROUGHPUT,
               adapter=None):
        """Queue one prompt on its class's engine; returns the GLOBAL
        request id (unique across classes — streams keyed by it)."""
        if slo not in self.engines:
            raise ValueError(
                f"slo must be {SLO_LATENCY!r} or {SLO_THROUGHPUT!r}, "
                f"got {slo!r}")
        eng = self.engines[slo]
        gid = self._next_rid
        self._next_rid = gid + 1
        eng._next_id = gid           # rid IS the sampling key id
        rid = eng.submit(prompt_ids, tenant=tenant, slo=slo,
                         adapter=adapter)
        assert rid == gid, (rid, gid)
        self._rid_slo[gid] = slo
        return gid

    def _unique_engines(self):
        seen, order = set(), []
        for slo in (SLO_LATENCY, SLO_THROUGHPUT):
            eng = self.engines[slo]
            if id(eng) not in seen:
                seen.add(id(eng))
                order.append(eng)
        return order

    def run(self, on_sync=None):
        """Drain every class engine (latency first, then throughput,
        looped until no churn — `on_sync(router, engine)` callbacks
        may submit more work mid-run). Returns {global rid: token
        list} across all classes."""
        outputs = {}
        hookof = (lambda e: (lambda en: on_sync(self, en))) \
            if on_sync is not None else (lambda e: None)
        while True:
            progressed = False
            for eng in self._unique_engines():
                if eng._queue:
                    outputs.update(eng.run(on_sync=hookof(eng)))
                    progressed = True
            if not progressed:
                return outputs

    def class_capacity(self):
        """Per-class admission economics, each priced from its OWN
        pool: quant mode, per-token/per-step bytes, pool capacity in
        tokens, and the scheduler's roofline-derived latency horizon
        cap + p99 target. The observability hook the capacity bench
        and tests pin the policy through."""
        out = {}
        for slo in (SLO_LATENCY, SLO_THROUGHPUT):
            dec, eng = self.decoders[slo], self.engines[slo]
            out[slo] = {
                "kv_quant": dec.kv_quant,
                "kv_token_bytes": int(dec.kv_token_bytes *
                                      dec.cfg.num_layers),
                "step_hbm_bytes": dec.step_hbm_bytes(),
                "pool_tokens": (dec.num_pages - 1) * dec.page_size,
                "k_latency": eng.scheduler.k_latency,
                "slo_target_s": eng.scheduler.slo_targets_s[slo],
            }
        return out

    def tenancy_summary(self):
        """Pooled tenancy view over the class engines — the same
        merge-then-`summarize_tenancy` math as the fleet, with each
        class's roofline target taken from ITS OWN scheduler (they
        differ when the pools do: that asymmetry is the policy)."""
        merged = {}
        for eng in self._unique_engines():
            for key, ts in eng._tenants.items():
                m = merged.get(key)
                if m is None:
                    m = merged[key] = TenantStats(tenant=ts.tenant,
                                                  slo=ts.slo)
                m.requests += ts.requests
                m.completed += ts.completed
                m.tokens += ts.tokens
                m.preemptions += ts.preemptions
                m.resumes += ts.resumes
                m.queue_wait_s.extend(ts.queue_wait_s)
                m.ttft_s.extend(ts.ttft_s)
                m.occupancy.extend(ts.occupancy)
        targets = {
            slo: self.engines[slo].scheduler.slo_targets_s[slo]
            for slo in (SLO_LATENCY, SLO_THROUGHPUT)}
        return summarize_tenancy(
            merged, slo_targets_s=targets,
            preemptions=sum(e.stats.preemptions
                            for e in self._unique_engines()),
            resumes=sum(e.stats.resumes
                        for e in self._unique_engines()))
