"""Ragged chunk-admission scheduling: token-budgeted prefill chunks
inside the decode horizon.

The dispatch-separate engine paid for prompt admission with a
HOST-BLOCKING prefill: one big forward over the whole (uncached)
prompt, synced before the next decode horizon could dispatch — one
long prompt stalled every decoding slot in the batch (the ROADMAP's
"single biggest lever on serving throughput-under-load"). Ragged
serving (arxiv 2604.15464) removes the separate dispatch entirely:
the uncached suffix rides the SAME K-tick device-resident horizon as
the running decode slots (`PagedGPTDecoder.ragged_multi` — every tick
serves decode rows and w-token prefill-chunk rows through one body),
and this module owns the POLICY half:

- **Chunk budget w** — how many prompt tokens one tick may consume per
  prefilling slot. Priced by `cost_model.ragged_chunk_tokens`: the
  largest power of two whose compute leg hides under the decode tick's
  HBM roofline (`cost_model.ragged_tick_roofline_s` — while the chunk
  stays under the HBM leg, prompt tokens stream in at near-zero
  marginal tick time and the decode rows' latency jitter is bounded by
  one chunk, not one prompt).
- **Horizon K** — how many ticks to fuse per host sync, the
  `cost_model.decode_horizon` pricing extended with the mixed-tick
  roofline. Bucketed to powers of two (bounded compile count).
- **Per-slot tick accounting** — a prefilling slot's first
  ceil(suffix/w) - 1 ticks consume chunks without emitting a token
  (the tick that consumes the LAST chunk also samples the first
  generated token); the scheduler tracks how many of a dispatched
  horizon's ticks can EMIT per slot, so the engine's
  budget/inflight invariants (device `remaining` == host budget minus
  in-flight emissions) hold exactly as they did for pure decode.
"""
import math

import numpy as np

__all__ = ["RaggedScheduler", "HorizonPlan"]


class HorizonPlan:
    """One horizon's dispatch decision: `k` ticks at chunk width `w`,
    with `emit_ticks[slot]` = how many of the k ticks can emit a token
    for that slot (k minus its leading chunk-consuming ticks),
    `n_chunks` = prompt chunks consumed across all slots (the
    ServeStats ledger), and `t_tokens` = the PACKED dispatch bucket:
    the smallest power of two covering the horizon's largest per-tick
    token total (live decode rows pay 1, prefilling rows min(left, w);
    tick 0 is the max — per-row shares only shrink as prompts drain),
    floored at the slot count so pure-decode horizons always dispatch
    one stable [S] bucket. The packed engine's jit key is (k,
    t_tokens); the dense twin's is (k, w) — total-token bucketing is
    what collapses the 2-D (S, w) dispatch grid."""

    __slots__ = ("k", "w", "emit_ticks", "n_chunks", "prefill_rows",
                 "t_tokens")

    def __init__(self, k, w, emit_ticks, n_chunks, prefill_rows,
                 t_tokens=None):
        self.k = k
        self.w = w
        self.emit_ticks = emit_ticks
        self.n_chunks = n_chunks
        self.prefill_rows = prefill_rows
        self.t_tokens = t_tokens


class RaggedScheduler:
    """Chunk-admission scheduler for the mixed ragged horizon (see
    module docstring). Owns per-slot suffix accounting (`admit` /
    `retire`) and per-round planning (`plan`); the ENGINE owns pool,
    cache and output state and executes the plan."""

    def __init__(self, decoder, chunk_tokens=None, k_max=None,
                 host_sync_s=None, chip=None):
        from ..cost_model import (decode_horizon, ragged_chunk_tokens)
        self.d = decoder
        hbm = decoder.step_hbm_bytes()
        # matmul FLOPs one prompt token costs (the 2*params GPT rule —
        # same constant bench.py and prefill_ttft_s use)
        self.flops_per_token = 2.0 * decoder.cfg.num_params()
        if chunk_tokens is None:
            chunk_tokens = ragged_chunk_tokens(
                hbm, self.flops_per_token, chip=chip)
        # normalize the budget DOWN to a power of two: plan() buckets
        # the per-dispatch width to pow2, and rounding UP there would
        # exceed the per-tick token budget this parameter exists to
        # bound (the priced default is already pow2)
        ct = max(1, int(chunk_tokens))
        self.chunk_tokens = 1
        while self.chunk_tokens * 2 <= ct:
            self.chunk_tokens *= 2
        if k_max is None:
            k_max = decode_horizon(hbm, host_sync_s=host_sync_s,
                                   chip=chip,
                                   chunk_tokens=self.chunk_tokens,
                                   flops_per_token=self.flops_per_token)
        self.k_max = max(1, int(k_max))
        self._pf_left = np.zeros(decoder.max_batch, np.int64)
        self._restore_s = 0.0       # in-flight tiered-KV H2D (seconds)

    # ------------------------------------------------- tiered-KV restores

    def note_restore(self, seconds):
        """Admission just dispatched a host-tier page restore priced at
        `seconds` of H2D (`cost_model.kv_restore_s`). The mount is
        functionally ordered before the NEXT horizon's reads, so that
        horizon's wall time carries the wire cost — `take_restore_s`
        hands the accumulated price to the engine's horizon pricing so
        the drift ledger compares like with like instead of flagging a
        correctly restoring engine as mispriced."""
        self._restore_s += float(seconds)

    def take_restore_s(self):
        """Drain the pending restore price (called once per dispatched
        horizon — the H2D lands inside exactly one measured window)."""
        s, self._restore_s = self._restore_s, 0.0
        return s

    # ------------------------------------------------------ accounting

    def admit(self, slot, suffix_len):
        """Slot now owes `suffix_len` uncached prompt tokens to the
        horizon (post prefix-cache mount: cached spans never get
        here)."""
        self._pf_left[slot] = int(suffix_len)

    def retire(self, slot):
        self._pf_left[slot] = 0

    def prefilling(self, slot):
        return self._pf_left[slot] > 0

    def suffix_left(self, slot):
        """Uncached suffix tokens of `slot` not yet covered by a
        dispatched horizon (part of the scheduler's public surface —
        the engine's `_table_width` position bound consumes it, so a
        custom `scheduler=` override only needs admit/retire/
        prefilling/suffix_left/plan plus chunk_tokens/k_max)."""
        return int(self._pf_left[slot])

    def stall_ticks(self, slot, w=None):
        """Ticks of slot's horizon share that CANNOT emit yet: its
        chunk-consuming ticks minus the final one (which consumes the
        last chunk AND samples the first token)."""
        w = w or self.chunk_tokens
        left = int(self._pf_left[slot])
        return max(0, math.ceil(left / w) - 1) if left else 0

    # ---------------------------------------------------------- policy

    def _compose(self, live):
        """(w, k_limit) of the next horizon — the COMPOSITION half of
        `plan`, split out so class-aware schedulers
        (`tenancy.TenantScheduler`) can re-price it per SLO class
        without touching the budget/inflight accounting below.

        Width policy: a mixed horizon's w is the smallest power of two
        covering the longest pending suffix, capped at the priced
        chunk budget — EVERY row of a tick pays w-wide compute, so a
        5-token prompt must not inflate the whole batch to the cap.
        Length policy: a mixed horizon is clamped to the chunk ticks
        it actually needs (pure-decode horizons revert to w=1 and the
        full k_max), so decode rows never ride wide windows longer
        than the prompt stream requires."""
        pf_max = max((int(self._pf_left[s]) for s in live), default=0)
        if pf_max:
            w = 1
            while w < min(self.chunk_tokens, pf_max):
                w *= 2
            # just enough ticks to finish the longest pending stream
            k_limit = min(self.k_max,
                          max(max(math.ceil(int(self._pf_left[s]) / w)
                                  for s in live if self._pf_left[s]), 1))
        else:
            w = 1
            k_limit = self.k_max
        return w, k_limit

    def plan(self, live, budgets, inflight):
        """Plan one horizon. `live` maps slot -> rid for occupied
        slots, `budgets` slot -> tokens the slot may still emit (host
        view, excluding in-flight emissions — see the engine's
        `_budget_left`), `inflight` per-slot in-flight EMISSION ticks.
        Returns a HorizonPlan, or None when no slot can make progress
        (everything emittable is already in flight). Consumes the
        planned chunk spans from the per-slot accounting. Composition
        (w, k_limit) comes from `_compose` — see its docstring for the
        width/length policy; class-aware schedulers override it."""
        w, k_limit = self._compose(live)
        avail = {}
        for s in live:
            # useful ticks = non-emitting chunk ticks + emittable ticks
            # (the tick consuming the LAST chunk also emits, so it
            # counts once, under the budget — not under pf)
            a = self.stall_ticks(s, w) + budgets[s] - inflight[s]
            if a > 0:
                avail[s] = a
        if not avail:
            return None
        k = 1
        while k * 2 <= min(min(avail.values()), k_limit):
            k *= 2
        # PACKED dispatch bucket: tick 0's token total is the horizon
        # max (per-row shares only shrink as prompts drain to decode),
        # floored at the slot count — pure-decode horizons then always
        # dispatch the one [S] bucket the dense twin's [S, 1] layout
        # costs, instead of churning variants with the live count
        from .decoder import pow2_at_least
        total = sum(min(int(self._pf_left[s]), w) if self._pf_left[s]
                    else 1 for s in live)
        t_tokens = pow2_at_least(max(total, self.d.max_batch))
        emit_ticks, n_chunks, prefill_rows = {}, 0, 0
        for s in live:
            stall = self.stall_ticks(s, w)
            # capped at the slot's remaining budget so inflight tracks
            # the device's possible emissions EXACTLY (the invariant
            # `device remaining == budget - inflight` for live slots;
            # k can exceed a slot's own avail when another slot set it)
            emit_ticks[s] = min(max(0, k - stall),
                                max(0, budgets[s] - inflight[s]))
            left = int(self._pf_left[s])
            if left:
                prefill_rows += 1
                n_chunks += min(math.ceil(left / w), k)
                self._pf_left[s] = max(0, left - k * w)
        return HorizonPlan(k, w, emit_ticks, n_chunks, prefill_rows,
                           t_tokens=t_tokens)
