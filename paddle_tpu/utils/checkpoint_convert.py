"""Reference-checkpoint conversion — SURVEY item 22.

Loads `.pdparams` / `.pdopt` files produced by the reference's `paddle.save`
(python/paddle/framework/io.py: a pickle of {name: ndarray}, where values may
also appear in the paddle-2.1 `(tensor_name, ndarray)` tuple form, and the
pickle stream may reference paddle-internal classes we don't ship). Our layer
tree uses the reference's state-dict naming (dotted sublayer paths, BatchNorm
`_mean`/`_variance`, Linear weight `[in, out]`), so after normalization the
dict applies directly via `set_state_dict`.
"""
import io
import pickle

import numpy as np

__all__ = ["load_reference_state_dict", "apply_reference_checkpoint",
           "convert_checkpoint"]


class _Stub:
    """Placeholder for paddle-internal classes inside reference pickles."""

    def __init__(self, *args, **kwargs):
        self.args = args

    def __setstate__(self, state):
        self.state = state


class _TolerantUnpickler(pickle.Unpickler):
    """Resolves classes normally when possible; any paddle.* / *fluid* class
    that is missing here becomes a _Stub so the load never fails on framework
    internals (the arrays themselves are plain numpy)."""

    def find_class(self, module, name):
        try:
            return super().find_class(module, name)
        except Exception:
            return _Stub

    def persistent_load(self, pid):
        return _Stub(pid)


def _normalize(value):
    """ndarray | (name, ndarray) | Stub-wrapped -> ndarray (or None)."""
    if isinstance(value, np.ndarray):
        return value
    if isinstance(value, tuple) and len(value) == 2 \
            and isinstance(value[1], np.ndarray):
        return value[1]  # paddle-2.1 VarBase form: (tensor.name, ndarray)
    if isinstance(value, (int, float, np.number)):
        return np.asarray(value)
    if isinstance(value, _Stub):
        state = getattr(value, "state", None)
        if isinstance(state, dict):
            for v in state.values():
                if isinstance(v, np.ndarray):
                    return v
    return None


def load_reference_state_dict(path):
    """Load a reference .pdparams/.pdopt into {name: np.ndarray}."""
    with open(path, "rb") as f:
        obj = _TolerantUnpickler(io.BytesIO(f.read())).load()
    out = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                key = f"{prefix}.{k}" if prefix else str(k)
                arr = _normalize(v)
                if arr is not None:
                    out[key] = arr
                elif isinstance(v, (dict, list)):
                    walk(key, v)
        elif isinstance(node, list):
            for i, v in enumerate(node):
                key = f"{prefix}.{i}"
                arr = _normalize(v)
                if arr is not None:
                    out[key] = arr
                else:
                    walk(key, v)

    arr = _normalize(obj)
    if arr is not None:
        return {"value": arr}
    walk("", obj)
    return out


def apply_reference_checkpoint(model, path, strict=True, dtype=None):
    """Load a reference .pdparams and push it into a paddle_tpu Layer.

    Returns (missing_keys, unexpected_keys)."""
    import jax.numpy as jnp

    from ..framework.core import Tensor

    ref = load_reference_state_dict(path)
    own = model.state_dict()
    missing = [k for k in own if k not in ref]
    unexpected = [k for k in ref if k not in own]
    if strict and (missing or unexpected):
        raise ValueError(
            f"state mismatch: missing={missing[:5]}... ({len(missing)}), "
            f"unexpected={unexpected[:5]}... ({len(unexpected)})")
    converted = {}
    for k, v in ref.items():
        if k not in own:
            continue
        tgt = own[k]
        arr = np.asarray(v)
        if list(arr.shape) != list(tgt.shape):
            raise ValueError(f"shape mismatch for {k}: "
                             f"checkpoint {arr.shape} vs model {list(tgt.shape)}")
        want = jnp.dtype(dtype) if dtype is not None else tgt._value.dtype
        converted[k] = Tensor(jnp.asarray(arr).astype(want))
    model.set_state_dict(converted)
    return missing, unexpected


def convert_checkpoint(src_path, dst_path):
    """One-shot file conversion: reference .pdparams -> our paddle.save
    format (plain {name: ndarray} pickle both ends, normalized)."""
    sd = load_reference_state_dict(src_path)
    with open(dst_path, "wb") as f:
        pickle.dump(sd, f, protocol=4)
    return sorted(sd.keys())
