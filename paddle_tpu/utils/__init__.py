"""paddle_tpu.utils — reference python/paddle/utils (deprecated decorator,
unique_name, download stub, try_import, flops helper lives in hapi)."""
import functools
import importlib
import threading
import warnings

__all__ = ["deprecated", "try_import", "unique_name", "run_check", "download"]


def deprecated(update_to="", since="", reason="", level=0):
    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            warnings.warn(
                f"{fn.__name__} is deprecated since {since}; {reason} "
                f"{'use ' + update_to if update_to else ''}",
                DeprecationWarning, stacklevel=2)
            return fn(*a, **k)
        return wrapper
    return decorator


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"{module_name} is required") from e


class _UniqueName:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}

    def generate(self, key="tmp"):
        with self._lock:
            n = self._counters.get(key, 0)
            self._counters[key] = n + 1
        return f"{key}_{n}"

    def guard(self, new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            saved = dict(self._counters)
            try:
                yield
            finally:
                self._counters = saved
        return ctx()


unique_name = _UniqueName()


def run_check():
    """paddle.utils.run_check parity: verifies the accelerator works."""
    import jax
    import jax.numpy as jnp
    devs = jax.devices()
    x = jnp.ones((128, 128))
    (x @ x).block_until_ready()
    print(f"paddle_tpu is installed successfully! device(s): {devs}")
    return True


class download:
    @staticmethod
    def get_weights_path_from_url(url, md5sum=None):
        raise NotImplementedError(
            "zero-egress environment: place weights locally and load with "
            "set_state_dict / paddle.load")


def require_version(min_version, max_version=None):
    """Check the installed framework version — reference
    python/paddle/utils/install_check.py:require_version."""
    from .. import __version__

    def _tup(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())
    cur = _tup(__version__)
    if _tup(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required min {min_version}")
    if max_version is not None and _tup(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed max {max_version}")
    return True


from . import checkpoint_convert  # noqa: F401,E402
from .checkpoint_convert import (  # noqa: F401,E402
    apply_reference_checkpoint,
    convert_checkpoint,
    load_reference_state_dict,
)

from . import dlpack  # noqa: F401,E402

from . import cpp_extension  # noqa: F401,E402
