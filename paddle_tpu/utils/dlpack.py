"""paddle_tpu.utils.dlpack — reference python/paddle/utils/dlpack.py
(to_dlpack/from_dlpack over the fluid core capsule API).

Modern DLPack rides the `__dlpack__` protocol rather than bare PyCapsules:
`to_dlpack` returns a zero-copy exporter object any consumer
(torch.from_dlpack, np.from_dlpack, jax) accepts, and `from_dlpack`
accepts any such exporter (torch/numpy/cupy tensors included).
"""
from ..framework.core import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack exporter (zero-copy view of the device buffer).

    The returned object implements __dlpack__/__dlpack_device__; pass it
    straight to torch.from_dlpack / numpy.from_dlpack / from_dlpack."""
    import jax.numpy as jnp

    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def from_dlpack(ext):
    """DLPack exporter (torch/numpy/cupy/jax array) -> Tensor, zero-copy
    when the producer lives on a compatible device."""
    import jax

    if not hasattr(ext, "__dlpack__"):
        raise TypeError(
            "from_dlpack expects an object implementing the DLPack protocol "
            "(__dlpack__); pass the tensor itself, not a raw capsule")
    return Tensor(jax.dlpack.from_dlpack(ext))
