"""paddle_tpu.utils.cpp_extension — the custom-op extension API.

Reference counterpart: python/paddle/utils/cpp_extension/cpp_extension.py
(`setup()` at :51, `load()` at :736) where users JIT-compile a C++/CUDA
kernel and get a paddle op with autograd wired in.

TPU-first split of that capability:

* **Device compute** belongs in Pallas/JAX, not C++: `register_op` turns a
  user-written JAX/Pallas kernel (plus optional custom VJP) into a
  paddle-style op — Tensor in/out, recorded on the eager autograd tape,
  differentiable under functional `paddle.grad`/`jax.grad`, traceable
  under `jit.to_static`, serializable through `jit.save` (jax.export
  inlines custom_vjp calls) and the ONNX exporter (paddle_tpu/onnx.py
  inlines custom_vjp_call subjaxprs).
* **Host-side native code** (IO, decode, tokenize — anything outside the
  XLA graph) keeps the C++ path: `load()` JIT-compiles C++ sources with
  g++ (hash-gated rebuilds, like paddle_tpu/runtime/_build.py) and binds
  the exported functions via ctypes.

In-tree proof: ops/layer_norm.py registers its fused Pallas LayerNorm /
RMSNorm through this exact public path.
"""
import ctypes
import hashlib
import inspect
import os
import types

import jax

from ..framework.core import apply_op

__all__ = [
    "register_op", "get_op", "custom_ops",
    "load", "setup", "CppExtension", "CUDAExtension", "BuildExtension",
    "get_build_directory",
]

_REGISTRY = {}


def _ensure_intree():
    """In-tree kernels register as an import side effect of their op
    modules; make the documented names reliable even before the first
    layer_norm call."""
    from ..ops import layer_norm  # noqa: F401


class _CustomOpsModule(types.ModuleType):
    def __getattr__(self, name):
        _ensure_intree()
        try:
            return _REGISTRY[name]
        except KeyError:
            raise AttributeError(
                f"no custom op {name!r}; registered: {sorted(_REGISTRY)}"
            ) from None


# namespace module holding every registered op (reference `load()` returns
# a module of ops; registered ops live here under their given name)
custom_ops = _CustomOpsModule(
    "paddle_tpu.utils.custom_ops",
    "Registered custom ops (populated by register_op)")


class CustomOp:
    """A registered op: `op(...)` is the paddle-level call (Tensor in/out,
    tape-recorded); `op.raw(...)` is the jax-level kernel (arrays in/out,
    differentiable via jax.grad) for use inside already-jitted code."""

    def __init__(self, name, fn, vjp, fwd, static_argnames, doc):
        self.name = name
        self._fn = fn
        self._vjp = vjp
        self._fwd = fwd
        self._static = tuple(static_argnames)
        self._kernels = {}          # statics tuple -> jax callable
        sig = inspect.signature(fn)
        for p in sig.parameters.values():
            if p.kind not in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                raise ValueError(
                    f"register_op({name!r}): kernel parameters must be "
                    f"positional (got {p.kind.description} {p.name!r}); "
                    "wrap *args/**kwargs kernels in an explicit signature")
        self._sig = sig
        self._param_names = list(sig.parameters)
        self._defaults = {p.name: p.default for p in sig.parameters.values()
                          if p.default is not inspect.Parameter.empty}
        missing = set(self._static) - set(self._param_names)
        if missing:
            raise ValueError(
                f"register_op({name!r}): static_argnames {sorted(missing)} "
                f"not in kernel signature {self._param_names}")
        self.__doc__ = doc or fn.__doc__
        self.__name__ = name

    def _split(self, args, kwargs):
        # hand-rolled Signature.bind — this sits on hot eager paths
        # (nn.functional.layer_norm runs through here every call)
        names = self._param_names
        if len(args) > len(names):
            raise TypeError(
                f"custom op {self.name!r} takes {len(names)} arguments "
                f"({len(args)} given)")
        vals = dict(self._defaults)
        vals.update(zip(names, args))
        n_pos = len(args)
        for k, v in kwargs.items():
            if k not in self._sig.parameters:
                raise TypeError(
                    f"custom op {self.name!r} got unexpected keyword "
                    f"argument {k!r}")
            if k in names[:n_pos]:
                raise TypeError(
                    f"custom op {self.name!r} got multiple values for {k!r}")
            vals[k] = v
        if len(vals) != len(names):
            missing = [n for n in names if n not in vals]
            raise TypeError(
                f"custom op {self.name!r} missing arguments: {missing}")
        statics = tuple((k, vals[k]) for k in names if k in self._static)
        arrays = [vals[k] for k in names if k not in self._static]
        try:
            hash(statics)
        except TypeError:
            raise TypeError(
                f"custom op {self.name!r}: static argument values must be "
                f"hashable, got {statics}") from None
        return statics, arrays

    def _kernel_for(self, statics_key):
        k = self._kernels.get(statics_key)
        if k is not None:
            return k
        statics = dict(statics_key)
        array_names = [n for n in self._param_names if n not in self._static]
        fn, user_fwd, user_vjp = self._fn, self._fwd, self._vjp

        def call_fn(*arrays):
            return fn(**dict(zip(array_names, arrays)), **statics)

        if user_vjp is None:
            kernel = call_fn
        else:
            kernel = jax.custom_vjp(call_fn)

            def k_fwd(*arrays):
                if user_fwd is not None:
                    return user_fwd(*arrays, **statics)
                return call_fn(*arrays), arrays

            def k_bwd(res, g):
                grads = user_vjp(res, g, **statics)
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                if len(grads) != len(array_names):
                    raise ValueError(
                        f"custom op {self.name!r}: vjp returned "
                        f"{len(grads)} gradients for {len(array_names)} "
                        f"tensor inputs {array_names}")
                return tuple(grads)

            kernel.defvjp(k_fwd, k_bwd)
        kernel.__name__ = self.name  # eager-profiler op label
        kernel.__qualname__ = self.name
        self._kernels[statics_key] = kernel
        return kernel

    def raw(self, *args, **kwargs):
        """jax-level call: raw arrays in, raw array(s) out (no Tensor
        wrapping, no tape) — compose inside other kernels/jitted fns."""
        key, arrays = self._split(args, kwargs)
        return self._kernel_for(key)(*arrays)

    def __call__(self, *args, **kwargs):
        key, arrays = self._split(args, kwargs)
        return apply_op(self._kernel_for(key), *arrays)


def register_op(name, fn, vjp=None, fwd=None, static_argnames=(),
                doc=None, override=False):
    """Register a JAX/Pallas kernel as a paddle-style custom op.

    Args:
        name: op name; the op becomes `custom_ops.<name>` and is
            retrievable via `get_op(name)`.
        fn: the kernel — a pure function of arrays (Pallas `pallas_call`
            wrappers, plain jnp code, anything jax-traceable). Parameters
            named in `static_argnames` are compile-time configuration
            (hashable); all others are tensor inputs.
        vjp: optional backward rule `vjp(residuals, out_grad, **statics)
            -> tuple of input gradients` (one per tensor input). Without
            it the op is differentiated by jax's autodiff through `fn`.
        fwd: optional forward-for-grad `fwd(*arrays, **statics) -> (out,
            residuals)`; defaults to `(fn(...), arrays)`.
        static_argnames: kernel parameters treated as static config
            (a distinct jax kernel is cached per combination).
        override: allow re-registering an existing name.

    Returns the CustomOp. Eager calls record on the autograd tape (so
    `.backward()` flows); `op.raw` is the unwrapped jax-level callable.
    """
    if not override and name in _REGISTRY:
        raise ValueError(
            f"custom op {name!r} already registered "
            "(pass override=True to replace)")
    op = CustomOp(name, fn, vjp, fwd, static_argnames, doc)
    _REGISTRY[name] = op
    setattr(custom_ops, name, op)
    return op


def get_op(name):
    """Look up a registered custom op by name."""
    if name not in _REGISTRY:
        _ensure_intree()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"no custom op {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None


# ---------------------------------------------------------------------------
# C++ host-side extensions (JIT-compiled, ctypes-bound)
# ---------------------------------------------------------------------------

def get_build_directory():
    """Where JIT-compiled extension .so files land (reference
    cpp_extension.get_build_directory; env PADDLE_TPU_EXTENSION_DIR)."""
    d = os.environ.get("PADDLE_TPU_EXTENSION_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """A C++ source bundle for setup()/load() (reference CppExtension)."""

    def __init__(self, sources, extra_compile_args=(), extra_link_args=(),
                 name=None, **kw):
        self.name = name
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args)
        self.extra_link_args = list(extra_link_args)


def CUDAExtension(sources, **kw):
    """CUDA sources have no TPU meaning: device kernels are Pallas
    (`register_op`). Accepted and compiled as plain C++ host code so
    reference build scripts degrade gracefully — .cu files are rejected."""
    cu = [s for s in sources if s.endswith((".cu", ".cuh"))]
    if cu:
        raise ValueError(
            f"CUDAExtension: {cu} are CUDA kernels; on TPU write the "
            "device kernel in Pallas and register it with register_op()")
    return CppExtension(sources, **kw)


class BuildExtension:
    """No-op stand-in for the reference's setuptools build_ext subclass
    (compilation here is direct g++, no setuptools pipeline)."""

    @classmethod
    def with_options(cls, **kw):
        return cls


class _ExtensionModule(types.ModuleType):
    """What load() returns: declared functions as attributes + `.lib`."""

    def __init__(self, name, lib, so_path):
        super().__init__(name, f"JIT-compiled extension ({so_path})")
        self.lib = lib
        self.so_path = so_path


def _compile(name, sources, extra_flags, build_dir, verbose=False):
    # staleness is content-addressed: the source/flag hash is IN the .so
    # name, so a rebuilt source compiles to a fresh path; the atomic
    # write itself is runtime/_build.py's shared compile_so
    for s in sources:
        if not os.path.exists(s):
            raise FileNotFoundError(f"extension source not found: {s}")
    h = hashlib.sha256()
    for s in sorted(sources):
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(extra_flags).encode())
    so_path = os.path.join(build_dir, f"{name}_{h.hexdigest()[:16]}.so")
    if not os.path.exists(so_path):
        from ..runtime._build import compile_so
        compile_so(sources, so_path, extra_flags, verbose)
    return so_path


def load(name, sources, functions=None, extra_cxx_flags=(),
         extra_ldflags=(), build_directory=None, verbose=False, **kw):
    """JIT-compile C++ sources and return a module of bound functions
    (reference cpp_extension.load at :736).

    `functions` maps an exported (extern "C") symbol to its ctypes
    signature: {"fname": (restype, [argtypes...])}. Unlisted symbols stay
    reachable through `module.lib`. Host-side only — the returned
    functions run on CPU outside the XLA graph; device compute goes
    through register_op."""
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    so_path = _compile(name, list(sources),
                       [*extra_cxx_flags, *extra_ldflags], build_dir,
                       verbose)
    lib = ctypes.CDLL(so_path)
    mod = _ExtensionModule(name, lib, so_path)
    for fname, (restype, argtypes) in (functions or {}).items():
        cfunc = getattr(lib, fname)
        cfunc.restype = restype
        cfunc.argtypes = list(argtypes)
        setattr(mod, fname, cfunc)
    return mod


def setup(name=None, ext_modules=(), verbose=False, **kw):
    """Ahead-of-time build of CppExtension bundles into the build
    directory (reference cpp_extension.setup at :51 — the pip-install
    packaging half is setuptools' job; this performs the compile step and
    returns the built .so paths)."""
    paths = []
    for ext in ext_modules:
        ext_name = ext.name or name or "extension"
        paths.append(_compile(
            ext_name, ext.sources,
            [*ext.extra_compile_args, *ext.extra_link_args],
            get_build_directory(), verbose))
    return paths
