"""Global data-format (layout) switch.

TPU's fast path wants channels on the 128-lane minor dim (channel-last):
with NCHW tensors XLA materializes transposes around every conv, which can
dominate a conv net's step time. Rather than plumbing `data_format` through
every model constructor, `set_channels_last(True)` flips the DEFAULT layout
of every conv/norm/pool layer and functional whose `data_format` the caller
left unspecified — so any vision model runs channel-last end-to-end:

    paddle.nn.set_channels_last(True)
    model = paddle.vision.models.mobilenet_v2()   # NHWC throughout
    out = model(images_nhwc)

Explicit `data_format=...` arguments always win. The reference has no such
switch (CUDA favors NCHW); this is a TPU-first extension.
"""
__all__ = ["set_channels_last", "channels_last_enabled", "resolve_data_format"]

# PROCESS-global (layers snapshot their layout at construction, so a model
# built in one thread behaves identically when driven from another)
_state = {"flag": False}

_CHANNEL_FIRST = {1: "NCL", 2: "NCHW", 3: "NCDHW"}
_CHANNEL_LAST = {1: "NLC", 2: "NHWC", 3: "NDHWC"}


def set_channels_last(flag=True):
    """Make channel-last the default layout for layers/functionals that were
    not given an explicit data_format. Returns the previous setting."""
    prev = channels_last_enabled()
    _state["flag"] = bool(flag)
    return prev


def channels_last_enabled():
    return _state["flag"]


def resolve_data_format(data_format, n_spatial):
    """None -> the current default for n_spatial dims; explicit strings pass
    through untouched."""
    if data_format is not None:
        return data_format
    table = _CHANNEL_LAST if channels_last_enabled() else _CHANNEL_FIRST
    return table[n_spatial]
