"""Layer: the module system (reference python/paddle/fluid/dygraph/layers.py).

TPU-first twist: a Layer tree is also a *functional* model. `functional_call`
binds an arbitrary params pytree (e.g. tracers inside jax.jit, or sharded
arrays) to the tree, runs forward purely, and restores — so the same model
object serves eager debugging and compiled GSPMD training.
"""
from __future__ import annotations

import collections
import threading

import jax
import jax.numpy as jnp
import numpy as np

# sys.modules lookup: the attribute `framework.dtype` is shadowed by the
# dtype() function that paddle exposes at top level
import importlib

dtypes = importlib.import_module("paddle_tpu.framework.dtype")
from ..framework.core import Parameter, Tensor, _pause_tape
from ..framework.random import next_key

__all__ = ["Layer", "ParamAttr", "functional_call", "state_pytree", "load_state_pytree"]


class ParamAttr:
    """Parameter attribute bundle (reference python/paddle/fluid/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        return ParamAttr(initializer=attr)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        d = self.__dict__
        d["_parameters"] = collections.OrderedDict()
        d["_sub_layers"] = collections.OrderedDict()
        d["_buffers"] = collections.OrderedDict()
        d["_non_persistable_buffer_names"] = set()
        d["training"] = True
        d["_dtype"] = dtypes.dtype(dtype)
        d["_name_scope"] = name_scope or type(self).__name__.lower()
        d["_forward_pre_hooks"] = collections.OrderedDict()
        d["_forward_post_hooks"] = collections.OrderedDict()
        d["_state_dict_hooks"] = collections.OrderedDict()

    # -- attribute routing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            layers[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            params[name] = value
        elif layers is not None and name in layers:
            layers[name] = value
        elif buffers is not None and name in buffers:
            buffers[name] = value if isinstance(value, Tensor) or value is None else Tensor(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- forward -----------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        return self._dispatch(self.forward, *inputs, **kwargs)

    def _dispatch(self, forward, *inputs, **kwargs):
        """Hook-wrapped forward dispatch — the single source of hook
        semantics (jit.to_static routes its converted forward here)."""
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    def register_state_dict_hook(self, hook):
        """hook(state_dict) runs on every state_dict() result (reference
        layers.py register_state_dict_hook); a non-None return replaces
        the dict."""
        handle = _HookHandle(self._state_dict_hooks)
        self._state_dict_hooks[handle.id] = hook
        return handle

    def backward(self, *inputs):
        # reference layers.py: autograd owns backward; a Layer must not
        raise ValueError("Layer shouldn't implement backward")

    def clear_gradients(self):
        """Zero out every parameter's .grad (reference layers.py
        clear_gradients — the per-layer form of optimizer.clear_grad)."""
        for p in self.parameters():
            p.clear_grad()

    def create_tensor(self, name=None, persistable=None, dtype=None):
        """An empty tensor attached to this layer as a (by default
        non-persistable) buffer — reference layers.py create_tensor,
        typically filled later via set_value (set_value accepts any
        shape while the tensor is still empty). Defaults to the layer's
        dtype, matching create_parameter."""
        t = Tensor(jnp.zeros(
            (0,), dtypes.dtype(dtype) if dtype is not None else self._dtype))
        t._deferred_shape = True   # set_value fills any shape ONCE
        n = name or f"_generated_tensor_{len(self._buffers)}"
        self.register_buffer(n, t, persistable=bool(persistable))
        return t

    # deprecated reference spelling of create_tensor
    create_variable = create_tensor

    def to_static_state_dict(self, destination=None, include_sublayers=True,
                             use_hook=True):
        """state_dict that also includes NON-persistable buffers
        (reference layers.py to_static_state_dict: the static-graph
        export needs every buffer)."""
        return self._collect_state(destination, include_sublayers, use_hook,
                                   persistable_only=False, prefix="")

    # -- parameter management ----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from .initializer import Constant, XavierUniform

        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtypes.dtype(dtype) if dtype is not None else self._dtype
        init = attr.initializer or default_initializer or (
            Constant(0.0) if is_bias else XavierUniform())
        value = init(shape, dtype)
        p = Parameter(value, name=attr.name, trainable=attr.trainable)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- traversal ----------------------------------------------------------
    def children(self):
        yield from self._sub_layers.values()

    def named_children(self):
        yield from self._sub_layers.items()

    def sublayers(self, include_self=False):
        return [m for _, m in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        layers_set = layers_set if layers_set is not None else set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None or id(sub) in layers_set:
                continue
            layers_set.add(id(sub))
            sub_prefix = prefix + ("." if prefix else "") + name
            yield sub_prefix, sub
            yield from sub.named_sublayers(prefix=sub_prefix, layers_set=layers_set)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters()]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for layer_prefix, layer in self._traverse(prefix):
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (layer_prefix + ("." if layer_prefix else "") + name, p)

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for layer_prefix, layer in self._traverse(prefix):
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (layer_prefix + ("." if layer_prefix else "") + name, b)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers()]

    def _traverse(self, prefix=""):
        yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield from sub._traverse(prefix + ("." if prefix else "") + name)

    # -- mode / dtype -------------------------------------------------------
    def train(self):
        for _, layer in self._traverse():
            layer.__dict__["training"] = True
        return self

    def eval(self):
        for _, layer in self._traverse():
            layer.__dict__["training"] = False
        return self

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast(dtypes.dtype(dtype))
        return self

    def astype(self, dtype):
        self._cast(dtypes.dtype(dtype))
        return self

    def _cast(self, d, floating_only=True):
        for _, layer in self._traverse():
            layer.__dict__["_dtype"] = d
            for name, p in layer._parameters.items():
                if p is not None and (not floating_only or dtypes.is_floating_point_dtype(p.dtype)):
                    p._value = p._value.astype(d)
            for name, b in layer._buffers.items():
                if b is not None and (not floating_only or dtypes.is_floating_point_dtype(b.dtype)):
                    b._value = b._value.astype(d)

    def float(self):
        return self.astype(dtypes.float32)

    def bfloat16(self):
        return self.astype(dtypes.bfloat16)

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        """Parameters + persistable buffers, collected RECURSIVELY so
        that (a) each layer's own _non_persistable_buffer_names filters
        its own buffers — a sublayer's scratch buffer can't leak through
        an ancestor, nor can a same-named persistable one be dropped —
        and (b) every layer's state_dict hooks run on the ACCUMULATED
        destination with fully prefixed names, as the reference
        _state_dict_impl does (fluid/dygraph/layers.py:1322-1362), so
        hooks ported from reference code see the same dict shape.
        Shared/tied objects are emitted under EVERY structured name —
        the reference does not dedup here (dedup applies only to
        named_parameters/optimizer state), so weight-tied checkpoints
        round-trip with reference paddle."""
        return self._collect_state(destination, include_sublayers, use_hook,
                                   persistable_only=True, prefix="")

    def _collect_state(self, destination, include_sublayers, use_hook,
                       persistable_only, prefix):
        if destination is None:
            destination = collections.OrderedDict()
        for name, p in self._parameters.items():
            if p is not None:
                destination[prefix + name] = p
        for name, b in self._buffers.items():
            if b is None:
                continue
            if persistable_only and name in self._non_persistable_buffer_names:
                continue
            destination[prefix + name] = b
        if include_sublayers:
            for sname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                # reference protocol (layers.py:1349-1356): the child gets a
                # COPY of the accumulated dict and its hooks' return value is
                # MERGED back — so a descendant's filtering hook can see the
                # whole prefixed dict but cannot drop siblings' or ancestors'
                # entries; only hooks of the layer state_dict() was called on
                # (applied last, below, by replacement) can filter.
                destination_temp = destination.copy()
                destination_temp.update(sub._collect_state(
                    destination_temp, True, use_hook, persistable_only,
                    f"{prefix}{sname}."))
                destination = destination_temp
        if use_hook:
            for hook in self._state_dict_hooks.values():
                out = hook(destination)
                if out is not None:
                    destination = out
        return destination

    def set_state_dict(self, state_dict, use_structured_name=True):
        # hooks filter what gets SAVED; loading must see the raw surface
        # or a save-filtering hook silently blocks restoring those keys
        own = self.state_dict(use_hook=False)
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                src = state_dict[name]
                v = src._value if isinstance(src, Tensor) else jnp.asarray(np.asarray(src))
                t._value = v.astype(t.dtype).reshape(t._value.shape)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            lines.append(f"({name}): " + ("\n  ".join(sub_repr)))
        body = ""
        if extra:
            body = extra
        if lines:
            body = (body + "\n" if body else "") + "\n".join(lines)
            body = "\n  " + body.replace("\n", "\n  ") + "\n"
        return f"{type(self).__name__}({body})"

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)


class _HookHandle:
    _next_id = [0]

    def __init__(self, store):
        self.id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1
        self._store = store

    def remove(self):
        self._store.pop(self.id, None)


# -- functional bridge -------------------------------------------------------
def state_pytree(layer: Layer, trainable_only=False):
    """Collect {name: jax.Array} of parameters (and buffers unless
    trainable_only) — the pytree fed to jax transforms."""
    params = {}
    for name, p in layer.named_parameters():
        if not trainable_only or not p.stop_gradient:
            params[name] = p._value
    return params


def buffer_pytree(layer: Layer):
    return {name: b._value for name, b in layer.named_buffers()}


_buffer_sink = threading.local()


class collect_buffer_updates:
    """Context that collects buffer writes attempted under tracing (e.g.
    BatchNorm running stats): ops call `record_buffer_update(tensor, value)`
    instead of mutating, and the compiled-step owner (Trainer) carries the
    returned {id(tensor): (tensor, traced_value)} into its next-step consts."""

    def __enter__(self):
        self._prev = getattr(_buffer_sink, "sink", None)
        _buffer_sink.sink = {}
        return _buffer_sink.sink

    def __exit__(self, *exc):
        _buffer_sink.sink = self._prev
        return False


def record_buffer_update(tensor, value):
    """Record a pending buffer update if a collect_buffer_updates context is
    active. Returns True if recorded (the caller should skip eager mutation)."""
    sink = getattr(_buffer_sink, "sink", None)
    if sink is None:
        return False
    sink[id(tensor)] = (tensor, value)
    return True


def load_state_pytree(layer: Layer, values: dict):
    for name, p in layer.named_parameters():
        if name in values:
            p._value = values[name]
    for name, b in layer.named_buffers():
        if name in values:
            b._value = values[name]


class functional_call:
    """Run `layer(*args)` with `params` (a {name: array} pytree) temporarily
    bound — pure w.r.t. params, so it composes with jax.grad / jax.jit:

        params = state_pytree(model, trainable_only=True)
        def loss_fn(params, batch):
            with functional_call(model, params):
                return model(batch).mean()
        grads = jax.grad(loss_fn)(params, batch)

    Also callable directly: functional_call(model, params, x) -> out.
    """

    def __new__(cls, layer, params, *args, **kwargs):
        self = super().__new__(cls)
        self.layer = layer
        self.params = params
        if args or kwargs:
            with self:
                return layer(*args, **kwargs)
        return self

    def __enter__(self):
        self._saved = {}
        by_name = dict(self.params)
        for name, p in list(self.layer.named_parameters()) + list(self.layer.named_buffers()):
            if name in by_name:
                self._saved[name] = (p, p._value)
                p._value = by_name[name]
        self._pause = _pause_tape()
        self._pause.__enter__()
        return self.layer

    def __exit__(self, *exc):
        self._pause.__exit__(*exc)
        for name, (p, v) in self._saved.items():
            p._value = v
        return False
