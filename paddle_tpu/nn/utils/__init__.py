"""nn.utils — reference python/paddle/nn/utils/__init__.py
(weight_norm_hook.py, spectral_norm_hook.py, transform_parameters.py)."""
import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Parameter, Tensor, apply_op

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except(w, dim):
    """L2 norm of w over all axes except `dim` (dim=None reduces everything)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(w)))
    axes = tuple(a for a in range(w.ndim) if a != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=True))


class _WeightNormHook:
    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    def compute(self, layer):
        """Effective weight as a taped op of (g, v) so loss.backward()
        accumulates into weight_g.grad / weight_v.grad."""
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        dim = self.dim
        return apply_op(lambda vv, gv: vv * (gv / _norm_except(vv, dim)), v, g)

    def __call__(self, layer, inputs):
        object.__setattr__(layer, self.name, self.compute(layer))
        return None


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize `layer.name` as g * v / ||v|| — reference
    python/paddle/nn/utils/weight_norm_hook.py."""
    w = getattr(layer, name)
    arr = w._value if isinstance(w, Tensor) else jnp.asarray(w)
    g0 = _norm_except(arr, dim)
    # replace the original parameter with (g, v)
    del layer._parameters[name]
    layer.add_parameter(name + "_g", Parameter(g0))
    layer.add_parameter(name + "_v", Parameter(arr))
    hook = _WeightNormHook(name, dim)
    handle = layer.register_forward_pre_hook(hook)
    layer.__dict__.setdefault("_weight_norm_hooks", {})[name] = (hook, handle)
    hook(layer, ())  # materialize layer.<name> immediately
    return layer


def remove_weight_norm(layer, name="weight"):
    hooks = layer.__dict__.get("_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"weight_norm of '{name}' not found in {type(layer).__name__}")
    hook, handle = hooks.pop(name)
    w = hook.compute(layer)._value
    handle.remove()
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    if name in layer.__dict__:
        del layer.__dict__[name]
    layer.add_parameter(name, Parameter(w))
    return layer


class _SpectralNormHook:
    def __init__(self, name, n_power_iterations, dim, eps):
        self.name = name
        self.n = n_power_iterations
        self.dim = dim
        self.eps = eps

    def compute(self, layer):
        """W / sigma(W) with the power-iteration vectors detached (torch
        semantics); taped on weight_orig so gradients reach it."""
        w = getattr(layer, self.name + "_orig")
        dim, n_it, eps = self.dim, max(self.n, 1), self.eps
        u0 = layer.__dict__["_sn_u_" + self.name]

        def _f(arr):
            mat = jnp.moveaxis(arr, dim, 0).reshape(arr.shape[dim], -1)
            u = u0
            v = None
            for _ in range(n_it):
                v = jax.lax.stop_gradient(mat).T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = jax.lax.stop_gradient(mat) @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ (mat @ v)        # grad flows through mat here
            return arr / sigma

        out = apply_op(_f, w)
        # update the persistent power-iteration vector (host-side state)
        arr = w._value
        mat = jnp.moveaxis(arr, dim, 0).reshape(arr.shape[dim], -1)
        u = u0
        for _ in range(n_it):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        layer.__dict__["_sn_u_" + self.name] = u
        return out

    def __call__(self, layer, inputs):
        object.__setattr__(layer, self.name, self.compute(layer))
        return None


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    """Spectral normalization hook — reference
    python/paddle/nn/utils/spectral_norm_hook.py."""
    if dim is None:
        dim = 1 if type(layer).__name__ in ("Linear", "Embedding") else 0
    w = getattr(layer, name)
    arr = w._value if isinstance(w, Tensor) else jnp.asarray(w)
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", Parameter(arr))
    rows = arr.shape[dim]
    key = np.random.RandomState(0).normal(size=(rows,)).astype(np.float32)
    layer.__dict__["_sn_u_" + name] = jnp.asarray(key / (np.linalg.norm(key) + eps))
    hook = _SpectralNormHook(name, n_power_iterations, dim, eps)
    handle = layer.register_forward_pre_hook(hook)
    layer.__dict__.setdefault("_spectral_norm_hooks", {})[name] = (hook, handle)
    hook(layer, ())
    return layer


def parameters_to_vector(parameters, name=None):
    """Flatten a list of parameters into one 1-D tensor — reference
    python/paddle/nn/utils/transform_parameters.py."""
    parts = []
    for p in parameters:
        arr = p._value if isinstance(p, Tensor) else jnp.asarray(p)
        parts.append(arr.reshape(-1))
    return Tensor(jnp.concatenate(parts))


def vector_to_parameters(vec, parameters, name=None):
    """Slice a flat vector back into the given parameters (in place)."""
    arr = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if len(p.shape) else 1
        chunk = arr[offset:offset + n].reshape(p.shape)
        p._value = chunk.astype(p._value.dtype)
        offset += n
