"""Recurrent layers — reference python/paddle/nn/layer/rnn.py.

TPU-first: the time loop is a single lax.scan (one compiled XLA while-op with
static shapes) rather than the reference's per-step dygraph loop / cuDNN RNN.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply_op
from ..initializer import Uniform
from ..layer_base import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU", "RNNCellBase"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape[0], (list, tuple)):
            return tuple(Tensor(jnp.full((batch,) + tuple(s), init_value, jnp.float32))
                         for s in shape)
        return Tensor(jnp.full((batch,) + tuple(shape), init_value, jnp.float32))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               attr=weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               attr=weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], attr=bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], attr=bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def _f(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = apply_op(_f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               attr=weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               attr=weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], attr=bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], attr=bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def _f(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h_new, c_new = apply_op(_f, inputs, h, c, self.weight_ih, self.weight_hh,
                                self.bias_ih, self.bias_hh)
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               attr=weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               attr=weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], attr=bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], attr=bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(ic + r * hc)
            return (1 - z) * n + z * h
        h = apply_op(_f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh)
        return h, h


class RNN(Layer):
    """Wraps a cell into a scanned sequence op (reference RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        outs = []
        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        T = x.shape[0]
        states = initial_states
        time_range = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for t in time_range:
            out, states = self.cell(x[t], states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        from ...tensor.manipulation import stack
        y = stack(outs, axis=0)
        if not self.time_major:
            y = y.transpose([1, 0, 2])
        return y, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_fw, st_bw = (None, None) if initial_states is None else initial_states
        y_fw, s_fw = self.rnn_fw(inputs, st_fw)
        y_bw, s_bw = self.rnn_bw(inputs, st_bw)
        from ...tensor.manipulation import concat
        return concat([y_fw, y_bw], axis=-1), (s_fw, s_bw)


class _MultiLayerRNN(Layer):
    """num_layers × (optionally bidirectional) scanned recurrence. The whole
    stack runs as lax.scan per layer-direction — static shapes, one XLA loop."""

    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.time_major = time_major
        self.dropout = dropout
        num_dirs = 2 if self.bidirectional else 1
        self.state_components = 2 if self.MODE == "LSTM" else 1
        from .container import LayerList
        self.cells = LayerList()
        for layer in range(num_layers):
            for _ in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                if self.MODE == "LSTM":
                    self.cells.append(LSTMCell(in_sz, hidden_size))
                elif self.MODE == "GRU":
                    self.cells.append(GRUCell(in_sz, hidden_size))
                else:
                    self.cells.append(SimpleRNNCell(in_sz, hidden_size, activation))

    def _cell_step(self, cell):
        mode = self.MODE

        def step(params, carry, x_t):
            wi, wh, bi, bh = params
            if mode == "LSTM":
                h, c = carry
                gates = x_t @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
                g = jnp.tanh(g)
                c_new = f * c + i * g
                h_new = o * jnp.tanh(c_new)
                return (h_new, c_new), h_new
            if mode == "GRU":
                h = carry
                gi = x_t @ wi.T + bi
                gh = h @ wh.T + bh
                ir, iz, ic = jnp.split(gi, 3, axis=-1)
                hr, hz, hc = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                n = jnp.tanh(ic + r * hc)
                h_new = (1 - z) * n + z * h
                return h_new, h_new
            h = carry
            act = jnp.tanh if cell.activation == "tanh" else jax.nn.relu
            h_new = act(x_t @ wi.T + bi + h @ wh.T + bh)
            return h_new, h_new
        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        num_dirs = 2 if self.bidirectional else 1
        mode = self.MODE

        def _f(x, *flat_params):
            xs = x if self.time_major else jnp.swapaxes(x, 0, 1)  # [T,B,I]
            B = xs.shape[1]
            per_cell = 4
            finals_h, finals_c = [], []
            for layer in range(self.num_layers):
                dir_outs = []
                for d in range(num_dirs):
                    ci = layer * num_dirs + d
                    params = flat_params[ci * per_cell: (ci + 1) * per_cell]
                    cell = self.cells[ci]
                    step = self._cell_step(cell)
                    h0 = jnp.zeros((B, self.hidden_size), xs.dtype)
                    carry0 = (h0, h0) if mode == "LSTM" else h0
                    seq = jnp.flip(xs, 0) if d == 1 else xs

                    def body(carry, x_t, _step=step, _params=params):
                        return _step(_params, carry, x_t)
                    carry, ys = jax.lax.scan(body, carry0, seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    dir_outs.append(ys)
                    if mode == "LSTM":
                        finals_h.append(carry[0])
                        finals_c.append(carry[1])
                    else:
                        finals_h.append(carry)
                xs = jnp.concatenate(dir_outs, axis=-1) if num_dirs == 2 else dir_outs[0]
            y = xs if self.time_major else jnp.swapaxes(xs, 0, 1)
            h_stack = jnp.stack(finals_h, axis=0)
            if mode == "LSTM":
                return y, h_stack, jnp.stack(finals_c, axis=0)
            return y, h_stack

        flat = []
        for cell in self.cells:
            flat += [cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh]
        outs = apply_op(_f, inputs, *flat)
        if mode == "LSTM":
            y, h, c = outs
            return y, (h, c)
        y, h = outs
        return y, h


class SimpleRNN(_MultiLayerRNN):
    MODE = "RNN"


class LSTM(_MultiLayerRNN):
    MODE = "LSTM"


class GRU(_MultiLayerRNN):
    MODE = "GRU"
