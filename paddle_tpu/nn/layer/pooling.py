"""Pooling layers — reference python/paddle/nn/layer/pooling.py."""
from .. import functional as F
from ..layer_base import Layer

__all__ = [
    "AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool2D", "AdaptiveMaxPool3D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
]


class _Pool(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0, **kwargs):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}
        self._snapshot_data_format()

    def _snapshot_data_format(self):
        # resolve the global layout at CONSTRUCTION, like every other layer
        # (a model built under set_channels_last must not change behavior if
        # the flag is flipped before forward)
        if "data_format" not in self.kwargs and self._fn and self._fn[-2].isdigit():
            from ..layout import resolve_data_format
            self.kwargs["data_format"] = resolve_data_format(None, int(self._fn[-2]))

    def forward(self, x):
        return getattr(F, self._fn)(x, self.kernel_size, self.stride, self.padding, **self.kwargs)

    def extra_repr(self):
        return f"kernel_size={self.kernel_size}, stride={self.stride}, padding={self.padding}"


class AvgPool1D(_Pool):
    _fn = "avg_pool1d"


class AvgPool2D(_Pool):
    _fn = "avg_pool2d"


class AvgPool3D(_Pool):
    _fn = "avg_pool3d"


class MaxPool1D(_Pool):
    _fn = "max_pool1d"


class MaxPool2D(_Pool):
    _fn = "max_pool2d"


class MaxPool3D(_Pool):
    _fn = "max_pool3d"


class _AdaptivePool(Layer):
    _fn = None

    def __init__(self, output_size, **kwargs):
        super().__init__()
        self.output_size = output_size
        self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}
        _Pool._snapshot_data_format(self)

    def forward(self, x):
        return getattr(F, self._fn)(x, self.output_size, **self.kwargs)


class AdaptiveAvgPool1D(_AdaptivePool):
    _fn = "adaptive_avg_pool1d"


class AdaptiveAvgPool2D(_AdaptivePool):
    _fn = "adaptive_avg_pool2d"


class AdaptiveAvgPool3D(_AdaptivePool):
    _fn = "adaptive_avg_pool3d"


class AdaptiveMaxPool1D(_AdaptivePool):
    _fn = "adaptive_max_pool1d"


class AdaptiveMaxPool2D(_AdaptivePool):
    _fn = "adaptive_max_pool2d"


class AdaptiveMaxPool3D(_AdaptivePool):
    _fn = "adaptive_max_pool3d"


class _MaxUnPool(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0, data_format=None,
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        return getattr(F, self._fn)(x, indices, self.kernel_size, self.stride,
                                    self.padding, output_size=self.output_size)

    def extra_repr(self):
        return f"kernel_size={self.kernel_size}, stride={self.stride}"


class MaxUnPool1D(_MaxUnPool):
    _fn = "max_unpool1d"


class MaxUnPool2D(_MaxUnPool):
    _fn = "max_unpool2d"


class MaxUnPool3D(_MaxUnPool):
    _fn = "max_unpool3d"
