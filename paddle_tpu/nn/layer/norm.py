"""Normalization layers — reference python/paddle/nn/layer/norm.py."""
import jax.numpy as jnp

from ...framework.core import Tensor
from .. import functional as F
from ..initializer import Constant
from ..layer_base import Layer
from ..layout import resolve_data_format as _resolve_df

__all__ = [
    "LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
    "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm1D",
    "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm",
]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr, default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """TPU-native extra (matches incubate fused_rms_norm in newer paddle)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, input):
        return F.rms_norm(input, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format=None, use_global_stats=None, name=None):
        data_format = _resolve_df(data_format, 2)
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, input):
        return F.batch_norm(
            input, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid-style BatchNorm(num_channels) — acts like BatchNorm2D."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 use_global_stats=None, **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, input):
        out = super().forward(input)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format=None, use_global_stats=None, name=None):
        data_format = _resolve_df(data_format, 1)
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCL" else "NHWC", use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format=None, use_global_stats=None, name=None):
        data_format = _resolve_df(data_format, 3)
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCDHW" else "NHWC", use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batchnorm. Inside shard_map/pmap the mean/var reduce over
    the 'dp' mesh axis (XLA psum); single-device it equals BatchNorm."""

    def forward(self, input):
        from ...distributed import in_shard_map, get_data_parallel_axis
        axis = get_data_parallel_axis() if in_shard_map() else None
        if axis is None:
            return super().forward(input)
        import jax

        def _f(v, rm, rv, w, b):
            ax = 1 if self._data_format.startswith("NC") else v.ndim - 1
            reduce_axes = tuple(i for i in range(v.ndim) if i != ax)
            x32 = v.astype(jnp.float32)
            cnt = jax.lax.psum(jnp.asarray(
                float(jnp.prod(jnp.asarray([v.shape[i] for i in reduce_axes])))), axis)
            mean = jax.lax.psum(jnp.sum(x32, axis=reduce_axes), axis) / cnt
            var = jax.lax.psum(jnp.sum(jnp.square(x32), axis=reduce_axes), axis) / cnt \
                - jnp.square(mean)
            shape = [1] * v.ndim
            shape[ax] = v.shape[ax]
            out = (x32 - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + self._epsilon)
            out = out.astype(v.dtype)
            if w is not None:
                out = out * w.reshape(shape).astype(v.dtype)
            if b is not None:
                out = out + b.reshape(shape).astype(v.dtype)
            return out
        from ...framework.core import apply_op
        return apply_op(_f, input, self._mean, self._variance, self.weight, self.bias)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            new.weight, new.bias = layer.weight, layer.bias
            new._buffers.update(layer._buffers)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format=None, name=None):
        data_format = _resolve_df(data_format, 2)
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format=None, name=None):
        data_format = _resolve_df(data_format, 2)
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.scale = None
        else:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.instance_norm(input, weight=self.scale, bias=self.bias,
                               eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format=None, name=None):
        data_format = _resolve_df(data_format, 2)
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, input):
        return F.local_response_norm(input, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        import numpy as np
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..initializer import Normal
        self.weight_u = self.create_parameter([h], default_initializer=Normal(0.0, 1.0))
        self.weight_v = self.create_parameter([w], default_initializer=Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...framework.core import apply_op
        import jax

        def _f(w, u, v):
            mat = jnp.moveaxis(w, self._dim, 0).reshape(w.shape[self._dim], -1)
            for _ in range(self._power_iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + self._eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + self._eps)
            sigma = u @ mat @ v
            return w / sigma
        return apply_op(_f, weight, self.weight_u, self.weight_v)
