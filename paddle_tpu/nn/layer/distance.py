"""Distance layers — reference python/paddle/nn/layer/distance.py."""
import jax.numpy as jnp

from ...framework.core import apply_op
from ..layer_base import Layer

__all__ = ["PairwiseDistance"]


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        def _f(a, b):
            d = a - b + self.epsilon
            return jnp.sum(jnp.abs(d) ** self.p, axis=-1, keepdims=self.keepdim) ** (1.0 / self.p)
        return apply_op(_f, x, y)
