"""Gradient clipping — reference python/paddle/fluid/clip.py (exposed as
paddle.nn.ClipGradBy*). Operates on (param, grad) Tensor pairs eagerly and on
grad pytrees in the functional/jit path."""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "ClipGradForMOEByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)

    def clip_pytree(self, grads):
        """Pure-pytree form used inside jitted train steps."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out

    def clip_pytree(self, grads):
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_one(self, g):
        norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
        return (g.astype(jnp.float32) * scale).astype(g.dtype)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(self._clip_one(g._value))))
        return out

    def clip_pytree(self, grads):
        return jax.tree_util.tree_map(self._clip_one, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    # squared-norm accumulation is the ONLY thing subclasses change
    # (ClipGradForMOEByGlobalNorm splits expert/dense and psums)
    def _sq_eager(self, params_grads):
        sq = 0.0
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            sq = sq + jnp.sum(jnp.square(g._value.astype(jnp.float32)))
        return sq

    def _sq_pytree(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        return sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)

    def _scale(self, sq):
        global_norm = jnp.sqrt(sq)
        return jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)

    def _dygraph_clip(self, params_grads):
        scale = self._scale(self._sq_eager(params_grads))
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._value.astype(jnp.float32) * scale).astype(g.dtype))))
        return out

    def clip_pytree(self, grads):
        scale = self._scale(self._sq_pytree(grads))
        return jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


def _leaf_name(key_path):
    """Pytree key path -> plain dotted name ("moe.w1", not "['moe.w1']"),
    so name predicates see the same strings as state_dict keys."""
    parts = []
    for k in key_path:
        parts.append(str(getattr(k, "key", getattr(k, "name",
                                                   getattr(k, "idx", k)))))
    return ".".join(parts)


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    """MoE-aware global-norm clip — reference
    python/paddle/incubate/distributed/models/moe/grad_clip.py
    (ClipGradForMOEByGlobalNorm): expert and non-expert gradients form ONE
    combined global norm, with the expert contribution summed across the
    expert-parallel group.

    TPU-native: under GSPMD the stacked expert tensors are logically
    global, so summing their squared norms IS the cross-group reduction —
    no explicit collective needed. Inside a shard_map body (manual
    collectives, each rank holding its expert slice) the expert
    contribution is psum'd over `moe_axis` to reproduce the reference's
    moe-group all_reduce.

    `is_expert_param_func(param_or_name) -> bool` selects expert params:
    it receives the param in the eager path and the pytree leaf NAME in
    clip_pytree.
    """

    def __init__(self, clip_norm, is_expert_param_func=None,
                 moe_axis="ep", group_name="default_moe_group"):
        super().__init__(clip_norm, group_name)
        self.is_expert = is_expert_param_func or (lambda p: False)
        self.moe_axis = moe_axis

    def _moe_psum(self, sq_moe):
        from ..distributed.mesh import current_axis_context, in_shard_map
        if in_shard_map() and self.moe_axis in (current_axis_context() or ()):
            return jax.lax.psum(sq_moe, self.moe_axis)
        return sq_moe

    def _combine(self, tagged_sqs):
        sq_normal = jnp.zeros((), jnp.float32)
        sq_moe = jnp.zeros((), jnp.float32)
        for is_moe, s in tagged_sqs:
            if is_moe:
                sq_moe = sq_moe + s
            else:
                sq_normal = sq_normal + s
        return sq_normal + self._moe_psum(sq_moe)

    def _sq_eager(self, params_grads):
        return self._combine(
            (self.is_expert(p),
             jnp.sum(jnp.square(g._value.astype(jnp.float32))))
            for p, g in params_grads
            if g is not None and getattr(p, "need_clip", True))

    def _sq_pytree(self, grads):
        return self._combine(
            (self.is_expert(_leaf_name(kp)),
             jnp.sum(jnp.square(g.astype(jnp.float32))))
            for kp, g in jax.tree_util.tree_flatten_with_path(grads)[0])
