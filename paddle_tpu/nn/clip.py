"""Gradient clipping — reference python/paddle/fluid/clip.py (exposed as
paddle.nn.ClipGradBy*). Operates on (param, grad) Tensor pairs eagerly and on
grad pytrees in the functional/jit path."""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)

    def clip_pytree(self, grads):
        """Pure-pytree form used inside jitted train steps."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out

    def clip_pytree(self, grads):
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_one(self, g):
        norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
        return (g.astype(jnp.float32) * scale).astype(g.dtype)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(self._clip_one(g._value))))
        return out

    def clip_pytree(self, grads):
        return jax.tree_util.tree_map(self._clip_one, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        sq = 0.0
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            sq = sq + jnp.sum(jnp.square(g._value.astype(jnp.float32)))
        global_norm = jnp.sqrt(sq)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._value.astype(jnp.float32) * scale).astype(g.dtype))))
        return out

    def clip_pytree(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        global_norm = jnp.sqrt(sq)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        return jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)
