"""Loss functionals — reference python/paddle/nn/functional/loss.py."""
import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply_op

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "ctc_loss",
    "hinge_embedding_loss", "cosine_embedding_loss", "triplet_margin_loss",
    "log_loss", "square_error_cost", "sigmoid_focal_loss", "dice_loss",
    "npair_loss", "hsigmoid_loss", "margin_cross_entropy",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    """reference python/paddle/nn/functional/loss.py:cross_entropy.
    Computes in fp32 regardless of input dtype (matches phi kernel behavior)."""
    def _f(logits, lab, *rest):
        lg = logits.astype(jnp.float32)
        if use_softmax:
            logp = jax.nn.log_softmax(lg, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(lg, 1e-30))
        if soft_label:
            sl = lab.astype(jnp.float32)
            if label_smoothing > 0.0:
                k = logp.shape[axis]
                sl = (1 - label_smoothing) * sl + label_smoothing / k
            loss = -jnp.sum(sl * logp, axis=axis)
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == logp.ndim:  # [N, 1]-style
                lab_i = jnp.squeeze(lab_i, axis=axis)
            valid = lab_i != ignore_index
            safe = jnp.where(valid, lab_i, 0)
            picked = jnp.take_along_axis(logp, safe[..., None], axis=axis)[..., 0] \
                if axis in (-1, logp.ndim - 1) else \
                jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis).squeeze(axis)
            if label_smoothing > 0.0:
                k = logp.shape[axis]
                smooth = jnp.mean(logp, axis=axis)
                loss = -( (1 - label_smoothing) * picked + label_smoothing * smooth )
            else:
                loss = -picked
            if rest:  # class weights
                w = rest[0].astype(jnp.float32)
                loss = loss * jnp.take(w, safe)
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
                if rest:
                    w = rest[0].astype(jnp.float32)
                    denom = jnp.maximum(jnp.sum(jnp.where(valid, jnp.take(w, safe), 0.0)), 1e-10)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply_op(_f, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, reduction="none", soft_label=soft_label,
                         ignore_index=ignore_index, axis=axis)
    from .activation import softmax as _softmax
    loss = loss.unsqueeze(axis) if not soft_label else loss
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def _f(p, y, *rest):
        p32, y32 = p.astype(jnp.float32), y.astype(jnp.float32)
        out = -(y32 * jnp.log(jnp.maximum(p32, 1e-12))
                + (1 - y32) * jnp.log(jnp.maximum(1 - p32, 1e-12)))
        if rest:
            out = out * rest[0]
        return _reduce(out, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply_op(_f, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def _f(z, y, *rest):
        z32, y32 = z.astype(jnp.float32), y.astype(jnp.float32)
        i = 0
        pw = None
        if weight is not None:
            w = rest[i]; i += 1
        else:
            w = None
        if pos_weight is not None:
            pw = rest[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight on the y term
        if pw is None:
            out = jnp.maximum(z32, 0) - z32 * y32 + jnp.log1p(jnp.exp(-jnp.abs(z32)))
        else:
            log_sig = jax.nn.log_sigmoid(z32)
            log_sig_neg = jax.nn.log_sigmoid(-z32)
            out = -(pw * y32 * log_sig + (1 - y32) * log_sig_neg)
        if w is not None:
            out = out * w
        return _reduce(out, reduction)
    args = (logit, label) + tuple(t for t in (weight, pos_weight) if t is not None)
    return apply_op(_f, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.square(a - b), reduction), input, label)


def square_error_cost(input, label):
    return apply_op(lambda a, b: jnp.square(a - b), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def _f(logp, lab, *rest):
        lab_i = lab.astype(jnp.int32)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(logp, safe[:, None] if logp.ndim == 2 else
                                     jnp.expand_dims(safe, 1), axis=1)
        picked = picked[:, 0] if logp.ndim == 2 else picked.squeeze(1)
        loss = -picked
        if rest:
            loss = loss * jnp.take(rest[0], safe)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(valid.astype(jnp.float32)) if not rest else \
                jnp.sum(jnp.where(valid, jnp.take(rest[0], safe), 0.0))
            return jnp.sum(loss) / jnp.maximum(denom, 1e-10)
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return apply_op(_f, *args)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def _f(a, b):
        d = jnp.abs(a - b)
        out = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(out, reduction)
    return apply_op(_f, input, label)


def kl_div(input, label, reduction="mean", name=None):
    def _f(logp, y):
        out = y * (jnp.log(jnp.maximum(y, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(out) / logp.shape[0]
        return _reduce(out, reduction)
    return apply_op(_f, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def _f(a, b, y):
        out = jnp.maximum(-y * (a - b) + margin, 0.0)
        return _reduce(out, reduction)
    return apply_op(_f, input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def _f(a, y):
        out = jnp.where(y == 1.0, a, jnp.maximum(0.0, margin - a))
        return _reduce(out, reduction)
    return apply_op(_f, input, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def _f(a, b, y):
        sim = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        out = jnp.where(y == 1, 1 - sim, jnp.maximum(0.0, sim - margin))
        return _reduce(out, reduction)
    return apply_op(_f, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def _f(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, axis=-1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, axis=-1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg) ** p, axis=-1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        out = jnp.maximum(dp - dn + margin, 0.0)
        return _reduce(out, reduction)
    return apply_op(_f, input, positive, negative)


def log_loss(input, label, epsilon=1e-4, name=None):
    def _f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return apply_op(_f, input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def _f(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        out = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            out = out / rest[0]
        return _reduce(out, reduction)
    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return apply_op(_f, *args)


def dice_loss(input, label, epsilon=1e-5, name=None):
    def _f(p, y):
        y1 = jax.nn.one_hot(y.squeeze(-1), p.shape[-1], dtype=p.dtype)
        inter = jnp.sum(p * y1, axis=tuple(range(1, p.ndim)))
        union = jnp.sum(p, axis=tuple(range(1, p.ndim))) + jnp.sum(y1, axis=tuple(range(1, p.ndim)))
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply_op(_f, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def _f(a, p, y):
        sim = a @ p.T
        eq = (y[:, None] == y[None, :]).astype(jnp.float32)
        targets = eq / jnp.sum(eq, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(targets * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1)) + jnp.mean(jnp.sum(p * p, axis=1))) / 2
        return ce + reg
    return apply_op(_f, anchor, positive, labels)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space (lax.scan over time).
    log_probs: [T, N, C] (paddle layout), labels: [N, S]."""
    def _f(lp, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, N, C = lp.shape
        S = lab.shape[1]
        ext = jnp.full((N, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        L = 2 * lab_len.astype(jnp.int32) + 1
        neg_inf = jnp.asarray(-1e30, jnp.float32)
        alpha0 = jnp.full((N, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(first_lab)

        same = jnp.pad(ext[:, 2:] == ext[:, :-2], ((0, 0), (2, 0)), constant_values=True)

        def step(alpha, lp_t):
            a1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)), constant_values=neg_inf)
            a2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)), constant_values=neg_inf)
            a2 = jnp.where(same, neg_inf, a2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_body(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            alpha = jnp.where((t < in_len)[:, None] & (t > 0), new_alpha, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(scan_body, alpha0, jnp.arange(T))
        last = jnp.take_along_axis(alpha, (L - 1)[:, None], axis=1)[:, 0]
        prev = jnp.take_along_axis(alpha, jnp.maximum(L - 2, 0)[:, None], axis=1)[:, 0]
        ll = jnp.logaddexp(last, prev)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)
    return apply_op(_f, log_probs, labels, input_lengths, label_lengths)


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid — reference python/paddle/nn/functional/loss.py:
    hsigmoid_loss + phi hsigmoid_loss kernel (SimpleCode: for label l the path
    code is c = l + num_classes; bit b's internal node is (c >> (b+1)) - 1 and
    its binary target is (c >> b) & 1)."""
    def _f(x, lab, w, b, ptab, pcode):
        lab = lab.reshape(-1).astype(jnp.int32)
        n = x.shape[0]
        if ptab is not None:
            node = ptab[lab] if ptab.ndim == 1 else ptab  # (N, D) path rows
            code = pcode[lab] if pcode.ndim == 1 else pcode
            node = node.astype(jnp.int32)
            valid = node >= 0
            bit = code.astype(x.dtype)
        else:
            c = lab + num_classes
            max_bits = int(np.ceil(np.log2(2 * num_classes)))
            bits = jnp.arange(max_bits, dtype=jnp.int32)
            shifted = c[:, None] >> (bits[None, :] + 1)
            node = shifted - 1                       # (N, B) internal node ids
            valid = shifted >= 1
            bit = ((c[:, None] >> bits[None, :]) & 1).astype(x.dtype)
        node_safe = jnp.maximum(node, 0)
        wrows = w[node_safe]                          # (N, B, D)
        pre = jnp.einsum("nd,nbd->nb", x.astype(jnp.float32),
                         wrows.astype(jnp.float32))
        if b is not None:
            pre = pre + b.reshape(-1)[node_safe].astype(jnp.float32)
        # BCE-with-logits against the path bit, masked beyond the path length
        losses = jax.nn.softplus(pre) - pre * bit.astype(jnp.float32)
        losses = jnp.where(valid, losses, 0.0)
        return jnp.sum(losses, axis=1, keepdims=True).astype(x.dtype)
    return apply_op(_f, input, label, weight, bias, path_table, path_code)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """ArcFace-style margin softmax — reference python/paddle/nn/functional/
    loss.py:margin_cross_entropy. Single-shard form; model-parallel sharded
    classes are handled by meta_parallel.ParallelCrossEntropy."""
    def _f(lg, lab):
        lab = lab.reshape(-1).astype(jnp.int32)
        lg32 = lg.astype(jnp.float32)
        onehot = jax.nn.one_hot(lab, lg.shape[-1], dtype=jnp.float32)
        cos_t = jnp.clip(jnp.sum(lg32 * onehot, axis=-1), -1.0, 1.0)
        theta = jnp.arccos(cos_t)
        target_logit = jnp.cos(margin1 * theta + margin2) - margin3
        modified = lg32 + onehot * (target_logit[:, None] - cos_t[:, None])
        modified = modified * scale
        logsm = jax.nn.log_softmax(modified, axis=-1)
        loss = -jnp.sum(logsm * onehot, axis=-1, keepdims=True)
        if reduction == "mean":
            lossr = jnp.mean(loss)
        elif reduction == "sum":
            lossr = jnp.sum(loss)
        else:
            lossr = loss
        if return_softmax:
            return lossr, jnp.exp(logsm).astype(lg.dtype)
        return lossr
    return apply_op(_f, logits, label)
