"""Common functionals — reference python/paddle/nn/functional/common.py
(linear, dropout, pad, interpolate, …) + input.py (one_hot, embedding)."""
import jax
import jax.numpy as jnp
import numpy as np
from ..layout import resolve_data_format as _resolve_df

from ...framework.core import Tensor, apply_op
from ...framework.random import next_key

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "pad",
    "zeropad2d", "interpolate", "upsample", "one_hot", "embedding",
    "cosine_similarity", "label_smooth", "unfold", "fold", "bilinear",
    "class_center_sample", "sequence_mask",
]


def amp_compute_cast(v, w):
    """AMP O2 rule shared by linear and conv: low-precision weights define
    the compute dtype — f32 activations are cast DOWN so a bf16 model rides
    the MXU instead of silently promoting the whole chain to f32."""
    if jnp.dtype(w.dtype) in (jnp.bfloat16, jnp.float16) and \
            jnp.dtype(v.dtype) == jnp.float32:
        return v.astype(w.dtype)
    return v


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Weight layout [in, out] (reference
    python/paddle/nn/functional/common.py:linear → matmul_v2)."""
    def _f(v, w, *r):
        v = amp_compute_cast(v, w)
        out = v @ w
        if r:
            out = out + r[0].astype(out.dtype)
        return out
    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply_op(_f, *args)


def _hash_keep(seed_key, mask_shape, p):
    """Counter-hash bernoulli(1-p) — the same lowbias32 mixer as the flash
    attention kernel's in-kernel dropout (imported, so the two can't
    desynchronize). ~8 int ops/element on the VPU vs ~hundreds for threefry,
    which dominates step time for dropout-trained encoders (BERT) at scale."""
    from ...ops.attention import _hash32, _rate_thresh
    n = int(np.prod(mask_shape, dtype=np.int64))
    # fold the jax PRNG key into a 32-bit salt (host-side when eager; a
    # traced constant under jit, same lifetime as the old bernoulli path)
    salt = jax.random.randint(seed_key, (), 0, 2 ** 31 - 1).astype(jnp.uint32)
    idx = jax.lax.iota(jnp.uint32, n) * jnp.uint32(0x9E3779B1)
    h = _hash32(idx ^ (salt * jnp.uint32(0x85EBCA77)))
    return (h >= _rate_thresh(p)).reshape(mask_shape)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = next_key()

    def _f(v):
        if axis is None:
            mask_shape = v.shape
        else:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            mask_shape = tuple(v.shape[i] if i in axes else 1 for i in range(v.ndim))
        keep = _hash_keep(key, mask_shape, p)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), jnp.zeros((), v.dtype))
        return jnp.where(keep, v, jnp.zeros((), v.dtype))
    return apply_op(_f, x)


def dropout2d(x, p=0.5, training=True, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    axes = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axes, training=training)


def dropout3d(x, p=0.5, training=True, data_format=None, name=None):
    data_format = _resolve_df(data_format, 3)
    axes = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axes, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def _f(v):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 - p + p * alpha_p ** 2) ** -0.5
        b = -a * p * alpha_p
        return a * jnp.where(keep, v, jnp.asarray(alpha_p, v.dtype)) + b
    return apply_op(_f, x)


def _pad_nd(v, pad, mode, value, data_format):
    # paddle pad: len-2N list [lo_last, hi_last, lo_prev, hi_prev, ...] over
    # spatial dims, or len-2*ndim over all dims
    mode_map = {"constant": "constant", "reflect": "reflect",
                "replicate": "edge", "circular": "wrap"}
    m = mode_map[mode]
    nd = v.ndim
    if len(pad) == 2 * nd:
        widths = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(nd)]
    else:
        n_spatial = len(pad) // 2
        widths = [(0, 0)] * nd
        if data_format.startswith("NC"):
            spatial = list(range(2, 2 + n_spatial))
        else:
            spatial = list(range(1, 1 + n_spatial))
        # paddle orders pad from last spatial dim inward? It's ordered per dim
        # starting from the first spatial dim: [l, r, t, b ...] for 2D is
        # actually [left,right,top,bottom] i.e. W then H (last dim first).
        for i, ax in enumerate(reversed(spatial)):
            widths[ax] = (int(pad[2 * i]), int(pad[2 * i + 1]))
    if m == "constant":
        return jnp.pad(v, widths, mode=m, constant_values=value)
    return jnp.pad(v, widths, mode=m)


def pad(x, pad, mode="constant", value=0.0, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    if isinstance(pad, Tensor):
        pad = [int(p) for p in np.asarray(pad._value)]
    pad = [int(p) for p in pad]
    return apply_op(lambda v: _pad_nd(v, pad, mode, value, data_format), x)


def zeropad2d(x, padding, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    def _f(v):
        chan_last = not data_format.startswith("NC")
        spatial_axes = list(range(1, v.ndim - 1)) if chan_last else list(range(2, v.ndim))
        in_sizes = [v.shape[a] for a in spatial_axes]
        if size is not None:
            sz = size
            if isinstance(sz, Tensor):
                sz = [int(s) for s in np.asarray(sz._value)]
            out_sizes = [int(s._value) if isinstance(s, Tensor) else int(s) for s in sz] \
                if isinstance(sz, (list, tuple)) else [int(sz)] * len(in_sizes)
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(in_sizes)
            out_sizes = [int(s * f) for s, f in zip(in_sizes, sf)]
        out_shape = list(v.shape)
        for a, s in zip(spatial_axes, out_sizes):
            out_shape[a] = s
        if mode == "area":
            # paddle/torch 'area' = adaptive average pooling
            from .pooling import _adaptive_avg
            return _adaptive_avg(v, out_sizes, spatial_axes)
        # Explicit per-axis source-coordinate gather. jax.image.resize
        # is unusable here: it ANTIALIASES when downsampling (PIL-style
        # scale-widened kernels), its cubic kernel is a=-0.5, and its
        # nearest rule is half-pixel — the reference *_interp_v2 ops do
        # plain source sampling (nearest: floor(j*in/out); cubic: Keys
        # a=-0.75 with border-replicated taps).
        if mode == "nearest":
            # pure gather — no float round-trip (int tensors > 2^24
            # must survive); paddle nearest_interp_v2 rounds HALF-UP
            # (floor(ratio*j + 0.5)) under align_corners, not
            # ties-to-even
            out = v
            for a, s_out in zip(spatial_axes, out_sizes):
                s_in = out.shape[a]
                j = jnp.arange(s_out, dtype=jnp.float32)
                if align_corners and s_out > 1:
                    ii = jnp.floor(j * ((s_in - 1.0) / (s_out - 1.0))
                                   + 0.5)
                else:
                    ii = jnp.floor(j * (s_in / s_out))
                out = jnp.take(out, jnp.clip(ii, 0, s_in - 1)
                               .astype(jnp.int32), axis=a)
            return out
        out = v.astype(jnp.float32)
        for a, s_out in zip(spatial_axes, out_sizes):
            s_in = out.shape[a]
            j = jnp.arange(s_out, dtype=jnp.float32)
            if s_out == 1 or s_in == 1:
                idx = jnp.zeros((s_out,), jnp.float32)
            elif align_corners:
                idx = j * ((s_in - 1.0) / (s_out - 1.0))
            elif align_mode == 1 and mode in ("linear", "bilinear",
                                              "trilinear"):
                idx = j * (s_in / s_out)          # legacy align_mode=1
            else:
                idx = (j + 0.5) * (s_in / s_out) - 0.5  # half-pixel
            bshape = [1] * out.ndim
            bshape[a] = s_out
            if mode == "bicubic":
                # Keys cubic, a = -0.75.  idx stays UNCLIPPED: the
                # fractional offset t keeps its true value at borders
                # (a half-pixel idx of -0.25 means i0=-1, t=0.75) and
                # only the TAP indices replicate the border.
                i0 = jnp.floor(idx).astype(jnp.int32)
                t = idx - i0
                A = -0.75

                def k1(s):   # |s| <= 1
                    return ((A + 2) * s - (A + 3)) * s * s + 1

                def k2(s):   # 1 < |s| < 2
                    return ((A * s - 5 * A) * s + 8 * A) * s - 4 * A
                ws = [k2(t + 1), k1(t), k1(1 - t), k2(2 - t)]
                acc = 0.0
                for o, wt in zip((-1, 0, 1, 2), ws):
                    ii = jnp.clip(i0 + o, 0, s_in - 1)
                    acc = acc + jnp.take(out, ii, axis=a) \
                        * wt.reshape(bshape)
                out = acc
                continue
            idx = jnp.clip(idx, 0.0, s_in - 1.0)
            i0 = jnp.floor(idx).astype(jnp.int32)
            i1 = jnp.minimum(i0 + 1, s_in - 1)
            w = (idx - i0).reshape(bshape)
            out = jnp.take(out, i0, axis=a) * (1 - w) \
                + jnp.take(out, i1, axis=a) * w
        return out.astype(v.dtype)
    return apply_op(_f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def one_hot(x, num_classes, name=None):
    return apply_op(lambda v: jax.nn.one_hot(v, num_classes, dtype=jnp.float32), x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def _f(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids != padding_idx)[..., None].astype(w.dtype)
            out = out * mask
        return out
    return apply_op(_f, x, weight)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def _f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.sqrt(jnp.sum(a * a, axis=axis)) * jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(den, eps)
    return apply_op(_f, x1, x2)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def _f(v, *rest):
        k = v.shape[-1]
        if rest:
            return (1 - epsilon) * v + epsilon * rest[0]
        return (1 - epsilon) * v + epsilon / k
    args = (label, prior_dist) if prior_dist is not None else (label,)
    return apply_op(_f, *args)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _norm(v, n=2):
        return list(v) if isinstance(v, (list, tuple)) else [v] * n
    k = _norm(kernel_sizes)
    s = _norm(strides)
    d = _norm(dilations)
    p = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 4
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]

    def _f(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])])
        out_h = (v.shape[2] - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        out_w = (v.shape[3] - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        patches = []
        for i in range(k[0]):
            for j in range(k[1]):
                sl = v[:, :, i * d[0]: i * d[0] + out_h * s[0]: s[0],
                       j * d[1]: j * d[1] + out_w * s[1]: s[1]]
                patches.append(sl)
        stacked = jnp.stack(patches, axis=2)  # [n, c, k*k, oh, ow]
        return stacked.reshape(n, c * k[0] * k[1], out_h * out_w)
    return apply_op(_f, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _norm(v, n=2):
        return list(v) if isinstance(v, (list, tuple)) else [v] * n
    osz = _norm(output_sizes)
    k = _norm(kernel_sizes)
    s = _norm(strides)
    d = _norm(dilations)
    p = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 4
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]

    def _f(v):
        n, ckk, L = v.shape
        c = ckk // (k[0] * k[1])
        ph, pw = osz[0] + p[0] + p[2], osz[1] + p[1] + p[3]
        out_h = (ph - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        out_w = (pw - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        v5 = v.reshape(n, c, k[0], k[1], out_h, out_w)
        out = jnp.zeros((n, c, ph, pw), v.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                out = out.at[:, :, i * d[0]: i * d[0] + out_h * s[0]: s[0],
                             j * d[1]: j * d[1] + out_w * s[1]: s[1]].add(v5[:, :, i, j])
        return out[:, :, p[0]: p[0] + osz[0], p[1]: p[1] + osz[1]]
    return apply_op(_f, x)


def bilinear(x1, x2, weight, bias=None, name=None):
    def _f(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply_op(_f, *args)


def sequence_mask(x, maxlen=None, dtype="int64"):
    ml = maxlen if maxlen is not None else int(np.asarray(x._value).max())

    def _f(v):
        r = jnp.arange(ml)
        return (r[None, :] < v[..., None]).astype(jnp.int32)
    return apply_op(_f, x)


def class_center_sample(label, num_classes, num_samples, group=None):
    # simplified eager implementation (reference is a distributed GPU op)
    lab = np.asarray(label._value)
    pos = np.unique(lab)
    extra = np.setdiff1d(np.arange(num_classes), pos)
    rng = np.random.default_rng(0)
    n_extra = max(0, num_samples - pos.size)
    sampled = np.concatenate([pos, rng.choice(extra, size=n_extra, replace=False)]) \
        if n_extra else pos[:num_samples]
    sampled.sort()
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(sampled.size)
    return Tensor(jnp.asarray(remap[lab])), Tensor(jnp.asarray(sampled))
