"""Extension functionals — reference python/paddle/nn/functional/extension.py
+ transformer attention entry points (fused path in paddle_tpu.ops)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from ..layout import resolve_data_format as _resolve_df

from ...framework.core import Tensor, apply_op

__all__ = ["diag_embed", "gather_tree", "temporal_shift",
           "scaled_dot_product_attention", "sparse_attention"]


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    def _f(v):
        k = v.shape[-1]
        n = k + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        rng = jnp.arange(k)
        r = rng + max(-offset, 0)
        c = rng + max(offset, 0)
        out = out.at[..., r, c].set(v)
        if (dim1, dim2) not in ((-2, -1), (out.ndim - 2, out.ndim - 1)):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out
    return apply_op(_f, input)


def gather_tree(ids, parents):
    def _f(idv, par):
        T = idv.shape[0]

        def body(carry, t):
            beams, cur = carry
            new_beams = jnp.take_along_axis(par[t], cur, axis=-1)
            tok = jnp.take_along_axis(idv[t], new_beams if t > 0 else cur, axis=-1)
            return (beams, new_beams), tok
        # walk from last step to first
        init = jnp.broadcast_to(jnp.arange(idv.shape[-1]), idv.shape[1:])
        outs = []
        cur = init
        for t in range(T - 1, -1, -1):
            outs.append(jnp.take_along_axis(idv[t], cur, axis=-1))
            cur = jnp.take_along_axis(par[t], cur, axis=-1)
        return jnp.stack(outs[::-1], axis=0)
    return apply_op(_f, ids, parents)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    def _f(v):
        if data_format == "NHWC":
            v = jnp.moveaxis(v, -1, 1)
        nt, c, h, w = v.shape
        n = nt // seg_num
        v5 = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.pad(v5[:, 1:, :fold], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
        right = jnp.pad(v5[:, :-1, fold:2 * fold], ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
        rest = v5[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op(_f, x)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """[B, L, H, D] layout (paddle). Routes to the Pallas flash kernel on TPU
    for the fused path; this jnp fallback is used on CPU/interpret tests."""
    from ...ops.attention import flash_attention_available, flash_attention

    rate = float(dropout_p or 0.0) if training else 0.0
    if flash_attention_available(query, attn_mask, dropout_p):
        return flash_attention(query, key, value, causal=is_causal,
                               attn_mask=attn_mask, dropout_rate=rate)

    # CPU fallback: the shared jnp reference (fp32 softmax, GQA +
    # additive/bool mask + hash dropout) in ops/attention.py
    from ...ops.attention import _next_seed, mha_reference

    seed = _next_seed() if rate else 0

    def _f(q, k, v, *rest):
        m = rest[0] if rest else None
        return mha_reference(q, k, v, causal=is_causal, attn_mask=m,
                             dropout_rate=rate, dropout_seed=seed)
    args = (query, key, value) + ((attn_mask,) if attn_mask is not None else ())
    return apply_op(_f, *args)


@functools.lru_cache(maxsize=16)
def _cached_block_layout(off_bytes, off_shape, col_bytes, col_shape, L):
    """Sparsity patterns are static across steps: the O(L^2) host-side
    block-alignment detection runs once per distinct CSR, not per call."""
    from ...ops import block_sparse_attention as _bsa
    off = np.frombuffer(off_bytes, np.int32).reshape(off_shape)
    cols = np.frombuffer(col_bytes, np.int32).reshape(col_shape)
    return _bsa.csr_to_block_layout(off, cols, L)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """CSR-sparsified softmax(QK^T/sqrt(d))V — reference
    python/paddle/nn/functional/sparse_attention.py:20 (CUDA
    sparse_attention_op).  q/k/v: [B, H, L, D]; offset [B, H, L+1];
    columns [B, H, nnz]; masks use 0 = masked.

    TPU-native: when the CSR pattern is concrete, mask-free and exactly
    block-aligned, it runs the blocked-CSR Pallas kernel
    (ops/block_sparse_attention.py) whose compute scales with nonzero
    blocks; otherwise a dense-masked XLA path with identical semantics."""
    from ...ops import block_sparse_attention as _bsa

    L = query.shape[-2]
    raw = lambda t: t._value if isinstance(t, Tensor) else t
    layout = None
    if key_padding_mask is None and attn_mask is None:
        try:
            off = np.asarray(raw(sparse_csr_offset)).astype(np.int32)
            cols = np.asarray(raw(sparse_csr_columns)).astype(np.int32)
            layout = _cached_block_layout(off.tobytes(), off.shape,
                                          cols.tobytes(), cols.shape, L)
        except Exception:   # traced CSR (inside jit) → dense fallback
            layout = None
    if layout is not None:
        bs, bcols, bcounts = layout

        def _kern(q, k, v):
            return _bsa.block_sparse_attention(q, k, v, bcols, bcounts, bs)
        return apply_op(_kern, query, key, value)

    def _dense(q, k, v, off, cols, *masks):
        mask = _bsa.csr_element_mask(off, cols, L)
        kpm = masks[0] if key_padding_mask is not None else None
        am = masks[-1] if attn_mask is not None else None
        return _bsa.dense_mask_sparse_attention(q, k, v, mask, kpm, am)

    args = (query, key, value, sparse_csr_offset, sparse_csr_columns)
    if key_padding_mask is not None:
        args += (key_padding_mask,)
    if attn_mask is not None:
        args += (attn_mask,)
    return apply_op(_dense, *args)
