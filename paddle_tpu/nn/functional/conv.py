"""Convolutions — reference python/paddle/nn/functional/conv.py.
lax.conv_general_dilated drives the MXU directly; weight layout matches
paddle ([out_c, in_c/groups, *kernel])."""
import jax
import jax.numpy as jnp
from ..layout import resolve_data_format as _resolve_df

from ...framework.core import apply_op

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose", "conv3d_transpose"]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v) if len(v) == n else tuple(int(v[0]) for _ in range(n))
    return (int(v),) * n


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        if len(padding) == n:
            return [(int(p), int(p)) for p in padding]
        if len(padding) == 2 * n:
            return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
        # nested [[lo,hi],...] incl. batch/channel dims
        flat = [tuple(int(q) for q in p) if isinstance(p, (list, tuple)) else (int(p), int(p))
                for p in padding]
        if len(flat) == n + 2:
            flat = flat[2:]
        return flat
    return [(int(padding), int(padding))] * n


def _dimnums(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    pad = _padding(padding, n)
    lhs_spec, rhs_spec, out_spec = _dimnums(n, channel_last)

    def _f(v, w, *rest):
        # paddle weight is [O, I/g, *k]; lax wants spec-ordered — transpose for
        # channel_last ("HWIO"), keep OI*k otherwise
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (1, 0)
            w = jnp.transpose(w, perm)
        from .common import amp_compute_cast
        v = amp_compute_cast(v, w)
        out = jax.lax.conv_general_dilated(
            v, w.astype(v.dtype), window_strides=stride, padding=pad,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=(lhs_spec, rhs_spec, out_spec),
        )
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[out_spec.index("C")] = b.shape[0]
            out = out + b.reshape(shape).astype(out.dtype)
        return out
    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply_op(_f, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format=None, name=None):
    data_format = _resolve_df(data_format, 1)
    fmt = "NWC" if data_format in ("NLC",) else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, fmt)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format=None, name=None):
    data_format = _resolve_df(data_format, 3)
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, data_format, output_size=None):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    pad = _padding(padding, n)
    opad = _tuple(output_padding, n) if output_padding is not None else (0,) * n
    lhs_spec, rhs_spec, out_spec = _dimnums(n, channel_last)

    def _f(v, w, *rest):
        # paddle transpose-conv weight: [in_c, out_c/g, *k]
        # equivalent: conv with lhs_dilation=stride (fractional stride)
        k = w.shape[2:]
        eff_k = [dilation[i] * (k[i] - 1) + 1 for i in range(n)]
        if isinstance(pad, str) and pad == "SAME":
            # SAME transpose conv = gradient of a SAME forward conv:
            # output spatial is exactly in*stride. Forward SAME pad
            # total is max(eff_k - s, 0); transpose pads are the
            # (eff_k-1 - fwd_pad) complements, with s - eff_k extra on
            # the right when the kernel is narrower than the stride.
            tpads = []
            for i in range(n):
                pt = max(eff_k[i] - stride[i], 0)
                fl = pt // 2
                fr = pt - fl
                tpads.append((eff_k[i] - 1 - fl,
                              eff_k[i] - 1 - fr
                              + max(stride[i] - eff_k[i], 0) + opad[i]))
        else:
            pads = [(0, 0)] * n if isinstance(pad, str) else pad
            tpads = [(eff_k[i] - 1 - pads[i][0],
                      eff_k[i] - 1 - pads[i][1] + opad[i])
                     for i in range(n)]
        # weight [I, O/g, *k] → flip spatial, swap to [O, I/g, *k]
        wf = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            ic, ocg = wf.shape[0], wf.shape[1]
            wf = wf.reshape((groups, ic // groups) + wf.shape[1:])
            wf = jnp.swapaxes(wf, 1, 2)  # [g, O/g, I/g, *k]
            wf = wf.reshape((ocg * groups, ic // groups) + k)
        else:
            wf = jnp.swapaxes(wf, 0, 1)
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (1, 0)
            wf = jnp.transpose(wf, perm)
        from .common import amp_compute_cast
        v = amp_compute_cast(v, wf)
        out = jax.lax.conv_general_dilated(
            v, wf.astype(v.dtype), window_strides=(1,) * n, padding=tpads,
            lhs_dilation=stride, rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=(lhs_spec, rhs_spec, out_spec),
        )
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[out_spec.index("C")] = b.shape[0]
            out = out + b.reshape(shape).astype(out.dtype)
        return out
    args = (x, weight) + ((bias,) if bias is not None else ())
    out = apply_op(_f, *args)
    if output_size is not None:
        # crop/pad to the exact requested size (paddle allows ambiguity)
        pass
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format=None, name=None):
    data_format = _resolve_df(data_format, 1)
    fmt = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, fmt, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format=None, name=None):
    data_format = _resolve_df(data_format, 3)
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size)
