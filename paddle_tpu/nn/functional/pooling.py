"""Pooling — reference python/paddle/nn/functional/pooling.py, via
lax.reduce_window (fuses well on TPU)."""
import jax
import jax.numpy as jnp
import numpy as np
from ..layout import resolve_data_format as _resolve_df

from ...framework.core import apply_op

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d", "max_unpool1d", "max_unpool2d", "max_unpool3d",
]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else [v[0]] * n))
    return (int(v),) * n


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        if len(padding) == n:
            return [(int(p), int(p)) for p in padding]
        if len(padding) == 2 * n:
            return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    return [(int(padding), int(padding))] * n


def _ceil_extend(size, k, s, pad):
    """Trailing-pad extension for ceil_mode with the torch/paddle drop
    rule: windows from ceil division fit, but a window that would start
    past input + left-pad is discarded, not emitted."""
    pl, ph = pad
    eff = size + pl + ph
    out_floor = (eff - k) // s + 1
    out_ceil = -(-(eff - k) // s) + 1
    if out_ceil > out_floor and (out_ceil - 1) * s >= size + pl:
        out_ceil -= 1
    return pl, ph + max(0, (out_ceil - 1) * s + k - eff)


def _pool(x, kernel, stride, padding, n, mode, channel_last, ceil_mode=False,
          exclusive=True, count_include_pad=False):
    kernel = _tuple(kernel, n)
    stride = _tuple(stride if stride is not None else kernel, n)
    pads = _pads(padding, n)

    def _f(v):
        if channel_last:
            window = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            wpads = ([(0, 0)] + list(pads) + [(0, 0)]) if not isinstance(pads, str) else pads
            sdims = list(range(1, 1 + n))
        else:
            window = (1, 1) + kernel
            strides = (1, 1) + stride
            wpads = ([(0, 0), (0, 0)] + list(pads)) if not isinstance(pads, str) else pads
            sdims = list(range(2, 2 + n))
        if isinstance(wpads, str):
            wpads = jax.lax.padtype_to_pads(v.shape, window, strides, wpads)
        wpads = [tuple(p) for p in wpads]
        orig_pads = list(wpads)
        if ceil_mode:
            for d in sdims:
                wpads[d] = _ceil_extend(v.shape[d], window[d], strides[d],
                                        wpads[d])
        # init values MUST be python scalars: an array init is a traced
        # constant under jit, which defeats lax's monoid specialization and
        # lands on the generic reduce_window (not reverse-differentiable)
        if mode == "max":
            init = -float("inf") if jnp.issubdtype(v.dtype, np.floating) \
                else int(jnp.iinfo(v.dtype).min)
            return jax.lax.reduce_window(v, init, jax.lax.max,
                                         window, strides, wpads)
        # avg
        zero = 0.0 if jnp.issubdtype(v.dtype, np.floating) else 0
        summed = jax.lax.reduce_window(v, zero, jax.lax.add,
                                       window, strides, wpads)
        if exclusive and not count_include_pad:
            ones = jnp.ones_like(v)
            counts = jax.lax.reduce_window(ones, zero, jax.lax.add,
                                           window, strides, wpads)
            return summed / counts
        if ceil_mode and wpads != orig_pads:
            # inclusive divisor counts input + REQUESTED padding but not
            # the ceil extension (torch/paddle rule): ones padded 1 over
            # the original pads, 0 over the extension
            ones = jnp.pad(jnp.ones_like(v),
                           [orig_pads[d] if d in sdims else (0, 0)
                            for d in range(v.ndim)],
                           constant_values=1)
            ext_pads = [(0, wpads[d][1] - orig_pads[d][1])
                        if d in sdims else (0, 0) for d in range(v.ndim)]
            counts = jax.lax.reduce_window(ones, zero, jax.lax.add,
                                           window, strides, ext_pads)
            return summed / counts
        return summed / float(np.prod(kernel))
    return apply_op(_f, x)


def _max_pool_with_mask(x, kernel, stride, padding, n, channel_last=False,
                        ceil_mode=False):
    """Max pool (n spatial dims) returning (out, mask). The mask holds the
    argmax position within the flattened input spatial plane — the contract
    max_unpool* consumes (reference phi max_pool2d_with_index kernel).
    Channel-last inputs are transposed to NC-first for the plane indexing,
    then transposed back."""
    kernel = _tuple(kernel, n)
    stride = _tuple(stride if stride is not None else kernel, n)
    pads = _pads(padding, n)
    if isinstance(pads, str):
        raise ValueError("string padding is not supported with return_mask")

    def _raw(v):
        spatial = v.shape[2:]
        flat_iota = jnp.arange(int(np.prod(spatial)), dtype=jnp.int32).reshape(spatial)
        idx = jnp.broadcast_to(flat_iota, v.shape)
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        wpads = [(0, 0), (0, 0)] + list(pads)
        if ceil_mode:
            # same extension + drop rule as _pool (shared helper): the
            # mask path must emit exactly the no-mask path's shape
            for i in range(n):
                wpads[2 + i] = _ceil_extend(spatial[i], kernel[i],
                                            stride[i], wpads[2 + i])
        neg = jnp.asarray(-jnp.inf if jnp.issubdtype(v.dtype, np.floating)
                          else jnp.iinfo(v.dtype).min, v.dtype)
        # variadic reduce: track (max value, its flat source index) per window
        def reducer(a, b):
            av, ai = a
            bv, bi = b
            take_b = bv > av
            return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)
        return jax.lax.reduce_window(
            (v, idx), (neg, jnp.asarray(-1, jnp.int32)), reducer,
            window, strides, wpads)

    # the variadic reduce_window has no AD rule; the gradient of max-pool
    # w.r.t. the input is exactly "scatter g at the argmax" — i.e. unpool.
    @jax.custom_vjp
    def _pool_op(v):
        return _raw(v)

    def _pool_fwd(v):
        out, mask = _raw(v)
        return (out, mask), (mask, v.shape)

    def _pool_bwd(res, g):
        mask, in_shape = res
        g_out, _ = g
        nc = in_shape[0] * in_shape[1]
        flat_in = int(np.prod(in_shape[2:]))
        vals = g_out.reshape(nc, -1)
        flat_idx = mask.reshape(nc, -1).astype(jnp.int32)
        dv = jnp.zeros((nc, flat_in), dtype=g_out.dtype)
        dv = dv.at[jnp.arange(nc)[:, None], flat_idx].add(vals)
        return (dv.reshape(in_shape),)

    _pool_op.defvjp(_pool_fwd, _pool_bwd)

    def _f(v):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
        out, mask = _pool_op(v)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
            mask = jnp.moveaxis(mask, 1, -1)
        return out, mask
    return apply_op(_f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 1,
                                   ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, 1, "max", False, ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 2,
                                   channel_last=data_format == "NHWC",
                                   ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, 2, "max", data_format == "NHWC", ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format=None, name=None):
    data_format = _resolve_df(data_format, 3)
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 3,
                                   channel_last=data_format == "NDHWC",
                                   ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, 3, "max", data_format == "NDHWC", ceil_mode)


def _max_unpool(x, indices, kernel_size, stride, padding, output_size, n,
                channel_last=False):
    """Scatter pooled values back to their argmax positions — reference
    python/paddle/nn/functional/pooling.py:max_unpool2d. The mask indices are
    NC-first plane positions (what _max_pool_with_mask emits for either
    layout), so channel-last inputs are transposed at the edges."""
    kernel = _tuple(kernel_size, n)
    stride = _tuple(stride if stride is not None else kernel_size, n)
    pads = [p[0] for p in _pads(padding, n)]

    def _f(v, idx):
        if channel_last:
            v = jnp.moveaxis(v, -1, 1)
            idx = jnp.moveaxis(idx, -1, 1)
        in_spatial = v.shape[2:]
        if output_size is not None:
            osz = tuple(int(s) for s in output_size[-n:])
        else:
            osz = tuple((in_spatial[i] - 1) * stride[i] - 2 * pads[i] + kernel[i]
                        for i in range(n))
        nc = v.shape[0] * v.shape[1]
        flat_out = int(np.prod(osz))
        vals = v.reshape(nc, -1)
        flat_idx = idx.reshape(nc, -1).astype(jnp.int32)
        out = jnp.zeros((nc, flat_out), dtype=v.dtype)
        out = out.at[jnp.arange(nc)[:, None], flat_idx].set(vals)
        out = out.reshape(v.shape[:2] + osz)
        return jnp.moveaxis(out, 1, -1) if channel_last else out
    return apply_op(_f, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
    data_format = _resolve_df(data_format, 1)
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 1,
                       channel_last=data_format == "NLC")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
    data_format = _resolve_df(data_format, 2)
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 2,
                       channel_last=data_format == "NHWC")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
    data_format = _resolve_df(data_format, 3)
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 3,
                       channel_last=data_format == "NDHWC")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", False, ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    return _pool(x, kernel_size, stride, padding, 2, "avg", data_format == "NHWC",
                 ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format=None, name=None):
    data_format = _resolve_df(data_format, 3)
    return _pool(x, kernel_size, stride, padding, 3, "avg", data_format == "NDHWC",
                 ceil_mode, exclusive)


def _adaptive_avg(v, out_sizes, spatial_axes):
    """Raw-array adaptive mean over explicit axes — the kernel behind
    interpolate(mode='area') and the _adaptive wrapper."""
    out = v
    for ax, o in zip(spatial_axes, out_sizes):
        s_in = out.shape[ax]
        starts = (np.arange(o) * s_in) // o
        ends = ((np.arange(o) + 1) * s_in + o - 1) // o
        out = jnp.concatenate(
            [jnp.mean(jax.lax.slice_in_dim(out, int(s), int(e), axis=ax),
                      axis=ax, keepdims=True)
             for s, e in zip(starts, ends)], axis=ax)
    return out


def _adaptive(x, output_size, n, mode, channel_last=False):
    def _f(v):
        spatial = list(range(1, 1 + n)) if channel_last else list(range(v.ndim - n, v.ndim))
        osz = output_size if isinstance(output_size, (list, tuple)) else [output_size] * n
        osz = [v.shape[ax] if o is None else int(o) for ax, o in zip(spatial, osz)]
        if mode == "avg":
            return _adaptive_avg(v, osz, spatial)
        out = v
        for ax, o in zip(spatial, osz):
            s_in = out.shape[ax]
            starts = (np.arange(o) * s_in) // o
            ends = ((np.arange(o) + 1) * s_in + o - 1) // o
            out = jnp.concatenate(
                [jnp.max(jax.lax.slice_in_dim(out, int(s), int(e), axis=ax),
                         axis=ax, keepdims=True)
                 for s, e in zip(starts, ends)], axis=ax)
        return out
    return apply_op(_f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg")


def adaptive_avg_pool2d(x, output_size, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    return _adaptive(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format=None, name=None):
    data_format = _resolve_df(data_format, 3)
    return _adaptive(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max")
