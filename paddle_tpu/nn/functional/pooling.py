"""Pooling — reference python/paddle/nn/functional/pooling.py, via
lax.reduce_window (fuses well on TPU)."""
import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import apply_op

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d",
]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else [v[0]] * n))
    return (int(v),) * n


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        if len(padding) == n:
            return [(int(p), int(p)) for p in padding]
        if len(padding) == 2 * n:
            return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    return [(int(padding), int(padding))] * n


def _pool(x, kernel, stride, padding, n, mode, channel_last, ceil_mode=False,
          exclusive=True, count_include_pad=False):
    kernel = _tuple(kernel, n)
    stride = _tuple(stride if stride is not None else kernel, n)
    pads = _pads(padding, n)

    def _f(v):
        if channel_last:
            window = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            wpads = ([(0, 0)] + list(pads) + [(0, 0)]) if not isinstance(pads, str) else pads
        else:
            window = (1, 1) + kernel
            strides = (1, 1) + stride
            wpads = ([(0, 0), (0, 0)] + list(pads)) if not isinstance(pads, str) else pads
        if isinstance(wpads, str):
            wpads = jax.lax.padtype_to_pads(v.shape, window, strides, wpads)
        if mode == "max":
            init = -jnp.inf if jnp.issubdtype(v.dtype, np.floating) else jnp.iinfo(v.dtype).min
            return jax.lax.reduce_window(v, jnp.asarray(init, v.dtype), jax.lax.max,
                                         window, strides, wpads)
        # avg
        summed = jax.lax.reduce_window(v, jnp.asarray(0, v.dtype), jax.lax.add,
                                       window, strides, wpads)
        if exclusive and not count_include_pad:
            ones = jnp.ones_like(v)
            counts = jax.lax.reduce_window(ones, jnp.asarray(0, v.dtype), jax.lax.add,
                                           window, strides, wpads)
            return summed / counts
        return summed / float(np.prod(kernel))
    return apply_op(_f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "max", False, ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "max", data_format == "NHWC", ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "max", data_format == "NDHWC", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", False, ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", data_format == "NHWC",
                 ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", data_format == "NDHWC",
                 ceil_mode, exclusive)


def _adaptive(x, output_size, n, mode, channel_last=False):
    def _f(v):
        spatial = list(range(1, 1 + n)) if channel_last else list(range(v.ndim - n, v.ndim))
        osz = output_size if isinstance(output_size, (list, tuple)) else [output_size] * n
        osz = [v.shape[ax] if o is None else int(o) for ax, o in zip(spatial, osz)]
        out = v
        for ax, o in zip(spatial, osz):
            s_in = out.shape[ax]
            starts = (np.arange(o) * s_in) // o
            ends = ((np.arange(o) + 1) * s_in + o - 1) // o
            slices = []
            for s, e in zip(starts, ends):
                seg = jax.lax.slice_in_dim(out, int(s), int(e), axis=ax)
                red = jnp.max(seg, axis=ax, keepdims=True) if mode == "max" \
                    else jnp.mean(seg, axis=ax, keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=ax)
        return out
    return apply_op(_f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max")
