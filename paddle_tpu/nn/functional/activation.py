"""Activation functionals — reference python/paddle/nn/functional/activation.py.
All map to jax.nn / lax primitives that XLA fuses into adjacent matmuls."""
import jax
import jax.numpy as jnp
from ..layout import resolve_data_format as _resolve_df

from ...framework.core import Tensor, apply_op

__all__ = [
    "relu", "relu_", "relu6", "gelu", "silu", "sigmoid", "tanh", "tanh_",
    "softmax", "softmax_", "log_softmax", "leaky_relu", "elu", "elu_", "celu",
    "selu", "softplus", "softsign", "softshrink", "hardshrink", "tanhshrink",
    "hardsigmoid", "hardswish", "hardtanh", "prelu", "rrelu", "swish", "mish",
    "maxout", "thresholded_relu", "log_sigmoid", "glu", "gumbel_softmax",
]


def relu(x, name=None):
    return apply_op(jax.nn.relu, x)


def relu_(x, name=None):
    return x._inplace_update(jax.nn.relu)


def relu6(x, name=None):
    return apply_op(jax.nn.relu6, x)


def gelu(x, approximate=False, name=None):
    return apply_op(lambda v: jax.nn.gelu(v, approximate=approximate), x)


def silu(x, name=None):
    return apply_op(jax.nn.silu, x)


swish = silu


def sigmoid(x, name=None):
    return apply_op(jax.nn.sigmoid, x)


def tanh(x, name=None):
    return apply_op(jnp.tanh, x)


def tanh_(x, name=None):
    return x._inplace_update(jnp.tanh)


def softmax(x, axis=-1, dtype=None, name=None):
    def _f(v):
        if dtype is not None:
            v = v.astype(jnp.dtype(dtype))
        return jax.nn.softmax(v, axis=axis)
    return apply_op(_f, x)


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._inplace_update(lambda v: jax.nn.softmax(v, axis=axis))


def log_softmax(x, axis=-1, dtype=None, name=None):
    def _f(v):
        if dtype is not None:
            v = v.astype(jnp.dtype(dtype))
        return jax.nn.log_softmax(v, axis=axis)
    return apply_op(_f, x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op(lambda v: jax.nn.leaky_relu(v, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return apply_op(lambda v: jax.nn.elu(v, alpha), x)


def elu_(x, alpha=1.0, name=None):
    return x._inplace_update(lambda v: jax.nn.elu(v, alpha))


def celu(x, alpha=1.0, name=None):
    return apply_op(lambda v: jax.nn.celu(v, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), x)


def softplus(x, beta=1, threshold=20, name=None):
    return apply_op(
        lambda v: jnp.where(v * beta > threshold, v, jnp.log1p(jnp.exp(beta * v)) / beta), x)


def softsign(x, name=None):
    return apply_op(jax.nn.soft_sign, x)


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda v: jnp.where(v > threshold, v - threshold,
                            jnp.where(v < -threshold, v + threshold, 0.0)), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), x)


def tanhshrink(x, name=None):
    return apply_op(lambda v: v - jnp.tanh(v), x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op(lambda v: jnp.clip(slope * v + offset, 0.0, 1.0), x)


def hardswish(x, name=None):
    return apply_op(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op(lambda v: jnp.clip(v, min, max), x)


def prelu(x, weight, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    def _f(v, w):
        if w.size == 1:
            wb = w.reshape(())
        else:
            ch_axis = 1 if data_format.startswith("NC") else v.ndim - 1
            shape = [1] * v.ndim
            shape[ch_axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(v > 0, v, wb * v)
    return apply_op(_f, x, weight)


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=True, name=None):
    from ...framework.random import next_key
    if training:
        key = next_key()
        def _f(v):
            a = jax.random.uniform(key, v.shape, v.dtype, lower, upper)
            return jnp.where(v >= 0, v, a * v)
        return apply_op(_f, x)
    mid = (lower + upper) / 2.0
    return apply_op(lambda v: jnp.where(v >= 0, v, mid * v), x)


def mish(x, name=None):
    return apply_op(lambda v: v * jnp.tanh(jax.nn.softplus(v)), x)


def maxout(x, groups, axis=1, name=None):
    def _f(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)
    return apply_op(_f, x)


def thresholded_relu(x, threshold=1.0, name=None):
    return apply_op(lambda v: jnp.where(v > threshold, v, 0.0), x)


def log_sigmoid(x, name=None):
    return apply_op(jax.nn.log_sigmoid, x)


def glu(x, axis=-1, name=None):
    def _f(v):
        a, b = jnp.split(v, 2, axis=axis)
        return a * jax.nn.sigmoid(b)
    return apply_op(_f, x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import next_key
    key = next_key()

    def _f(v):
        g = jax.random.gumbel(key, v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            onehot = (y == jnp.max(y, axis=axis, keepdims=True)).astype(y.dtype)
            y = jax.lax.stop_gradient(onehot - y) + y
        return y
    return apply_op(_f, x)
