"""Vision functionals — reference python/paddle/nn/functional/vision.py."""
import jax
import jax.numpy as jnp
from ..layout import resolve_data_format as _resolve_df

from ...framework.core import apply_op

__all__ = ["pixel_shuffle", "pixel_unshuffle", "channel_shuffle", "affine_grid", "grid_sample"]


def pixel_shuffle(x, upscale_factor, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    r = upscale_factor

    def _f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            out = v.reshape(n, c // (r * r), r, r, h, w)
            out = out.transpose(0, 1, 4, 2, 5, 3)
            return out.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        out = v.reshape(n, h, w, r, r, c // (r * r))
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h * r, w * r, c // (r * r))
    return apply_op(_f, x)


def pixel_unshuffle(x, downscale_factor, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    r = downscale_factor

    def _f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            out = v.reshape(n, c, h // r, r, w // r, r)
            out = out.transpose(0, 1, 3, 5, 2, 4)
            return out.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        out = v.reshape(n, h // r, r, w // r, r, c)
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h // r, w // r, c * r * r)
    return apply_op(_f, x)


def channel_shuffle(x, groups, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    def _f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            out = v.reshape(n, groups, c // groups, h, w)
            return jnp.swapaxes(out, 1, 2).reshape(n, c, h, w)
        n, h, w, c = v.shape
        out = v.reshape(n, h, w, groups, c // groups)
        return jnp.swapaxes(out, 3, 4).reshape(n, h, w, c)
    return apply_op(_f, x)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def _f(th):
        n, _, h, w = [int(s) for s in out_shape] if len(out_shape) == 4 else \
            (int(out_shape[0]), 0, int(out_shape[1]), int(out_shape[2]))
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(1, h * w, 3)
        grid = base @ jnp.swapaxes(th, 1, 2)  # [n, h*w, 2]
        return grid.reshape(th.shape[0], h, w, 2).astype(th.dtype)
    return apply_op(_f, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    def _f(v, g):
        n, c, h, w = v.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(ix, iy):
            inb = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
            if padding_mode == "border":
                ixc, iyc = jnp.clip(ix, 0, w - 1), jnp.clip(iy, 0, h - 1)
                inb = jnp.ones_like(inb)
            elif padding_mode == "reflection":
                # sampling the reflected SIGNAL at the original taps ==
                # reflecting the continuous coordinate first (torch's
                # rule): ac=True mirrors about pixel CENTERS (period
                # 2(w-1)), ac=False about pixel EDGES -0.5/w-0.5
                # (period 2w, tap m >= w folds to 2w-1-m)
                if align_corners:
                    ixc = jnp.abs(jnp.mod(ix + (w - 1), 2 * (w - 1))
                                  - (w - 1))
                    iyc = jnp.abs(jnp.mod(iy + (h - 1), 2 * (h - 1))
                                  - (h - 1))
                else:
                    mx, my = jnp.mod(ix, 2 * w), jnp.mod(iy, 2 * h)
                    ixc = jnp.where(mx >= w, 2 * w - 1 - mx, mx)
                    iyc = jnp.where(my >= h, 2 * h - 1 - my, my)
                ixc, iyc = jnp.clip(ixc, 0, w - 1), jnp.clip(iyc, 0, h - 1)
                inb = jnp.ones_like(inb)
            else:
                ixc, iyc = jnp.clip(ix, 0, w - 1), jnp.clip(iy, 0, h - 1)
            # v:[n,c,h,w], idx:[n,hg,wg]
            vals = v[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [n,hg,wg,c]
            vals = jnp.moveaxis(vals, -1, 1)
            return vals * inb[:, None].astype(v.dtype)

        if mode == "nearest":
            return sample(jnp.round(fx).astype(jnp.int32), jnp.round(fy).astype(jnp.int32))
        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = (fx - x0).astype(v.dtype)[:, None]
        wy = (fy - y0).astype(v.dtype)[:, None]
        out = (sample(x0, y0) * (1 - wx) * (1 - wy) + sample(x1, y0) * wx * (1 - wy)
               + sample(x0, y1) * (1 - wx) * wy + sample(x1, y1) * wx * wy)
        return out
    return apply_op(_f, x, grid)
