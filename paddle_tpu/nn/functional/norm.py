"""Normalization functionals — reference python/paddle/nn/functional/norm.py.
layer_norm/rms_norm have Pallas fused variants in paddle_tpu.ops; these jnp
forms are the reference implementations XLA already fuses well."""
import jax
import jax.numpy as jnp
from ..layout import resolve_data_format as _resolve_df

from ...framework.core import Tensor, apply_op

__all__ = ["normalize", "layer_norm", "batch_norm", "instance_norm", "group_norm", "local_response_norm", "rms_norm"]


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def _f(v):
        nrm = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(nrm, epsilon)
    return apply_op(_f, x)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else [normalized_shape]
    n_axes = len(ns)

    if n_axes == 1 and weight is not None and bias is not None:
        # fused Pallas path (falls back internally on odd shapes),
        # dispatched through the public custom-op registration
        from ...ops.layer_norm import fused_layer_norm_op
        return fused_layer_norm_op(x, weight, bias, eps=epsilon)

    def _f(v, *rest):
        axes = tuple(range(v.ndim - n_axes, v.ndim))
        x32 = v.astype(jnp.float32)
        mean = jnp.mean(x32, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=axes, keepdims=True)
        out = (x32 - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * rest[i].astype(v.dtype)
            i += 1
        if bias is not None:
            out = out + rest[i].astype(v.dtype)
        return out
    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply_op(_f, *args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    if weight is not None:
        from ...ops.layer_norm import fused_rms_norm_op
        return fused_rms_norm_op(x, weight, eps=epsilon)

    def _f(v, *rest):
        x32 = v.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        out = (x32 * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        if rest:
            out = out * rest[0].astype(v.dtype)
        return out
    args = (x,) + ((weight,) if weight is not None else ())
    return apply_op(_f, *args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-05, data_format=None, use_global_stats=None, name=None):
    data_format = _resolve_df(data_format, 2)
    use_global = (not training) if use_global_stats is None else use_global_stats
    ch_axis = 1 if data_format.startswith("NC") else -1

    def _f(v, rm, rv, *rest):
        ax = ch_axis % v.ndim
        shape = [1] * v.ndim
        shape[ax] = v.shape[ax]
        reduce_axes = tuple(i for i in range(v.ndim) if i != ax)
        if use_global:
            mean, var = rm, rv
        else:
            x32 = v.astype(jnp.float32)
            mean = jnp.mean(x32, axis=reduce_axes)
            var = jnp.var(x32, axis=reduce_axes)
        out = (v.astype(jnp.float32) - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + epsilon)
        out = out.astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape).astype(v.dtype)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape).astype(v.dtype)
        return out
    args = (x, running_mean, running_var) + tuple(t for t in (weight, bias) if t is not None)
    out = apply_op(_f, *args)

    # running-stat update (mirrors reference batch_norm_kernel). Eager: mutate
    # the buffers in place. Under tracing, mutation would leak tracers into
    # the buffers — instead the new values are RECORDED via the buffer-update
    # sink, and the compiled-step owner (distributed.trainer.Trainer) carries
    # them across steps; a bare jit with no sink skips the update.
    if training and not use_global and isinstance(running_mean, Tensor) \
            and isinstance(x._value, jax.Array):
        v = x._value.astype(jnp.float32)
        ax = ch_axis % v.ndim
        reduce_axes = tuple(i for i in range(v.ndim) if i != ax)
        batch_mean = jnp.mean(v, axis=reduce_axes)
        batch_var = jnp.var(v, axis=reduce_axes)
        new_rm = (momentum * running_mean._value
                  + (1 - momentum) * batch_mean.astype(running_mean.dtype))
        new_rv = (momentum * running_var._value
                  + (1 - momentum) * batch_var.astype(running_var.dtype))
        if isinstance(x._value, jax.core.Tracer):
            from ..layer_base import record_buffer_update
            record_buffer_update(running_mean, new_rm)
            record_buffer_update(running_var, new_rv)
        else:
            running_mean._value = new_rm
            running_var._value = new_rv
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    chan_last = not data_format.startswith("NC")

    def _f(v, *rest):
        vv = jnp.moveaxis(v, -1, 1) if chan_last else v
        spatial = tuple(range(2, vv.ndim))
        x32 = vv.astype(jnp.float32)
        mean = jnp.mean(x32, axis=spatial, keepdims=True)
        var = jnp.var(x32, axis=spatial, keepdims=True)
        out = ((x32 - mean) * jax.lax.rsqrt(var + eps)).astype(v.dtype)
        shape = [1] * vv.ndim
        shape[1] = vv.shape[1]
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape).astype(v.dtype)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape).astype(v.dtype)
        return jnp.moveaxis(out, 1, -1) if chan_last else out
    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply_op(_f, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    def _f(v, *rest):
        n = v.shape[0]
        if data_format == "NHWC":
            v_nchw = jnp.moveaxis(v, -1, 1)
        else:
            v_nchw = v
        c = v_nchw.shape[1]
        g = num_groups
        grouped = v_nchw.reshape((n, g, c // g) + v_nchw.shape[2:]).astype(jnp.float32)
        axes = tuple(range(2, grouped.ndim))
        mean = jnp.mean(grouped, axis=axes, keepdims=True)
        var = jnp.var(grouped, axis=axes, keepdims=True)
        out = ((grouped - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v_nchw.shape).astype(v.dtype)
        shape = [1] * out.ndim
        shape[1] = c
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape).astype(v.dtype)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape).astype(v.dtype)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply_op(_f, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format=None, name=None):
    data_format = _resolve_df(data_format, 2)
    def _f(v):
        ch_axis = 1 if data_format.startswith("NC") else v.ndim - 1
        sq = jnp.square(v)
        moved = jnp.moveaxis(sq, ch_axis, -1)
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        padded = jnp.pad(moved, [(0, 0)] * (moved.ndim - 1) + [(pad_lo, pad_hi)])
        win = jax.lax.reduce_window(
            padded, 0.0, jax.lax.add,  # python scalar: keeps the monoid path
            (1,) * (moved.ndim - 1) + (size,), (1,) * moved.ndim, "VALID")
        win = jnp.moveaxis(win, -1, ch_axis)
        return v / jnp.power(k + alpha * win, beta)
    return apply_op(_f, x)
