"""Quantization-aware-training layers — reference
python/paddle/nn/quant/quant_layers.py. Fake-quant: quantize→dequantize in
forward with a straight-through estimator, so XLA still sees dense bf16/fp32
matmuls (real int8 execution lives in paddle_tpu.quantization)."""
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply_op
from ..layer_base import Layer

__all__ = [
    "FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax", "FakeQuantChannelWiseAbsMax",
    "QuantizedConv2D", "QuantizedConv2DTranspose", "QuantizedLinear",
    "MovingAverageAbsMaxScale", "MAOutputScaleLayer", "FakeQuantMAOutputScaleLayer",
    "QuantStub",
]


def _fake_quant(x, scale, bits):
    """Quantize-dequantize with straight-through gradient."""
    qmax = float(2 ** (bits - 1) - 1)
    def _f(v, s):
        s = jnp.maximum(s, 1e-9)
        q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax) * s / qmax
        # straight-through: forward q, backward identity
        return v + jax.lax.stop_gradient(q - v)
    return apply_op(_f, x, scale)


class FakeQuantAbsMax(Layer):
    def __init__(self, name=None, quant_bits=8, dtype="float32", quant_on_weight=False):
        super().__init__()
        self._quant_bits = quant_bits

    def forward(self, input):
        scale = input.abs().max()
        return _fake_quant(input, scale, self._quant_bits)


class FakeQuantChannelWiseAbsMax(Layer):
    def __init__(self, name=None, channel_num=None, quant_bits=8,
                 quant_axis=0, dtype="float32", quant_on_weight=False):
        super().__init__()
        self._quant_bits = quant_bits
        self._quant_axis = quant_axis

    def forward(self, input):
        def _f(v):
            axes = tuple(a for a in range(v.ndim) if a != self._quant_axis)
            return jnp.max(jnp.abs(v), axis=axes, keepdims=True)
        scale = apply_op(_f, input)
        return _fake_quant(input, scale, self._quant_bits)


class FakeQuantMovingAverageAbsMax(Layer):
    def __init__(self, name=None, moving_rate=0.9, quant_bits=8, dtype="float32"):
        super().__init__()
        self._moving_rate = moving_rate
        self._quant_bits = quant_bits
        # persisted like the reference's `state`/`accum` tensors so a
        # restored QAT checkpoint keeps its EMA instead of re-seeding
        self.register_buffer("seen", Tensor(jnp.zeros([], jnp.int32)),
                             persistable=True)
        self.register_buffer("scale", Tensor(jnp.ones([])), persistable=True)

    def forward(self, input):
        if self.training:
            cur = input.abs().max()
            seeded = self.seen._value > 0
            ema = self.scale._value * self._moving_rate \
                + cur._value * (1 - self._moving_rate)
            new = jnp.where(seeded, ema, cur._value)
            self.scale._value = jax.lax.stop_gradient(new)
            self.seen._value = jnp.ones([], jnp.int32)
        return _fake_quant(input, Tensor(self.scale._value), self._quant_bits)


class MovingAverageAbsMaxScale(Layer):
    """Observes abs-max scale of activations without quantizing."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32"):
        super().__init__()
        self._moving_rate = moving_rate
        self.register_buffer("scale", Tensor(jnp.ones([])), persistable=True)

    def forward(self, input):
        if self.training:
            cur = input.abs().max()
            new = self.scale._value * self._moving_rate \
                + cur._value * (1 - self._moving_rate)
            self.scale._value = jax.lax.stop_gradient(new)
        return input


class _QuantizedWrapper(Layer):
    """Wraps a float layer: fake-quants weight + activation, then calls it."""

    def __init__(self, layer, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 weight_quantize_type="abs_max", activation_quantize_type="moving_average_abs_max",
                 **kwargs):
        super().__init__()
        self._inner = layer
        if weight_quantize_type == "channel_wise_abs_max":
            # per-OUTPUT-channel grid: out channels live on the LAST axis
            # of both Linear [in, out] and conv [..., in, out] weights —
            # must match quantize_weight(axis=0)'s per-out export grid
            self._fake_quant_weight = FakeQuantChannelWiseAbsMax(
                quant_bits=weight_bits,
                quant_axis=layer.weight._value.ndim - 1)
        else:
            self._fake_quant_weight = FakeQuantAbsMax(quant_bits=weight_bits, quant_on_weight=True)
        self._fake_quant_input = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)

    def forward(self, input):
        qin = self._fake_quant_input(input)
        w = self._inner.weight
        qw = self._fake_quant_weight(Tensor(w._value, stop_gradient=w.stop_gradient))
        saved = w._value
        try:
            self._inner.weight._value = qw._value
            return self._inner(qin)
        finally:
            self._inner.weight._value = saved


class QuantizedLinear(_QuantizedWrapper):
    pass


class QuantizedConv2D(_QuantizedWrapper):
    pass


class QuantizedConv2DTranspose(_QuantizedWrapper):
    pass


class QuantStub(Layer):
    """Marks a quantization entry point; observes activation scale."""

    def __init__(self, name=None, moving_rate=0.9):
        super().__init__()
        self._observer = MovingAverageAbsMaxScale(moving_rate=moving_rate)

    def forward(self, input):
        return self._observer(input)


class MAOutputScaleLayer(Layer):
    def __init__(self, layer=None, moving_rate=0.9, name=None, dtype="float32"):
        super().__init__()
        self._layer = layer
        self._ma_output_scale = MovingAverageAbsMaxScale(moving_rate=moving_rate)

    def forward(self, *inputs, **kwargs):
        out = self._layer(*inputs, **kwargs)
        if isinstance(out, (list, tuple)):
            return out
        return self._ma_output_scale(out)


class FakeQuantMAOutputScaleLayer(Layer):
    def __init__(self, layer, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 name=None, *args, **kwargs):
        super().__init__()
        self._layer = layer
        self._fake_quant_output = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)

    def forward(self, *inputs, **kwargs):
        out = self._layer(*inputs, **kwargs)
        if isinstance(out, (list, tuple)):
            return out
        return self._fake_quant_output(out)
