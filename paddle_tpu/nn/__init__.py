"""paddle_tpu.nn — reference python/paddle/nn/__init__.py."""
from . import functional  # noqa: F401
from . import layout  # noqa: F401
from .layout import channels_last_enabled, set_channels_last  # noqa: F401
from . import initializer  # noqa: F401
from . import quant  # noqa: F401
from . import utils  # noqa: F401
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm,  # noqa: F401
                   ClipGradByValue, ClipGradForMOEByGlobalNorm)
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .utils import spectral_norm  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer_base import Layer, ParamAttr, functional_call, state_pytree  # noqa: F401
