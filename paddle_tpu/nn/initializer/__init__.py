"""Weight initializers — API of reference python/paddle/nn/initializer/*.

Each initializer is a callable (shape, dtype) -> jax.Array, drawing from the
global seeded key stream so paddle.seed() reproduces full init.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ...framework.dtype import dtype as _dt
from ...framework.random import next_key

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "Bilinear", "calculate_gain",
    "set_global_initializer",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in gains:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return gains[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 2:
        fan_in = fan_out = shape[0] if shape else 1
    else:
        # paddle convention: fan_in = shape[0]*rf, fan_out = shape[1]*rf for
        # linear ([in, out]) and conv ([out, in, *k] → handled via receptive field)
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        if len(shape) > 2:  # conv weight [out_c, in_c, *kernel]
            fan_in = shape[1] * receptive
            fan_out = shape[0] * receptive
        else:  # linear weight [in, out]
            fan_in, fan_out = shape[0], shape[1]
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        return jnp.full(tuple(shape), v, _dt(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        d = _dt(dtype)
        return jax.random.normal(next_key(), tuple(shape), d) * jnp.asarray(self.std, d) + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        d = _dt(dtype)
        return jax.random.truncated_normal(next_key(), -2.0, 2.0, tuple(shape), d) \
            * jnp.asarray(self.std, d) + self.mean


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(next_key(), tuple(shape), _dt(dtype), self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(next_key(), tuple(shape), _dt(dtype)) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), tuple(shape), _dt(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return jax.random.normal(next_key(), tuple(shape), _dt(dtype)) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), tuple(shape), _dt(dtype), -limit, limit)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(np.asarray(v), _dt(dtype)).reshape(tuple(shape))
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype):
        shape = tuple(shape)
        rows, cols = shape[0], int(np.prod(shape[1:]))
        flat = jax.random.normal(next_key(), (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(_dt(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        # conv weight [out_c, in_c, *kernel] — identity-preserving init
        arr = np.zeros(tuple(shape), np.float32)
        out_c, in_c = shape[0], shape[1]
        centers = [k // 2 for k in shape[2:]]
        per_group = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per_group, in_c)):
                idx = (g * per_group + i, i, *centers)
                arr[idx] = 1.0
        return jnp.asarray(arr, _dt(dtype))


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed conv (reference
    python/paddle/nn/initializer/Bilinear): each [kh, kw] slice is the
    bilinear interpolation stencil, identical across channels."""

    def __call__(self, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D conv "
                             f"weight, got shape {shape}")
        arr = np.zeros(tuple(shape), np.float32)
        kh, kw = shape[2], shape[3]
        fh, fw = (kh + 1) // 2, (kw + 1) // 2
        ch = (2 * fh - 1 - fh % 2) / (2.0 * fh)
        cw = (2 * fw - 1 - fw % 2) / (2.0 * fw)
        yy, xx = np.meshgrid(np.arange(kh), np.arange(kw), indexing="ij")
        stencil = (1 - np.abs(yy / fh - ch)) * (1 - np.abs(xx / fw - cw))
        arr[:, :] = stencil
        return jnp.asarray(arr, _dt(dtype))


_global_weight_init = [None]
_global_bias_init = [None]


def set_global_initializer(weight_init, bias_init=None):
    _global_weight_init[0] = weight_init
    _global_bias_init[0] = bias_init
