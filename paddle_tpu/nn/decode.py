"""Beam-search decoding — reference python/paddle/fluid/layers/rnn.py:870
(BeamSearchDecoder) and :1587 (dynamic_decode).

The decode loop runs as a host loop over jitted step functions (decode is
latency-bound, not FLOP-bound; the per-step cell is still XLA-compiled).
Production generation uses models.generate() (lax.scan + KV cache) — this
class exists for API parity with paddle.nn.BeamSearchDecoder.
"""
import collections

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """Abstract decoder interface: initialize / step / finalize."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states

    @property
    def tracks_own_finished(self):
        return False


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _tree_gather_beams(tree, beam_indices, batch_size, beam_size):
    """Reorder the beam axis of every (B*K, ...) leaf by beam_indices (B, K)."""
    def _g(leaf):
        leaf = _unwrap(leaf)
        if not hasattr(leaf, "shape") or leaf.ndim == 0:
            return leaf
        shaped = leaf.reshape((batch_size, beam_size) + leaf.shape[1:])
        out = jnp.take_along_axis(
            shaped, beam_indices.reshape((batch_size, beam_size) +
                                         (1,) * (shaped.ndim - 2)).astype(jnp.int32),
            axis=1)
        return out.reshape(leaf.shape)
    return jax.tree_util.tree_map(_g, tree)


class BeamSearchDecoder(Decoder):
    """Reference python/paddle/fluid/layers/rnn.py:BeamSearchDecoder."""

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """(B, ...) -> (B*beam, ...) by tiling each batch item beam_size times."""
        arr = _unwrap(x)
        tiled = jnp.repeat(arr[:, None], beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + arr.shape[1:]))

    def _expand_to_beam_size(self, x):
        arr = _unwrap(x)
        return jnp.repeat(arr[:, None], self.beam_size, axis=1)

    def initialize(self, initial_cell_states):
        states = jax.tree_util.tree_map(
            lambda s: self.tile_beam_merge_with_batch(s, self.beam_size)._value
            if hasattr(_unwrap(s), "shape") else s, initial_cell_states)
        leaf = jax.tree_util.tree_leaves(states)[0]
        self._batch_size = int(leaf.shape[0]) // self.beam_size
        b, k = self._batch_size, self.beam_size
        log_probs = jnp.tile(
            jnp.array([0.0] + [-1e9] * (k - 1), jnp.float32), (b, 1))
        finished = jnp.zeros((b, k), jnp.bool_)
        lengths = jnp.zeros((b, k), jnp.int32)
        init_ids = jnp.full((b, k), self.start_token, jnp.int32)
        init_inputs = self.embedding_fn(Tensor(init_ids.reshape(-1))) \
            if self.embedding_fn is not None else Tensor(init_ids.reshape(-1))
        state = self.StateWrapper(states, log_probs, finished, lengths)
        return init_inputs, state, Tensor(finished)

    def _beam_search_step(self, time, logits, next_cell_states, beam_state):
        b, k = self._batch_size, self.beam_size
        logits = _unwrap(logits).astype(jnp.float32)
        vocab = logits.shape[-1]
        step_log_probs = jax.nn.log_softmax(logits.reshape(b, k, vocab))
        # finished beams only extend with end_token at probability 1
        noend = jnp.full((vocab,), -1e9, jnp.float32).at[self.end_token].set(0.0)
        step_log_probs = jnp.where(beam_state.finished[:, :, None],
                                   noend[None, None, :], step_log_probs)
        total = beam_state.log_probs[:, :, None] + step_log_probs
        flat = total.reshape(b, k * vocab)
        topk_scores, topk_idx = jax.lax.top_k(flat, k)
        beam_idx = (topk_idx // vocab).astype(jnp.int32)
        token_ids = (topk_idx % vocab).astype(jnp.int32)
        next_finished = jnp.take_along_axis(beam_state.finished, beam_idx, axis=1)
        next_lengths = jnp.take_along_axis(beam_state.lengths, beam_idx, axis=1)
        next_lengths = next_lengths + jnp.where(next_finished, 0, 1)
        next_finished = next_finished | (token_ids == self.end_token)
        cell_states = _tree_gather_beams(next_cell_states, beam_idx, b, k)
        next_state = self.StateWrapper(cell_states, topk_scores,
                                       next_finished, next_lengths)
        output = self.OutputWrapper(Tensor(topk_scores), Tensor(token_ids),
                                    Tensor(beam_idx.astype(jnp.int32)))
        return output, next_state

    def step(self, time, inputs, states, **kwargs):
        cell_outputs, next_cell_states = self.cell(inputs, states.cell_states, **kwargs)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        outputs, next_state = self._beam_search_step(
            time, cell_outputs, next_cell_states, states)
        next_inputs = self.embedding_fn(outputs.predicted_ids.reshape([-1])) \
            if self.embedding_fn is not None else outputs.predicted_ids
        return outputs, next_state, next_inputs, Tensor(next_state.finished)

    @property
    def tracks_own_finished(self):
        return True

    def finalize(self, outputs, final_states, sequence_lengths):
        """Back-trace parent pointers (gather_tree) to emit final beams."""
        pred = np.stack([np.asarray(_unwrap(o.predicted_ids)) for o in outputs])   # (T, B, K)
        parents = np.stack([np.asarray(_unwrap(o.parent_ids)) for o in outputs])
        t_max, b, k = pred.shape
        out = np.zeros_like(pred)
        beams = np.tile(np.arange(k), (b, 1))
        for t in range(t_max - 1, -1, -1):
            out[t] = np.take_along_axis(pred[t], beams, axis=1)
            beams = np.take_along_axis(parents[t], beams, axis=1)
        # (T, B, K) -> (B, T, K) as in reference finalize
        return Tensor(jnp.asarray(out.transpose(1, 0, 2))), final_states


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Run decoder.initialize/step until finished — reference
    python/paddle/fluid/layers/rnn.py:dynamic_decode."""
    inputs, states, finished = decoder.initialize(inits)
    outputs = []
    step = 0
    limit = max_step_num if max_step_num is not None else 256
    while step <= limit:
        out, states, inputs, finished = decoder.step(step, inputs, states, **kwargs)
        outputs.append(out)
        step += 1
        if bool(np.asarray(_unwrap(finished)).all()):
            break
    seq_len = Tensor(states.lengths) if hasattr(states, "lengths") else None
    final_outputs, final_states = decoder.finalize(outputs, states, seq_len)
    if output_time_major and isinstance(final_outputs, Tensor):
        final_outputs = Tensor(jnp.swapaxes(_unwrap(final_outputs), 0, 1))
    if return_length:
        return final_outputs, final_states, seq_len
    return final_outputs, final_states
