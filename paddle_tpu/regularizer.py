"""Regularizers — reference python/paddle/regularizer.py."""

__all__ = ["L1Decay", "L2Decay"]


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        self.coeff = self._coeff

    def __call__(self, param):
        import jax.numpy as jnp
        return self._coeff * jnp.sign(param)


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        self.coeff = self._coeff

    def __call__(self, param):
        return self._coeff * param
