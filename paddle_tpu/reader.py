"""Legacy reader combinators — reference python/paddle/reader/decorator.py.

Readers are zero-arg callables returning iterators. The reference's
multiprocess/xmap variants exist for CPU-bound python decode; here the fast
path is paddle_tpu.io.DataLoader (+ native worker pool in runtime/), so
these combinators run threaded/serial but keep identical semantics.
"""
import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "xmap_readers", "multiprocess_reader"]


def cache(reader):
    all_data = []
    filled = []

    def cached():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)
    return cached


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return shuffled


def chain(*readers):
    def chained():
        return itertools.chain(*[r() for r in readers])
    return chained


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        rs = [r() for r in readers]
        if check_alignment:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in itertools.zip_longest(*rs):
                yield sum((make_tuple(i) for i in items if i is not None), ())
    return composed


def buffered(reader, size):
    """Prefetch up to `size` items on a worker thread."""
    end = object()

    def buffered_reader():
        q = _queue.Queue(maxsize=size)

        def fill():
            try:
                for item in reader():
                    q.put(item)
            finally:
                q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                return
            yield item
    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        return itertools.islice(reader(), n)
    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (the reference uses
    processes; decode workloads here should use io.DataLoader instead)."""
    end = object()

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def feed():
            for i, item in enumerate(reader()):
                in_q.put((i, item))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                got = in_q.get()
                if got is end:
                    out_q.put(end)
                    return
                i, item = got
                out_q.put((i, mapper(item)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        done = 0
        if order:
            pending = {}
            want = 0
            while done < process_num:
                got = out_q.get()
                if got is end:
                    done += 1
                    continue
                i, val = got
                pending[i] = val
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while done < process_num:
                got = out_q.get()
                if got is end:
                    done += 1
                    continue
                yield got[1]
    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Serial-fallback of the reference's fork-based multiprocess reader
    (single-controller JAX processes shouldn't fork); semantics preserved."""
    def reader():
        for r in readers:
            yield from r()
    return reader
