"""Weight-only int8 quantization for inference.

Reference counterpart: paddle/fluid/contrib/slim quantization + nn.quant.
TPU-native: per-channel symmetric int8 weights with bf16 activations — the
dequantize folds into the matmul epilogue; XLA keeps the int8 weights in HBM
(half the bandwidth of bf16, the usual decode bottleneck).
"""
import jax
import jax.numpy as jnp

from .framework.core import Parameter, Tensor, apply_op
from .nn import Linear
from .nn.layer_base import Layer

__all__ = ["quantize_weight", "dequantize_weight", "QuantizedLinear",
           "quantize_model"]


def quantize_weight(w, axis=0):
    """w: [in, out] float → (int8 w_q, float32 scale[out]) per-channel."""
    wv = w._value if isinstance(w, Tensor) else jnp.asarray(w)
    amax = jnp.max(jnp.abs(wv.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(wv.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_weight(q, scale):
    return q.astype(jnp.float32) * scale


class QuantizedLinear(Layer):
    """Drop-in Linear with int8 weight + per-out-channel scale."""

    def __init__(self, linear: Linear):
        super().__init__()
        q, scale = quantize_weight(linear.weight, axis=0)
        self.register_buffer("weight_q", Tensor(q))
        self.register_buffer("weight_scale", Tensor(scale))
        self.bias = linear.bias
        self._out_features = linear._out_features
        self._in_features = linear._in_features

    def forward(self, x):
        def _f(v, q, s, *rest):
            w = (q.astype(v.dtype) * s.astype(v.dtype))
            out = v @ w
            if rest:
                out = out + rest[0]
            return out
        args = (x, self.weight_q, self.weight_scale) + \
            ((self.bias,) if self.bias is not None else ())
        return apply_op(_f, *args)


def quantize_model(model, min_out_features=64):
    """Replace every Linear (≥ min_out_features) with QuantizedLinear."""
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, Linear) and sub._out_features >= min_out_features:
            model._sub_layers[name] = QuantizedLinear(sub)
        else:
            quantize_model(sub, min_out_features)
    return model
