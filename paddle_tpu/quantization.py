"""Weight-only int8 quantization for inference.

Reference counterpart: paddle/fluid/contrib/slim quantization + nn.quant.
TPU-native: per-channel symmetric int8 weights with bf16 activations — the
dequantize folds into the matmul epilogue; XLA keeps the int8 weights in HBM
(half the bandwidth of bf16, the usual decode bottleneck).
"""
import jax
import jax.numpy as jnp

from .framework.core import Parameter, Tensor, apply_op
from .nn import Linear
from .nn.layer_base import Layer

__all__ = ["quantize_weight", "dequantize_weight", "QuantizedLinear",
           "quantize_model", "QuantizedLinearA8W8", "PTQ"]


def quantize_weight(w, axis=0):
    """w: [in, out] float → (int8 w_q, float32 scale[out]) per-channel."""
    wv = w._value if isinstance(w, Tensor) else jnp.asarray(w)
    amax = jnp.max(jnp.abs(wv.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(wv.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_weight(q, scale):
    return q.astype(jnp.float32) * scale


class QuantizedLinear(Layer):
    """Drop-in Linear with int8 weight + per-out-channel scale."""

    def __init__(self, linear: Linear):
        super().__init__()
        q, scale = quantize_weight(linear.weight, axis=0)
        self.register_buffer("weight_q", Tensor(q))
        self.register_buffer("weight_scale", Tensor(scale))
        self.bias = linear.bias
        self._out_features = linear._out_features
        self._in_features = linear._in_features

    def forward(self, x):
        def _f(v, q, s, *rest):
            w = (q.astype(v.dtype) * s.astype(v.dtype))
            out = v @ w
            if rest:
                out = out + rest[0]
            return out
        args = (x, self.weight_q, self.weight_scale) + \
            ((self.bias,) if self.bias is not None else ())
        return apply_op(_f, *args)


def quantize_model(model, min_out_features=64):
    """Replace every Linear (≥ min_out_features) with QuantizedLinear."""
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, Linear) and sub._out_features >= min_out_features:
            model._sub_layers[name] = QuantizedLinear(sub)
        else:
            quantize_model(sub, min_out_features)
    return model


# ---------------------------------------------------------------------------
# Post-training static quantization (A8W8) — reference paddle slim PTQ
# (fluid/contrib/slim post_training_quantization.py: abs-max activation
# calibration + per-channel weights). On TPU the int8·int8→int32 matmul
# runs on the MXU via dot_general(preferred_element_type=int32).
# ---------------------------------------------------------------------------


class QuantizedLinearA8W8(Layer):
    """Linear with int8 weights AND int8 activations (static scale from
    calibration): y = (q_x · q_w) · (s_x · s_w) + b."""

    def __init__(self, linear: Linear, act_scale):
        super().__init__()
        q, scale = quantize_weight(linear.weight, axis=0)
        self.register_buffer("weight_q", Tensor(q))
        self.register_buffer("weight_scale", Tensor(scale))
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(act_scale, jnp.float32)))
        self.bias = linear.bias
        self._out_features = linear._out_features
        self._in_features = linear._in_features

    def forward(self, x):
        def _f(v, q, sw, sx, *rest):
            qx = jnp.clip(jnp.round(v.astype(jnp.float32) / sx),
                          -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                qx, q, (((qx.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            # sw is [1, out] (keepdims): flatten so a 1-D input keeps
            # Linear's [out] output rank instead of broadcasting to [1, out]
            out = acc.astype(jnp.float32) * (sw.reshape(-1) * sx)
            if rest:
                out = out + rest[0].astype(jnp.float32)
            return out.astype(v.dtype)
        args = (x, self.weight_q, self.weight_scale, self.act_scale) + \
            ((self.bias,) if self.bias is not None else ())
        return apply_op(_f, *args)


class PTQ:
    """Post-training static quantization driver.

        ptq = PTQ(model)                 # hooks every Linear
        for batch in calib: model(batch) # observe activation abs-max
        model = ptq.convert()            # Linears -> int8 A8W8

    Calibration records the running abs-max of each Linear's INPUT; convert
    swaps in QuantizedLinearA8W8 with that static scale and removes hooks.
    """

    def __init__(self, model, min_out_features=16):
        self.model = model
        self.min_out = min_out_features
        self._amax = {}
        self._handles = []
        for name, sub in model.named_sublayers():
            if isinstance(sub, Linear) and \
                    sub._out_features >= min_out_features:
                self._hook(name, sub)

    def _hook(self, name, layer):
        def pre(lyr, inputs):
            x = inputs[0]
            v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
            try:
                amax = float(jnp.max(jnp.abs(v.astype(jnp.float32))))
            except Exception:        # traced (jitted calibration): skip
                return None
            prev = self._amax.get(name, 0.0)
            self._amax[name] = max(prev, amax)
            return None
        self._handles.append(layer.register_forward_pre_hook(pre))

    def convert(self):
        import warnings
        for h in self._handles:
            try:
                h.remove()
            except Exception:
                pass
        if self._handles and not any(v > 0 for v in self._amax.values()):
            warnings.warn(
                "PTQ.convert(): calibration observed no activations (were "
                "the calibration forwards run eagerly, not under jit?); "
                "returning the model UNQUANTIZED", RuntimeWarning)

        def swap(layer, prefix=""):
            for name, sub in list(layer._sub_layers.items()):
                full = f"{prefix}{name}"
                if isinstance(sub, Linear) and full in self._amax \
                        and self._amax[full] > 0:
                    scale = max(self._amax[full] / 127.0, 1e-8)
                    layer._sub_layers[name] = QuantizedLinearA8W8(sub, scale)
                else:
                    swap(sub, f"{full}.")
        swap(self.model)
        return self.model
