"""Weight-only int8 quantization for inference.

Reference counterpart: paddle/fluid/contrib/slim quantization + nn.quant.
TPU-native: per-channel symmetric int8 weights with bf16 activations — the
dequantize folds into the matmul epilogue; XLA keeps the int8 weights in HBM
(half the bandwidth of bf16, the usual decode bottleneck).
"""
import jax
import jax.numpy as jnp

from .framework.core import Parameter, Tensor, apply_op
from .nn import Linear
from .nn.layer_base import Layer

__all__ = ["quantize_weight", "dequantize_weight", "QuantizedLinear",
           "QuantizedLinearW4", "quantize_model", "QuantizedLinearA8W8",
           "PTQ", "QAT"]


def quantize_weight(w, axis=0, bits=8):
    """w: [in, out] float → (int8 w_q, float32 scale[out]) per-channel
    symmetric; `bits` sets the grid (8 → ±127, 4 → ±7) — the ONE
    quantization recipe (PTQ, QAT export, serving a8w8 and the int4
    packer all come through here)."""
    qmax = float(2 ** (bits - 1) - 1)
    wv = w._value if isinstance(w, Tensor) else jnp.asarray(w)
    amax = jnp.max(jnp.abs(wv.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / qmax, 1e-8)
    q = jnp.clip(jnp.round(wv.astype(jnp.float32) / scale),
                 -qmax, qmax).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_weight(q, scale):
    return q.astype(jnp.float32) * scale


class QuantizedLinear(Layer):
    """Drop-in Linear with int8 weight + per-out-channel scale.
    Subclasses swap the quantizer/matmul pair (QuantizedLinearW4)."""

    def __init__(self, linear: Linear):
        super().__init__()
        q, scale = self._quantize(linear)
        self.register_buffer("weight_q", Tensor(q))
        self.register_buffer("weight_scale", Tensor(scale))
        self.bias = linear.bias
        self._out_features = linear._out_features
        self._in_features = linear._in_features

    def _quantize(self, linear):
        return quantize_weight(linear.weight, axis=0)

    def _matmul(self, v, q, s):
        return v @ (q.astype(v.dtype) * s.astype(v.dtype))

    def forward(self, x):
        def _f(v, q, s, *rest):
            out = self._matmul(v, q, s)
            if rest:
                out = out + rest[0].astype(out.dtype)
            return out
        args = (x, self.weight_q, self.weight_scale) + \
            ((self.bias,) if self.bias is not None else ())
        return apply_op(_f, *args)


class QuantizedLinearW4(QuantizedLinear):
    """Weight-only int4 Linear (two nibbles per byte, per-out-channel
    scales; ops/w4_matmul.py Pallas kernel unpacks in VMEM). Quarter the
    weight HBM traffic of bf16 — the decode regime's bottleneck — at
    ~2x the quantization error of int8."""

    def _quantize(self, linear):
        from .ops.w4_matmul import quantize_w4
        return quantize_w4(linear.weight._value)

    def _matmul(self, v, q, s):
        from .ops.w4_matmul import w4_matmul
        return w4_matmul(v, q, s, self._in_features)


def _swap_sublayers(layer, visit, prefix=""):
    """Shared sublayer-swap traversal (quantize_model, PTQ.convert,
    QAT.quantize/convert all walk the same way). `visit(full_name, sub)`
    returns a replacement layer, False to skip recursing into `sub`, or
    None to recurse."""
    for name, sub in list(layer._sub_layers.items()):
        full = f"{prefix}{name}"
        r = visit(full, sub)
        if r is False:
            continue
        if r is not None:
            layer._sub_layers[name] = r
        else:
            _swap_sublayers(sub, visit, f"{full}.")


def quantize_model(model, min_out_features=64, weight_bits=8):
    """Replace every Linear (≥ min_out_features) with its weight-only
    quantized form: int8 (default) or int4 (weight_bits=4)."""
    assert weight_bits in (8, 4), weight_bits
    cls = QuantizedLinear if weight_bits == 8 else QuantizedLinearW4
    _swap_sublayers(model, lambda full, sub: cls(sub)
                    if isinstance(sub, Linear)
                    and sub._out_features >= min_out_features else None)
    return model


# ---------------------------------------------------------------------------
# Post-training static quantization (A8W8) — reference paddle slim PTQ
# (fluid/contrib/slim post_training_quantization.py: abs-max activation
# calibration + per-channel weights). On TPU the int8·int8→int32 matmul
# runs on the MXU via dot_general(preferred_element_type=int32).
# ---------------------------------------------------------------------------


class QuantizedLinearA8W8(Layer):
    """Linear with int8 weights AND int8 activations (static scale from
    calibration): y = (q_x · q_w) · (s_x · s_w) + b."""

    def __init__(self, linear: Linear, act_scale):
        super().__init__()
        q, scale = quantize_weight(linear.weight, axis=0)
        self.register_buffer("weight_q", Tensor(q))
        self.register_buffer("weight_scale", Tensor(scale))
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(act_scale, jnp.float32)))
        self.bias = linear.bias
        self._out_features = linear._out_features
        self._in_features = linear._in_features

    def forward(self, x):
        def _f(v, q, sw, sx, *rest):
            qx = jnp.clip(jnp.round(v.astype(jnp.float32) / sx),
                          -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                qx, q, (((qx.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            # sw is [1, out] (keepdims): flatten so a 1-D input keeps
            # Linear's [out] output rank instead of broadcasting to [1, out]
            out = acc.astype(jnp.float32) * (sw.reshape(-1) * sx)
            if rest:
                out = out + rest[0].astype(jnp.float32)
            return out.astype(v.dtype)
        args = (x, self.weight_q, self.weight_scale, self.act_scale) + \
            ((self.bias,) if self.bias is not None else ())
        return apply_op(_f, *args)


class PTQ:
    """Post-training static quantization driver.

        ptq = PTQ(model)                 # hooks every Linear
        for batch in calib: model(batch) # observe activation abs-max
        model = ptq.convert()            # Linears -> int8 A8W8

    Calibration records the running abs-max of each Linear's INPUT; convert
    swaps in QuantizedLinearA8W8 with that static scale and removes hooks.
    """

    def __init__(self, model, min_out_features=16):
        self.model = model
        self.min_out = min_out_features
        self._amax = {}
        self._handles = []
        for name, sub in model.named_sublayers():
            if isinstance(sub, Linear) and \
                    sub._out_features >= min_out_features:
                self._hook(name, sub)

    def _hook(self, name, layer):
        def pre(lyr, inputs):
            x = inputs[0]
            v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
            try:
                amax = float(jnp.max(jnp.abs(v.astype(jnp.float32))))
            except Exception:        # traced (jitted calibration): skip
                return None
            prev = self._amax.get(name, 0.0)
            self._amax[name] = max(prev, amax)
            return None
        self._handles.append(layer.register_forward_pre_hook(pre))

    def convert(self):
        import warnings
        for h in self._handles:
            try:
                h.remove()
            except Exception:
                pass
        if self._handles and not any(v > 0 for v in self._amax.values()):
            warnings.warn(
                "PTQ.convert(): calibration observed no activations (were "
                "the calibration forwards run eagerly, not under jit?); "
                "returning the model UNQUANTIZED", RuntimeWarning)

        def visit(full, sub):
            if isinstance(sub, Linear) and self._amax.get(full, 0) > 0:
                return QuantizedLinearA8W8(
                    sub, max(self._amax[full] / 127.0, 1e-8))
            return None
        _swap_sublayers(self.model, visit)
        return self.model


# ---------------------------------------------------------------------------
# Quantization-aware training — reference
# python/paddle/fluid/contrib/slim/quantization/quantization_pass.py
# (QuantizationTransformPass inserts fake-quant ops into the graph) and
# imperative/qat.py (ImperativeQuantAware). TPU-native: fake-quant
# LAYERS (nn/quant) wrap each Linear so the straight-through estimator
# trains THROUGH the int8 grid inside the normal jit-compiled step —
# no separate graph pass; XLA still sees dense fp matmuls during
# training, and convert() exports the learned scales to real int8.
# ---------------------------------------------------------------------------


class QAT:
    """Quantization-aware training driver.

        qat = QAT()                 # weight_bits=8, activation_bits=8
        qat.quantize(model)         # Linears -> fake-quant wrappers
        ... train as usual ...      # STE learns int8-friendly weights
        qat.convert(model)          # wrappers -> int8 A8W8 execution

    quantize() wraps every Linear (>= min_out_features) in
    nn.quant.QuantizedLinear: the weight is fake-quantized per forward
    (abs-max) and the input through a trained moving-average abs-max
    observer. convert() swaps each wrapper for QuantizedLinearA8W8,
    carrying the OBSERVED activation scale (EMA buffer / 127) and the
    trained weights — so the deployed int8 model computes with the same
    grid the training loop optimized against.
    """

    def __init__(self, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 min_out_features=16,
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max"):
        if weight_bits != 8 or activation_bits != 8:
            # the int8 execution path (QuantizedLinearA8W8) is the only
            # deployment grid; exporting a differently-trained grid would
            # silently break the trained==deployed guarantee
            raise NotImplementedError(
                "QAT export currently targets int8 only: weight_bits and "
                f"activation_bits must be 8, got {weight_bits}/"
                f"{activation_bits}")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate
        self.min_out = min_out_features
        self.weight_quantize_type = weight_quantize_type
        self.activation_quantize_type = activation_quantize_type

    def quantize(self, model):
        from .nn.quant import QuantizedLinear as FakeQuantLinear

        def visit(full, sub):
            if isinstance(sub, FakeQuantLinear):
                return False            # idempotent: never double-wrap
            if isinstance(sub, Linear) and \
                    sub._out_features >= self.min_out:
                return FakeQuantLinear(
                    sub, weight_bits=self.weight_bits,
                    activation_bits=self.activation_bits,
                    moving_rate=self.moving_rate,
                    weight_quantize_type=self.weight_quantize_type,
                    activation_quantize_type=self.activation_quantize_type)
            return None
        _swap_sublayers(model, visit)
        return model

    def convert(self, model):
        import warnings

        from .nn.quant import QuantizedLinear as FakeQuantLinear

        def visit(full, sub):
            if not isinstance(sub, FakeQuantLinear):
                return None
            obs = sub._fake_quant_input
            if int(obs.seen._value) == 0:
                warnings.warn(
                    f"QAT.convert(): {full} never observed an activation "
                    "(no train-mode forward ran); exporting with the "
                    "uninitialized scale 1.0 will saturate inputs |x|>1",
                    RuntimeWarning)
            act_scale = max(float(obs.scale._value) / 127.0, 1e-8)
            return QuantizedLinearA8W8(sub._inner, act_scale)
        _swap_sublayers(model, visit)
        return model
