"""Elastic training — reference python/paddle/distributed/elastic +
fleet/elastic/manager.py (etcd registration, fault watch, restart).

TPU-native rendering: JAX's single-controller collectives can't be patched
mid-flight, so elasticity = whole-group restart + checkpoint-resume.
- ElasticManager: in-job surface — heartbeat file (the etcd-lease
  replacement), SIGTERM-aware should_exit, resume_step from the latest
  orbax checkpoint.
- launch_elastic: the supervisor — runs the worker group via
  distributed.launch, watches exits AND heartbeat staleness, and restarts
  the whole group (bounded by max_restarts); the restarted job resumes
  from the checkpoint.  Multi-host production delegates the restart to
  k8s/systemd; this is the single-host supervisor and the test harness.
"""
import json
import os
import signal
import subprocess
import sys
import time

__all__ = ["ElasticManager", "enable_elastic", "launch_elastic"]


class ElasticManager:
    def __init__(self, checkpoint_dir, heartbeat_path=None, interval_s=30):
        self.checkpoint_dir = checkpoint_dir
        self.heartbeat_path = heartbeat_path or os.path.join(checkpoint_dir, "heartbeat.json")
        self.interval_s = interval_s
        self._last_beat = 0.0
        self._should_exit = False
        self._prev_term = None
        # signal.signal only works on the main thread; chain any existing
        # handler rather than clobbering a launcher's own shutdown hook.
        import threading
        if threading.current_thread() is threading.main_thread():
            self._prev_term = signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, signum, frame):
        self._should_exit = True
        if callable(self._prev_term):
            self._prev_term(signum, frame)

    def heartbeat(self, step, extra=None):
        now = time.time()
        if now - self._last_beat < self.interval_s:
            return
        self._last_beat = now
        os.makedirs(os.path.dirname(self.heartbeat_path), exist_ok=True)
        tmp = self.heartbeat_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": step, "ts": now, **(extra or {})}, f)
        os.replace(tmp, self.heartbeat_path)

    @property
    def should_exit(self):
        return self._should_exit

    def resume_step(self):
        """Latest checkpointed step (or None) to resume from after restart."""
        from ..incubate.checkpoint import CheckpointManager
        return CheckpointManager(self.checkpoint_dir).latest_step()


def enable_elastic(args=None, distribute_mode=None):
    return None


def launch_elastic(training_script, script_args=(), nproc_per_node=1,
                   cpu_devices_per_rank=0, max_restarts=3,
                   heartbeat_path=None, heartbeat_timeout_s=None,
                   log_dir=None, job_id="elastic", env=None, poll_s=0.3,
                   verbose=True):
    """Supervise an elastic training job: launch the worker group, restart
    it on worker death (any nonzero exit, incl. SIGKILL) or heartbeat
    staleness, up to `max_restarts` times.  The training script is
    expected to resume via ElasticManager.resume_step /
    CheckpointManager.restore_latest.

    Returns the number of restarts performed on success; raises
    RuntimeError when the group still fails after max_restarts.
    """
    restarts = 0
    while True:
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--nproc_per_node", str(nproc_per_node),
               "--job_id", f"{job_id}.r{restarts}"]
        if cpu_devices_per_rank:
            cmd += ["--cpu_devices_per_rank", str(cpu_devices_per_rank)]
        if log_dir:
            cmd += ["--log_dir", log_dir]
        cmd += [training_script, *script_args]
        # a dead incarnation's heartbeat must not count for (or against)
        # the new one
        if heartbeat_path and os.path.exists(heartbeat_path):
            try:
                os.remove(heartbeat_path)
            except OSError:
                pass
        started = time.time()
        proc = subprocess.Popen(cmd, env=env)
        reason = None
        while True:
            rc = proc.poll()
            if rc is not None:
                if rc != 0:
                    reason = f"worker group exited rc={rc}"
                break
            if heartbeat_timeout_s and heartbeat_path:
                # clock starts at launch: a worker that hangs BEFORE its
                # first beat is detected too
                last = started
                try:
                    last = max(last, os.path.getmtime(heartbeat_path))
                except OSError:
                    pass  # beat file not written yet (or deleted mid-check)
                age = time.time() - last
                if age > heartbeat_timeout_s:
                    reason = f"heartbeat stale for {age:.0f}s"
                    proc.send_signal(signal.SIGINT)  # launch forwards it
                    try:
                        proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()
                    break
            time.sleep(poll_s)
        if reason is None:
            return restarts
        restarts += 1
        if restarts > max_restarts:
            raise RuntimeError(
                f"elastic job failed after {max_restarts} restarts "
                f"(last: {reason})")
        if verbose:
            print(f"[elastic] {reason}; restart {restarts}/{max_restarts}",
                  file=sys.stderr, flush=True)
