"""Elastic training — reference python/paddle/distributed/elastic +
fleet/elastic/manager.py (etcd registration, fault watch, restart).

TPU-native rendering: JAX's single-controller collectives can't be patched
mid-flight, so elasticity = whole-group restart + checkpoint-resume.
- ElasticManager: in-job surface — heartbeat file (the etcd-lease
  replacement), SIGTERM-aware should_exit, resume_step from the latest
  orbax checkpoint.
- launch_elastic: the supervisor — runs the worker group via
  distributed.launch, watches exits AND heartbeat staleness, and restarts
  the whole group (bounded by max_restarts); the restarted job resumes
  from the checkpoint.  Multi-host production delegates the restart to
  k8s/systemd; this is the single-host supervisor and the test harness.
"""
import json
import os
import signal
import subprocess
import sys
import time

__all__ = ["ElasticManager", "enable_elastic", "launch_elastic",
           "launch_elastic_node", "launch_elastic_multihost"]


class ElasticManager:
    def __init__(self, checkpoint_dir, heartbeat_path=None, interval_s=30):
        self.checkpoint_dir = checkpoint_dir
        # per-node supervisors export their node's beat file path
        self.heartbeat_path = heartbeat_path \
            or os.environ.get("PADDLE_ELASTIC_HEARTBEAT") \
            or os.path.join(checkpoint_dir, "heartbeat.json")
        self.interval_s = interval_s
        self._last_beat = 0.0
        self._should_exit = False
        self._prev_term = None
        # signal.signal only works on the main thread; chain any existing
        # handler rather than clobbering a launcher's own shutdown hook.
        import threading
        if threading.current_thread() is threading.main_thread():
            self._prev_term = signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, signum, frame):
        self._should_exit = True
        if callable(self._prev_term):
            self._prev_term(signum, frame)

    def heartbeat(self, step, extra=None):
        now = time.time()
        if now - self._last_beat < self.interval_s:
            return
        self._last_beat = now
        os.makedirs(os.path.dirname(self.heartbeat_path), exist_ok=True)
        # per-pid temp name: every rank heartbeats the same path, and two
        # ranks sharing one ".tmp" race write-vs-replace into
        # FileNotFoundError (surfaced once CPU gloo collectives let
        # multi-process groups actually train)
        tmp = f"{self.heartbeat_path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"step": step, "ts": now, **(extra or {})}, f)
        os.replace(tmp, self.heartbeat_path)

    @property
    def should_exit(self):
        return self._should_exit

    def resume_step(self):
        """Latest checkpointed step (or None) to resume from after restart."""
        from ..incubate.checkpoint import CheckpointManager
        return CheckpointManager(self.checkpoint_dir).latest_step()


def enable_elastic(args=None, distribute_mode=None):
    return None


def _clear_beat(heartbeat_path):
    """A dead incarnation's heartbeat must not count for the new one."""
    if heartbeat_path and os.path.exists(heartbeat_path):
        try:
            os.remove(heartbeat_path)
        except OSError:
            pass


def _beat_age(heartbeat_path, started):
    """Seconds since the last worker heartbeat (clock starts at launch,
    so a worker that hangs BEFORE its first beat is detected too)."""
    last = started
    try:
        last = max(last, os.path.getmtime(heartbeat_path))
    except OSError:
        pass  # beat file not written yet (or deleted mid-check)
    return time.time() - last


def _stop_group(proc):
    """Stop a distributed.launch group: SIGINT (launch forwards it to
    the workers — it has no SIGTERM handler, so SIGTERM would orphan
    them), escalate to SIGKILL if the group won't die."""
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def launch_elastic(training_script, script_args=(), nproc_per_node=1,
                   cpu_devices_per_rank=0, max_restarts=3,
                   heartbeat_path=None, heartbeat_timeout_s=None,
                   log_dir=None, job_id="elastic", env=None, poll_s=0.3,
                   verbose=True):
    """Supervise an elastic training job: launch the worker group, restart
    it on worker death (any nonzero exit, incl. SIGKILL) or heartbeat
    staleness, up to `max_restarts` times.  The training script is
    expected to resume via ElasticManager.resume_step /
    CheckpointManager.restore_latest.

    Returns the number of restarts performed on success; raises
    RuntimeError when the group still fails after max_restarts.
    """
    restarts = 0
    while True:
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--nproc_per_node", str(nproc_per_node),
               "--job_id", f"{job_id}.r{restarts}"]
        if cpu_devices_per_rank:
            cmd += ["--cpu_devices_per_rank", str(cpu_devices_per_rank)]
        if log_dir:
            cmd += ["--log_dir", log_dir]
        cmd += [training_script, *script_args]
        _clear_beat(heartbeat_path)
        started = time.time()
        run_env = dict(env) if env is not None else dict(os.environ)
        # same fail-fast barrier as launch_elastic_node: THIS loop is the
        # recovery path, so a relaunched group must not wait out jax's
        # 300 s coordinator default when its peer rank died at startup
        run_env.setdefault("PADDLE_TPU_DIST_INIT_TIMEOUT", "60")
        proc = subprocess.Popen(cmd, env=run_env)
        reason = None
        while True:
            rc = proc.poll()
            if rc is not None:
                if rc != 0:
                    reason = f"worker group exited rc={rc}"
                break
            if heartbeat_timeout_s and heartbeat_path:
                age = _beat_age(heartbeat_path, started)
                if age > heartbeat_timeout_s:
                    reason = f"heartbeat stale for {age:.0f}s"
                    _stop_group(proc)
                    break
            time.sleep(poll_s)
        if reason is None:
            return restarts
        restarts += 1
        if restarts > max_restarts:
            raise RuntimeError(
                f"elastic job failed after {max_restarts} restarts "
                f"(last: {reason})")
        if verbose:
            print(f"[elastic] {reason}; restart {restarts}/{max_restarts}",
                  file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Cross-host elastic (reference fleet/elastic/manager.py: per-host agents
# registered in etcd watch for peer failure and restart the job together).
# The shared-filesystem coord_dir stands in for etcd: it carries the job
# EPOCH (bumped by whichever node watches its group die) and the jax
# coordinator address per epoch. JAX collectives cannot heal around a lost
# process, so any node failure means a whole-job restart on every node —
# each node's supervisor notices the epoch moved, kills its local group,
# and relaunches; workers resume from the shared checkpoint.
# ---------------------------------------------------------------------------


def _coordinator_addr(host=None):
    """Routable coordinator address for THIS machine: peers on other
    hosts must be able to reach it (loopback would only ever work in the
    single-machine simulation)."""
    import socket

    from .launch import _free_port
    if host is None:
        try:
            host = socket.gethostbyname(socket.gethostname())
        except OSError:
            host = "127.0.0.1"
    return f"{host}:{_free_port()}"


def _read_epoch(coord_dir):
    try:
        with open(os.path.join(coord_dir, "epoch")) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def _bump_epoch(coord_dir, seen_epoch, reason):
    """Advance the job epoch from the one we observed. Concurrent bumps
    from the same epoch both write seen+1 — idempotent by construction."""
    path = os.path.join(coord_dir, "epoch")
    tmp = f"{path}.tmp.{os.getpid()}.{seen_epoch}"
    with open(tmp, "w") as f:
        f.write(str(seen_epoch + 1))
    os.replace(tmp, path)
    with open(os.path.join(coord_dir, f"reason.e{seen_epoch + 1}"), "w") as f:
        f.write(reason)


def launch_elastic_node(node_rank, nnodes, training_script, script_args=(),
                        coord_dir=None, nproc_per_node=1,
                        cpu_devices_per_rank=0, max_restarts=3,
                        log_dir=None, job_id="elastic", env=None,
                        poll_s=0.2, publish_timeout_s=600,
                        coordinator_host=None, heartbeat_path=None,
                        heartbeat_timeout_s=None):
    """ONE host's supervisor in a cross-host elastic job; run one per
    machine against a shared coord_dir (NFS/etcd-mount). Node 0 publishes
    the jax coordinator address for each epoch; every node launches its
    slice of the job via distributed.launch (--nnodes/--rank/--master),
    watches for local group death OR a stale heartbeat file (bump the
    epoch) and for the epoch moving (a peer died/hung: kill local group,
    relaunch) — the reference manager's etcd-lease fault watch, file-
    rendered. Workers beat via ElasticManager(heartbeat_path=...)."""
    if coord_dir is None:
        raise ValueError("coord_dir (shared across nodes) is required")
    os.makedirs(coord_dir, exist_ok=True)
    restarts = 0
    reason = None
    while True:
        epoch = _read_epoch(coord_dir)
        addr_path = os.path.join(coord_dir, f"master.e{epoch}")
        if node_rank == 0 and not os.path.exists(addr_path):
            tmp = addr_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(_coordinator_addr(coordinator_host))
            os.replace(tmp, addr_path)
        deadline = time.time() + publish_timeout_s
        while not os.path.exists(addr_path):
            if time.time() > deadline:
                raise RuntimeError(
                    f"node {node_rank}: coordinator address for epoch "
                    f"{epoch} never published")
            time.sleep(poll_s)
        with open(addr_path) as f:
            master = f.read().strip()
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--nnodes", str(nnodes), "--rank", str(node_rank),
               "--master", master,
               "--nproc_per_node", str(nproc_per_node),
               "--job_id", f"{job_id}.n{node_rank}.e{epoch}"]
        if cpu_devices_per_rank:
            cmd += ["--cpu_devices_per_rank", str(cpu_devices_per_rank)]
        if log_dir:
            cmd += ["--log_dir", log_dir]
        cmd += [training_script, *script_args]
        _clear_beat(heartbeat_path)
        started = time.time()
        run_env = dict(env) if env is not None else dict(os.environ)
        # an elastic job must fail-fast at the coordinator barrier: the
        # supervisor's restart loop IS the recovery path, so waiting out
        # jax.distributed.initialize's 300 s default when the peer host
        # is mid-teardown only delays it (see init_parallel_env)
        run_env.setdefault("PADDLE_TPU_DIST_INIT_TIMEOUT", "60")
        if heartbeat_path:
            # workers find THIS node's beat file via the env
            # (ElasticManager defaults its path from it)
            run_env["PADDLE_ELASTIC_HEARTBEAT"] = heartbeat_path
        proc = subprocess.Popen(cmd, env=run_env)
        while True:
            rc = proc.poll()
            cur = _read_epoch(coord_dir)
            if cur != epoch:
                # a peer's group died or hung: whole-job restart
                _stop_group(proc)
                reason = f"peer bumped epoch {epoch}->{cur}"
                break
            if rc is not None:
                if rc == 0:
                    return restarts
                reason = f"node {node_rank} group exited rc={rc}"
                _bump_epoch(coord_dir, epoch, reason)
                break
            if heartbeat_timeout_s and heartbeat_path:
                age = _beat_age(heartbeat_path, started)
                if age > heartbeat_timeout_s:
                    # a WEDGED local group never exits: detect via the
                    # workers' heartbeat file and restart the whole job
                    _stop_group(proc)
                    reason = (f"node {node_rank} heartbeat stale "
                              f"for {age:.0f}s")
                    _bump_epoch(coord_dir, epoch, reason)
                    break
            time.sleep(poll_s)
        restarts += 1
        if restarts > max_restarts:
            raise RuntimeError(
                f"elastic node {node_rank} failed after {max_restarts} "
                f"restarts (last: {reason})")


def launch_elastic_multihost(training_script, script_args=(), nnodes=2,
                             **node_kw):
    """In-process harness over launch_elastic_node: one supervisor THREAD
    per simulated host (production runs one launch_elastic_node per
    machine, where each machine naturally has its own heartbeat file).
    A shared heartbeat_path is made per-node here (suffix .n{rank}) —
    one live node's beats must not mask a wedged peer. Returns the max
    restart count across nodes."""
    import threading
    results = {}
    beat = node_kw.pop("heartbeat_path", None)
    # same-machine simulation: loopback is the one address guaranteed to
    # be locally bindable AND reachable (a container's hostname may
    # resolve elsewhere); real per-machine deployments keep the
    # routable-hostname default of launch_elastic_node
    node_kw.setdefault("coordinator_host", "127.0.0.1")

    def run(rank):
        kw = dict(node_kw)
        if beat:
            kw["heartbeat_path"] = f"{beat}.n{rank}"
        try:
            results[rank] = launch_elastic_node(
                rank, nnodes, training_script, script_args, **kw)
        except BaseException as e:   # surface to the caller's thread
            results[rank] = e

    threads = [threading.Thread(target=run, args=(r,), daemon=True)
               for r in range(nnodes)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for v in results.values():
        if isinstance(v, BaseException):
            raise v
    return max(results.values())
