"""Elastic training hooks — reference python/paddle/distributed/elastic.

JAX's single-controller model restarts whole processes rather than patching
collectives mid-flight; elasticity = checkpoint-resume. This module provides
the watch/trigger surface: a heartbeat file + resume helper that pairs with
incubate.checkpoint.CheckpointManager.
"""
import json
import os
import signal
import time

__all__ = ["ElasticManager", "enable_elastic", "launch_elastic"]


class ElasticManager:
    def __init__(self, checkpoint_dir, heartbeat_path=None, interval_s=30):
        self.checkpoint_dir = checkpoint_dir
        self.heartbeat_path = heartbeat_path or os.path.join(checkpoint_dir, "heartbeat.json")
        self.interval_s = interval_s
        self._last_beat = 0.0
        self._should_exit = False
        self._prev_term = None
        # signal.signal only works on the main thread; chain any existing
        # handler rather than clobbering a launcher's own shutdown hook.
        import threading
        if threading.current_thread() is threading.main_thread():
            self._prev_term = signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, signum, frame):
        self._should_exit = True
        if callable(self._prev_term):
            self._prev_term(signum, frame)

    def heartbeat(self, step, extra=None):
        now = time.time()
        if now - self._last_beat < self.interval_s:
            return
        self._last_beat = now
        os.makedirs(os.path.dirname(self.heartbeat_path), exist_ok=True)
        tmp = self.heartbeat_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": step, "ts": now, **(extra or {})}, f)
        os.replace(tmp, self.heartbeat_path)

    @property
    def should_exit(self):
        return self._should_exit

    def resume_step(self):
        """Latest checkpointed step (or None) to resume from after restart."""
        from ..incubate.checkpoint import CheckpointManager
        return CheckpointManager(self.checkpoint_dir).latest_step()


def enable_elastic(args=None, distribute_mode=None):
    return None


def launch_elastic(*a, **k):
    raise NotImplementedError(
        "run under an external supervisor (k8s/systemd restart) + "
        "ElasticManager heartbeat/resume")
