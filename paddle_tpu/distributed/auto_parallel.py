"""Auto-parallel API — reference python/paddle/distributed/auto_parallel
(shard_tensor / shard_op / ProcessMesh + cost-model planner).

On TPU the planner IS the compiler: users annotate intent (shard_tensor →
sharding constraint; engine = jit with GSPMD), XLA's SPMD partitioner does
placement + collective insertion. ProcessMesh maps onto jax.sharding.Mesh.
"""
import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..framework.core import Tensor, apply_op
from .mesh import get_mesh, set_mesh

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "Engine"]


class ProcessMesh:
    def __init__(self, mesh, dim_names=None, process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
        devices = np.asarray(jax.devices())[arr.reshape(-1)].reshape(arr.shape)
        self._mesh = Mesh(devices, tuple(self.dim_names))
        set_mesh(self._mesh)

    @property
    def mesh(self):
        return self._mesh


def shard_tensor(x, process_mesh=None, shard_spec=None, **kwargs):
    """Annotate (and physically place) a tensor's sharding."""
    mesh = process_mesh.mesh if isinstance(process_mesh, ProcessMesh) else get_mesh()
    spec = PartitionSpec(*(shard_spec or []))
    sh = NamedSharding(mesh, spec)
    if isinstance(x, Tensor):
        if isinstance(x._value, jax.Array):
            x._value = jax.device_put(x._value, sh)
            return x
        return apply_op(lambda v: jax.lax.with_sharding_constraint(v, sh), x)
    return jax.device_put(x, sh)


def shard_op(op_fn, process_mesh=None, in_shard_specs=None, out_shard_specs=None):
    """Wrap an op so its inputs/outputs carry sharding constraints."""
    mesh = process_mesh.mesh if isinstance(process_mesh, ProcessMesh) else get_mesh()

    def wrapped(*args, **kwargs):
        if in_shard_specs is not None:
            args = tuple(
                shard_tensor(a, process_mesh, spec) if spec is not None else a
                for a, spec in zip(args, in_shard_specs))
        out = op_fn(*args, **kwargs)
        if out_shard_specs is not None:
            specs = out_shard_specs if isinstance(out, (list, tuple)) else [out_shard_specs]
            outs = out if isinstance(out, (list, tuple)) else [out]
            outs = [shard_tensor(o, process_mesh, s) if s is not None else o
                    for o, s in zip(outs, specs)]
            out = type(out)(outs) if isinstance(out, (list, tuple)) else outs[0]
        return out
    return wrapped


class Engine:
    """auto_parallel.Engine parity: fit/evaluate over the auto-sharded step
    (delegates to distributed.trainer.Trainer)."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None, strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self._trainer = None

    def _ensure(self):
        if self._trainer is None:
            from .trainer import Trainer

            loss_layer = self.loss

            def loss_fn(m, batch):
                out = m(batch["x"])
                return loss_layer(out, batch["y"])
            self._trainer = Trainer(self.model, self.optimizer, loss_fn)
        return self._trainer

    def fit(self, train_data, epochs=1, batch_size=1, **kwargs):
        from ..io import DataLoader
        loader = train_data if isinstance(train_data, DataLoader) else DataLoader(
            train_data, batch_size=batch_size)
        trainer = self._ensure()
        history = []
        for _ in range(epochs):
            for batch in loader:
                x, y = batch if isinstance(batch, (list, tuple)) else (batch, None)
                history.append(float(trainer.step({"x": x, "y": y})))
        return history
