"""Auto-parallel API — reference python/paddle/distributed/auto_parallel
(interface.py shard_tensor/shard_op, process_mesh.py, planner_v2.py,
engine.py).

On TPU the partitioner IS the compiler: users annotate intent and XLA's
SPMD pass does placement + collective insertion. The pieces:

- ProcessMesh            → jax.sharding.Mesh wrapper (named axes)
- shard_tensor/shard_op  → persistent partition_spec annotations +
                           physical placement / sharding constraints
- Planner                → derives the Mesh from the annotations' axis
                           names + DistributedStrategy degrees (the
                           reference's search-based planner becomes a
                           deterministic degree solver; XLA handles the
                           per-op placement search)
- Engine                 → prepare/fit/evaluate/predict over the
                           GSPMD-compiled Trainer step
"""
import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..framework.core import Tensor, apply_op
from .mesh import build_mesh, get_mesh, set_mesh

from .planner_cost import (  # noqa: F401
    ClusterSpec,
    ModelStats,
    gpt_stats,
    search_mesh,
)

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "Planner", "Engine",
           "ClusterSpec", "ModelStats", "gpt_stats", "search_mesh"]


class ProcessMesh:
    def __init__(self, mesh, dim_names=None, process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
        devices = np.asarray(jax.devices())[arr.reshape(-1)].reshape(arr.shape)
        self._mesh = Mesh(devices, tuple(self.dim_names))
        set_mesh(self._mesh)

    @property
    def mesh(self):
        return self._mesh


def shard_tensor(x, process_mesh=None, shard_spec=None, **kwargs):
    """Annotate a tensor's sharding and place it.

    The annotation is PERSISTENT: it is stored as `partition_spec` on the
    tensor (the same attribute meta_parallel layers use), so the Engine /
    Trainer re-applies it when compiling the training step — reference
    dist_tensor dims_mapping semantics."""
    mesh = process_mesh.mesh if isinstance(process_mesh, ProcessMesh) else get_mesh()
    spec = tuple(shard_spec or [])
    sh = NamedSharding(mesh, PartitionSpec(*spec))
    if isinstance(x, Tensor):
        x.partition_spec = spec
        if isinstance(x._value, jax.Array) and \
                not isinstance(x._value, jax.core.Tracer):
            x._value = jax.device_put(x._value, sh)
            return x
        # symbolic/traced values get a GSPMD constraint instead of a placement
        return apply_op(lambda v: jax.lax.with_sharding_constraint(v, sh), x)
    return jax.device_put(x, sh)


def shard_op(op_fn, process_mesh=None, in_shard_specs=None, out_shard_specs=None):
    """Wrap an op so its inputs/outputs carry sharding constraints."""

    def wrapped(*args, **kwargs):
        if in_shard_specs is not None:
            args = tuple(
                shard_tensor(a, process_mesh, spec) if spec is not None else a
                for a, spec in zip(args, in_shard_specs))
        out = op_fn(*args, **kwargs)
        if out_shard_specs is not None:
            specs = out_shard_specs if isinstance(out, (list, tuple)) else [out_shard_specs]
            outs = out if isinstance(out, (list, tuple)) else [out]
            outs = [shard_tensor(o, process_mesh, s) if s is not None else o
                    for o, s in zip(outs, specs)]
            out = type(out)(outs) if isinstance(out, (list, tuple)) else outs[0]
        return out
    return wrapped


class Planner:
    """Derives the device mesh from the model's sharding annotations
    (reference planner_v2.Planner; the op-level placement search is XLA's).

    Axis sizing: axes named in annotations get their degree from the
    DistributedStrategy (mp_degree→tp, sharding_degree→fsdp, …) when
    given; otherwise an annotated axis defaults to the largest power-of-2
    that divides the remaining device count; whatever remains goes to dp.
    """

    KNOWN_AXES = ("dp", "fsdp", "pp", "tp", "sp", "ep")

    def __init__(self, strategy=None):
        self.strategy = strategy

    def search(self, stats, cluster=None, **kw):
        """Cost-model mesh search (reference planner/parallel_tuner):
        given ModelStats (e.g. gpt_stats(...)) and a ClusterSpec, rank
        dp/fsdp/tp/pp factorizations by roofline-estimated step time.
        See planner_cost.search_mesh."""
        return search_mesh(stats, cluster, **kw)

    def collect_axes(self, model):
        axes = []
        for _, p in model.named_parameters():
            for entry in (getattr(p, "partition_spec", None) or ()):
                for a in (entry if isinstance(entry, (tuple, list)) else [entry]):
                    if a is not None and a not in axes:
                        axes.append(a)
        return axes

    def plan(self, model, n_devices=None):
        n = n_devices or len(jax.devices())
        degrees = {}
        if self.strategy is not None and hasattr(self.strategy, "_degrees"):
            degrees = {k: v for k, v in self.strategy._degrees().items() if v > 1}
        axes = self.collect_axes(model)
        sizes = {a: 1 for a in self.KNOWN_AXES}
        remaining = n
        # explicit strategy degrees are binding and claim devices FIRST
        for a, d in degrees.items():
            if a not in sizes:
                raise ValueError(f"strategy names unknown axis {a!r}")
            if remaining % d != 0:
                raise ValueError(f"axis {a!r} degree {d} does not divide "
                                 f"remaining {remaining} devices")
            sizes[a] = d
            remaining //= d
        # annotated axes without an explicit degree: largest 2^k that fits
        for a in axes:
            if a not in sizes:
                raise ValueError(
                    f"annotation uses axis {a!r}; Planner understands "
                    f"{self.KNOWN_AXES} — pass a ProcessMesh for custom axes")
            if a in degrees:
                continue
            d = 1
            while remaining % (d * 2) == 0 and d * 2 <= remaining:
                d *= 2
            sizes[a] = d
            remaining //= d
        sizes["dp"] *= remaining
        return build_mesh(devices=jax.devices()[:n], **sizes)


class Engine:
    """reference auto_parallel/engine.py:Engine — prepare/fit/evaluate/
    predict over ONE GSPMD-compiled step. The annotated partition_specs
    land in the compiled HLO as sharding ops; XLA inserts the collectives."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics is not None else [])
        self.strategy = strategy
        self._trainer = None
        self._mesh = None
        self._history = {"loss": []}

    # -- planning ---------------------------------------------------------
    def prepare(self, inputs_spec=None, labels_spec=None, mode="train",
                n_devices=None):
        if self._mesh is None:
            self._mesh = Planner(self.strategy).plan(self.model, n_devices)
        if self._trainer is None and mode != "predict" and \
                self.optimizer is not None:
            from .trainer import Trainer

            loss_layer = self.loss

            def loss_fn(m, batch):
                out = m(batch["x"])
                if loss_layer is None:
                    return out
                return loss_layer(out, batch["y"])

            self._trainer = Trainer(self.model, self.optimizer, loss_fn,
                                    mesh=self._mesh)
        return self

    def compiled_hlo(self, batch):
        """Lowered+compiled HLO text of the train step for `batch` —
        lets callers (and tests) inspect the GSPMD shardings."""
        self.prepare()
        t = self._trainer
        b = {k: np.asarray(v) for k, v in batch.items()}
        return t.lower_step(b, self.optimizer.get_lr()).as_text()

    # -- loops ------------------------------------------------------------
    def _loader(self, data, batch_size):
        from ..io import DataLoader
        return data if isinstance(data, DataLoader) else DataLoader(
            data, batch_size=batch_size)

    def fit(self, train_data, epochs=1, batch_size=1, steps_per_epoch=None,
            log_freq=0, **kwargs):
        self.prepare()
        if self._trainer is None:
            raise ValueError("Engine.fit needs an optimizer")
        self._history = {"loss": []}    # fresh per fit() call
        loader = self._loader(train_data, batch_size)
        for ep in range(epochs):
            for i, batch in enumerate(loader):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                x, y = batch if isinstance(batch, (list, tuple)) else (batch, None)
                loss = float(self._trainer.step({"x": x, "y": y}))
                self._history["loss"].append(loss)
                if log_freq and i % log_freq == 0:
                    print(f"[auto_parallel] epoch {ep} step {i} loss {loss:.4f}")
        return self._history

    def evaluate(self, valid_data, batch_size=1, steps=None, **kwargs):
        self.prepare()
        if self._trainer is not None:
            self._trainer.sync_to_model()
        self.model.eval()
        losses, n = 0.0, 0
        for m in self.metrics:
            if hasattr(m, "reset"):
                m.reset()
        for i, batch in enumerate(self._loader(valid_data, batch_size)):
            if steps is not None and i >= steps:
                break
            x, y = batch if isinstance(batch, (list, tuple)) else (batch, None)
            out = self.model(x)
            if self.loss is not None:
                losses += float(self.loss(out, y))
                n += 1
            for m in self.metrics:
                m.update(m.compute(out, y)) if hasattr(m, "compute") else None
        self.model.train()
        res = {"loss": losses / max(n, 1)}
        for m in self.metrics:
            if hasattr(m, "accumulate"):
                res[getattr(m, "name", lambda: m.__class__.__name__)()
                    if callable(getattr(m, "name", None)) else "metric"] = m.accumulate()
        return res

    def predict(self, test_data, batch_size=1, steps=None, **kwargs):
        self.prepare(mode="predict")
        if self._trainer is not None:
            self._trainer.sync_to_model()
        self.model.eval()
        outs = []
        for i, batch in enumerate(self._loader(test_data, batch_size)):
            if steps is not None and i >= steps:
                break
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(self.model(x))
        self.model.train()
        return outs

    def save(self, path, training=True):
        from ..framework.io import save
        if self._trainer is not None:
            self._trainer.sync_to_model()
        save(self.model.state_dict(), path + ".pdparams")
        if training and self.optimizer is not None and \
                hasattr(self.optimizer, "state_dict"):
            save(self.optimizer.state_dict(), path + ".pdopt")

    def load(self, path):
        from ..framework.io import load
        self.model.set_state_dict(load(path + ".pdparams"))
