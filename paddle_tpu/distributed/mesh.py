"""Global device-mesh state.

Replaces the reference's process-group world (paddle/fluid/distributed +
ProcessGroupNCCL) with a jax.sharding.Mesh. Axis vocabulary:

  dp    — data parallel (batch dim)
  fsdp  — sharded-parameter data parallel (ZeRO-3 ≈ fleet sharding stage 3)
  pp    — pipeline stages
  tp    — tensor (model) parallel, reference fleet "mp"
  sp    — sequence/context parallel (ring attention)
  ep    — expert parallel (MoE)

On TPU pods, axes laid out in this order ride ICI for the inner axes; DCN
only ever sees 'dp'/'pp' traffic — same layout discipline the scaling
playbook prescribes.
"""
import contextlib

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["get_mesh", "set_mesh", "build_mesh", "mesh_axis_size", "PartitionSpec",
           "NamedSharding", "Mesh", "named_sharding", "current_axis_context",
           "in_shard_map", "axis_scope", "compat_shard_map"]


def compat_shard_map(f, mesh, in_specs, out_specs, axis_names=None,
                     check=True):
    """`jax.shard_map` across jax versions (the localsgd.py shim made
    reusable): top-level export on jax >= 0.6, experimental module on
    0.4.x; the replication-check kwarg picked by SIGNATURE (check_vma vs
    check_rep — renamed independently of the import move). `axis_names`
    (the >= 0.6 manual-axes subset) maps onto 0.4.x's complementary
    `auto` set, where replication checking must be off (0.4.x rejects
    check_rep with auto axes)."""
    import inspect
    try:
        from jax import shard_map as sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
    params = inspect.signature(sm).parameters
    kw = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if "check_vma" in params:
        kw["check_vma"] = check
    elif "check_rep" in params:
        kw["check_rep"] = check
    if axis_names:
        if "axis_names" in params:
            kw["axis_names"] = set(axis_names)
        else:
            # 0.4.x `auto` (the complement set) raises NotImplementedError
            # on these program shapes; leaving the other axes MANUAL with
            # replicated specs is numerically equivalent as long as the
            # body only issues collectives over `axis_names` — true for
            # every caller here (pipeline schedules over 'pp'). check_rep
            # can't see that and must be off.
            if "check_rep" in kw:
                kw["check_rep"] = False
    return sm(f, **kw)

_state = {"mesh": None, "axis_context": ()}


def build_mesh(dp=1, fsdp=1, pp=1, tp=1, sp=1, ep=1, devices=None):
    """Create a Mesh over `devices` with only the >1 axes materialized (axes
    of size 1 are kept too so PartitionSpecs stay valid)."""
    devices = devices if devices is not None else jax.devices()
    sizes = {"dp": dp, "fsdp": fsdp, "pp": pp, "tp": tp, "sp": sp, "ep": ep}
    total = int(np.prod(list(sizes.values())))
    if total != len(devices):
        if dp == 1 and len(devices) % total == 0:
            # dp left at its default of 1: absorb the remaining devices
            sizes["dp"] = len(devices) // total
        else:
            raise ValueError(
                f"mesh axes {sizes} multiply to {total} but {len(devices)} "
                "devices were given; make the product match (dp=1 may be "
                "left unset to absorb the remainder)")
    arr = np.asarray(devices).reshape([sizes[a] for a in ("dp", "fsdp", "pp", "tp", "sp", "ep")])
    mesh = Mesh(arr, ("dp", "fsdp", "pp", "tp", "sp", "ep"))
    set_mesh(mesh)
    return mesh


def set_mesh(mesh):
    _state["mesh"] = mesh


def get_mesh(create_default=True):
    if _state["mesh"] is None and create_default:
        build_mesh(dp=len(jax.devices()))
    return _state["mesh"]


def mesh_axis_size(axis):
    mesh = get_mesh()
    return mesh.shape.get(axis, 1)


def mesh_axis_sizes():
    """{axis: size} of the current global mesh (empty dict when none is
    built). The Graph Doctor's collective analyzer uses this to
    attribute each lowered collective's replica-group size to a mesh
    axis (per-axis payload accounting, T3-style)."""
    mesh = get_mesh(create_default=False)
    if mesh is None:
        return {}
    return dict(mesh.shape)


def named_sharding(*spec):
    return NamedSharding(get_mesh(), PartitionSpec(*spec))


@contextlib.contextmanager
def axis_scope(*axes):
    """Marks that we're inside a shard_map over `axes` (collectives use this
    to decide between lax collectives and no-ops)."""
    prev = _state["axis_context"]
    _state["axis_context"] = prev + tuple(axes)
    try:
        yield
    finally:
        _state["axis_context"] = prev


def current_axis_context():
    return _state["axis_context"]


def in_shard_map():
    return bool(_state["axis_context"])
