"""Global device-mesh state.

Replaces the reference's process-group world (paddle/fluid/distributed +
ProcessGroupNCCL) with a jax.sharding.Mesh. Axis vocabulary:

  dp    — data parallel (batch dim)
  fsdp  — sharded-parameter data parallel (ZeRO-3 ≈ fleet sharding stage 3)
  pp    — pipeline stages
  tp    — tensor (model) parallel, reference fleet "mp"
  sp    — sequence/context parallel (ring attention)
  ep    — expert parallel (MoE)

On TPU pods, axes laid out in this order ride ICI for the inner axes; DCN
only ever sees 'dp'/'pp' traffic — same layout discipline the scaling
playbook prescribes.
"""
import contextlib

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["get_mesh", "set_mesh", "build_mesh", "mesh_axis_size", "PartitionSpec",
           "NamedSharding", "Mesh", "named_sharding", "current_axis_context",
           "in_shard_map", "axis_scope"]

_state = {"mesh": None, "axis_context": ()}


def build_mesh(dp=1, fsdp=1, pp=1, tp=1, sp=1, ep=1, devices=None):
    """Create a Mesh over `devices` with only the >1 axes materialized (axes
    of size 1 are kept too so PartitionSpecs stay valid)."""
    devices = devices if devices is not None else jax.devices()
    sizes = {"dp": dp, "fsdp": fsdp, "pp": pp, "tp": tp, "sp": sp, "ep": ep}
    total = int(np.prod(list(sizes.values())))
    if total != len(devices):
        if dp == 1 and len(devices) % total == 0:
            # dp left at its default of 1: absorb the remaining devices
            sizes["dp"] = len(devices) // total
        else:
            raise ValueError(
                f"mesh axes {sizes} multiply to {total} but {len(devices)} "
                "devices were given; make the product match (dp=1 may be "
                "left unset to absorb the remainder)")
    arr = np.asarray(devices).reshape([sizes[a] for a in ("dp", "fsdp", "pp", "tp", "sp", "ep")])
    mesh = Mesh(arr, ("dp", "fsdp", "pp", "tp", "sp", "ep"))
    set_mesh(mesh)
    return mesh


def set_mesh(mesh):
    _state["mesh"] = mesh


def get_mesh(create_default=True):
    if _state["mesh"] is None and create_default:
        build_mesh(dp=len(jax.devices()))
    return _state["mesh"]


def mesh_axis_size(axis):
    mesh = get_mesh()
    return mesh.shape.get(axis, 1)


def mesh_axis_sizes():
    """{axis: size} of the current global mesh (empty dict when none is
    built). The Graph Doctor's collective analyzer uses this to
    attribute each lowered collective's replica-group size to a mesh
    axis (per-axis payload accounting, T3-style)."""
    mesh = get_mesh(create_default=False)
    if mesh is None:
        return {}
    return dict(mesh.shape)


def named_sharding(*spec):
    return NamedSharding(get_mesh(), PartitionSpec(*spec))


@contextlib.contextmanager
def axis_scope(*axes):
    """Marks that we're inside a shard_map over `axes` (collectives use this
    to decide between lax collectives and no-ops)."""
    prev = _state["axis_context"]
    _state["axis_context"] = prev + tuple(axes)
    try:
        yield
    finally:
        _state["axis_context"] = prev


def current_axis_context():
    return _state["axis_context"]


def in_shard_map():
    return bool(_state["axis_context"])
