"""paddle_tpu.distributed — reference python/paddle/distributed/__init__.py,
rebuilt on jax.sharding meshes + XLA collectives (no NCCL/gloo)."""
from . import fleet  # noqa: F401
from .collective import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_reduce,
    alltoall,
    barrier,
    broadcast,
    get_group,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    wait,
)
from .mesh import (  # noqa: F401
    Mesh,
    NamedSharding,
    PartitionSpec,
    axis_scope,
    build_mesh,
    get_mesh,
    in_shard_map,
    mesh_axis_size,
    named_sharding,
    set_mesh,
)
from .parallel import (  # noqa: F401
    DataParallel,
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
)
from .sharding_utils import constraint, plan_shardings, shard_params  # noqa: F401

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "DataParallel",
    "ParallelEnv", "all_reduce", "all_gather", "reduce", "broadcast",
    "scatter", "reduce_scatter", "alltoall", "send", "recv", "barrier",
    "ReduceOp", "Group", "new_group", "get_group", "wait", "fleet",
    "get_mesh", "build_mesh", "Mesh", "PartitionSpec", "NamedSharding",
    "plan_shardings", "shard_params", "constraint", "spawn", "launch",
]


def get_data_parallel_axis():
    ctx = __import__("paddle_tpu.distributed.mesh", fromlist=["current_axis_context"])
    axes = ctx.current_axis_context()
    return "dp" if "dp" in axes else None


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Single-controller JAX drives all local devices from one process; spawn
    therefore just runs func once (multi-host uses one process per host,
    launched externally with jax.distributed env vars)."""
    func(*args)


def launch():
    raise NotImplementedError(
        "use standard multi-host launching (one process per host with "
        "JAX_COORDINATOR/process env) — see docs/distributed.md")
