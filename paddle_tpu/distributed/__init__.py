"""paddle_tpu.distributed — reference python/paddle/distributed/__init__.py,
rebuilt on jax.sharding meshes + XLA collectives (no NCCL/gloo)."""
from . import fleet  # noqa: F401
from . import launch  # noqa: F401  (the launcher module — python -m ...launch)
from .collective import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_reduce,
    alltoall,
    barrier,
    broadcast,
    get_group,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    wait,
)
from .mesh import (  # noqa: F401
    Mesh,
    NamedSharding,
    PartitionSpec,
    axis_scope,
    build_mesh,
    get_mesh,
    in_shard_map,
    mesh_axis_size,
    mesh_axis_sizes,
    named_sharding,
    set_mesh,
)
from .parallel import (  # noqa: F401
    DataParallel,
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
)
from .auto_parallel import ProcessMesh, shard_op, shard_tensor  # noqa: F401
from .planner_cost import (  # noqa: F401
    ClusterSpec,
    ModelStats,
    gpt_stats,
    search_mesh,
)
from .compression import DGCCompressor, bf16_compress  # noqa: F401
from .localsgd import LocalSGDTrainer  # noqa: F401
from .sharded_embedding import ShardedEmbedding  # noqa: F401
from .sharding_utils import constraint, plan_shardings, shard_params  # noqa: F401
from .trainer import LossBuffer, Trainer, shard_batch  # noqa: F401
from . import sharding  # noqa: F401  (group_sharded_parallel API)
from . import utils  # noqa: F401  (Cluster/Pod/Trainer launch plumbing)

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "DataParallel",
    "ParallelEnv", "all_reduce", "all_gather", "reduce", "broadcast",
    "scatter", "reduce_scatter", "alltoall", "send", "recv", "barrier",
    "ReduceOp", "Group", "new_group", "get_group", "wait", "fleet",
    "get_mesh", "build_mesh", "Mesh", "PartitionSpec", "NamedSharding",
    "plan_shardings", "shard_params", "constraint", "spawn", "launch",
    "Trainer", "LocalSGDTrainer", "LossBuffer", "shard_batch",
]


def get_data_parallel_axis():
    ctx = __import__("paddle_tpu.distributed.mesh", fromlist=["current_axis_context"])
    axes = ctx.current_axis_context()
    return "dp" if "dp" in axes else None


def _spawn_worker(func, args):
    from .parallel import init_parallel_env
    init_parallel_env()
    func(*args)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Start `nprocs` local worker processes, each joining one
    jax.distributed job, and run func in every one (reference
    python/paddle/distributed/spawn.py). With nprocs<=1 — the normal TPU
    situation, where ONE process drives all local chips — func simply runs
    inline.

    options: cpu_devices_per_rank=N gives each worker N virtual CPU
    devices (emulation/testing); master="ip:port" pins the coordinator."""
    if nprocs is None or nprocs <= 1:
        from .parallel import init_parallel_env
        init_parallel_env()
        func(*args)
        return []
    import multiprocessing as mp
    import os

    from .launch import _free_port, force_cpu_devices

    master = options.get("master") or f"127.0.0.1:{_free_port()}"
    cpu_devices = int(options.get("cpu_devices_per_rank", 0))
    ctx = mp.get_context("spawn")
    procs = []
    # the child inherits os.environ at start(); plugin/backends load at
    # interpreter start (sitecustomize), so env must be staged HERE
    saved = dict(os.environ)
    try:
        os.environ["PADDLE_MASTER"] = master
        os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
        if cpu_devices:
            force_cpu_devices(os.environ, cpu_devices)
        for rank in range(nprocs):
            os.environ["PADDLE_TRAINER_ID"] = str(rank)
            p = ctx.Process(target=_spawn_worker, args=(func, args),
                            daemon=daemon)
            p.start()
            procs.append(p)
    finally:
        os.environ.clear()
        os.environ.update(saved)
    if join:
        import time

        # fail fast: a dead worker leaves peers blocked in collectives, so
        # terminate the group as soon as any exitcode is nonzero
        first_bad = None
        while any(p.is_alive() for p in procs):
            for p in procs:
                if p.exitcode not in (None, 0) and first_bad is None:
                    first_bad = p.exitcode
                    for q in procs:
                        if q.is_alive():
                            q.terminate()
            time.sleep(0.2)
        for p in procs:
            p.join()
        if first_bad is None:
            bad = [p.exitcode for p in procs if p.exitcode]
            first_bad = bad[0] if bad else None
        if first_bad is not None:
            raise RuntimeError(f"spawn worker failed with exit code {first_bad}")
    return procs


# NOTE: `paddle_tpu.distributed.launch` is the launcher MODULE (run it with
# `python -m paddle_tpu.distributed.launch`), mirroring reference
# python/paddle/distributed/launch/. No function of the same name is bound
# here — it would be shadowed by the submodule import anyway.


class ParallelMode:
    """Reference python/paddle/distributed/parallel.py:ParallelMode."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Model-parallel building block — reference
    python/paddle/distributed/collective.py:1547:split. Builds the matching
    meta_parallel layer (GSPMD shards the weight over the 'tp'/'mp' mesh axis;
    no manual partition bookkeeping needed) and applies it."""
    from .fleet.meta_parallel import (ColumnParallelLinear, RowParallelLinear,
                                      VocabParallelEmbedding)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1], weight_attr=weight_attr)
        return layer(x)
    if operation != "linear":
        raise ValueError("operation must be 'linear' or 'embedding'")
    if axis == 0:
        layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                  has_bias=bias_attr is not False,
                                  input_is_parallel=False)
    else:
        layer = ColumnParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                     has_bias=bias_attr is not False,
                                     gather_output=gather_out)
    return layer(x)


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU rendezvous — jax.distributed handles multi-host setup; accepted
    for parity (reference uses gloo for CPU-only collectives)."""
    return None


def gloo_barrier():
    return None


def gloo_release():
    return None


class _EntryBase:
    """Sparse-table entry configs (reference distributed/entry_attr.py) —
    parameter-server artifacts, kept as config carriers."""

    def __init__(self, *args):
        self._args = args


class CountFilterEntry(_EntryBase):
    def __init__(self, count_filter=0):
        super().__init__(count_filter)


class ShowClickEntry(_EntryBase):
    def __init__(self, show_name="", click_name=""):
        super().__init__(show_name, click_name)


class ProbabilityEntry(_EntryBase):
    def __init__(self, probability=1.0):
        super().__init__(probability)


class InMemoryDataset:
    """Reference distributed/fleet/dataset:InMemoryDataset — host-side sample
    store feeding the data loader (parameter-server era API; file-list based)."""

    def __init__(self):
        self._files = []
        self._records = []
        self._batch_size = 1
        self._parse_fn = None

    def init(self, batch_size=1, thread_num=1, use_var=None, pipe_command=None,
             input_type=0, fs_name="", fs_ugi="", download_cmd="cat", **kwargs):
        self._batch_size = batch_size

    def set_filelist(self, filelist):
        self._files = list(filelist)

    def load_into_memory(self):
        self._records = []
        for fn in self._files:
            with open(fn) as f:
                for line in f:
                    line = line.rstrip("\n")
                    self._records.append(
                        self._parse_fn(line) if self._parse_fn else line)

    def local_shuffle(self):
        import random
        random.shuffle(self._records)

    def global_shuffle(self, fleet=None, thread_num=12):
        self.local_shuffle()

    def get_memory_data_size(self, fleet=None):
        return len(self._records)

    def release_memory(self):
        self._records = []

    def __iter__(self):
        return iter(self._records)


class QueueDataset(InMemoryDataset):
    """Streaming variant: iterates files lazily instead of loading to memory."""

    def __iter__(self):
        for fn in self._files:
            with open(fn) as f:
                for line in f:
                    yield line.rstrip("\n")


__all__ += ["ParallelMode", "split", "gloo_init_parallel_env", "gloo_barrier",
            "gloo_release", "CountFilterEntry", "ShowClickEntry",
            "ProbabilityEntry", "InMemoryDataset", "QueueDataset"]

from . import metric  # noqa: F401,E402  (PS metric deflection)
from . import passes  # noqa: F401,E402  (pass framework + deflections)
from . import ps  # noqa: F401,E402  (PS runtime deflection)
