"""Pipeline parallelism: SPMD schedules over the 'pp' mesh axis.

Replaces reference fleet pipeline_parallel.py (P2P send/recv between rank
processes, GPipe/1F1B schedulers in python —
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:82,171)
with the TPU-native formulation: ONE compiled program in which every stage
runs the same code, activations hop stages via ppermute on ICI, and the
microbatch schedule is a lax.scan over ticks. shard_map is manual ONLY over
'pp' (axis_names={'pp'}) so tensor/data parallel dims inside each stage stay
GSPMD-managed — pp×tp×dp×sp compose.

Three schedules:

- "gpipe": forward scan, backward by XLA autodiff of the scan. Simple, but
  the autodiff saves EVERY tick's stage residuals (all internal
  activations × (M+S-1) ticks) for the backward — the GPipe liveness
  profile.
- "1f1b": custom_vjp. Forward saves only each tick's stage INPUT (one
  microbatch activation per tick); backward is an explicit reverse scan
  that recomputes the stage forward and runs its VJP, with activation
  gradients hopping backward over the reverse ppermute ring. This is the
  1F1B memory discipline (peak extra liveness = per-tick inputs, not full
  residuals) expressed as a single XLA program. Measured on GPTStacked
  pp=4×dp=2, 8 microbatches (examples/bench_pipeline.py): 1.56× faster
  and 5.7× less temp memory than "gpipe".
- "interleaved": virtual pipeline stages (reference
  fleet/meta_parallel/pipeline_parallel.py interleaved 1F1B scheduler +
  Megatron-LM interleaving). Each device owns `virtual` non-contiguous
  layer chunks; chunk c on device d is global virtual stage c*S+d, so one
  microbatch visits every device V times. A tick does 1/V of a stage's
  work, shrinking the pipeline-fill bubble from (S-1) stage-ticks to
  ~(S-1) CHUNK-ticks — the bubble fraction drops by the virtual factor V.
  The schedule itself is simulated on the host at trace time (greedy
  earliest-ready, breadth-first priority) and baked into the compiled
  program as static gather tables; activations hop on a forward ppermute
  ring plus a wrap ring (last device → device 0) between chunks.
- "interleaved_1f1b": the interleaved schedule with the 1F1B recompute
  backward (reference interleaved-1F1B,
  fleet/meta_parallel/pipeline_parallel.py:171): virtual-stage bubble AND
  per-tick-input liveness. Measured on GPTStacked pp=4×dp=2, 8
  microbatches (examples/bench_pipeline.py): 1.19× faster and 8.3× less
  temp memory than "interleaved"'s autodiff backward.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_apply", "interleaved_schedule_table"]

# jax 0.4.x shard_map transpose convention (pre-VMA, detected via the
# pcast API that shipped with the new system): the cotangent of a
# replicated (P()) OUTPUT reaches a custom_vjp body divided by the FULL
# device count, while a replicated input's cotangent is only psummed
# over the axes its in_spec leaves unmentioned. A body whose params ride
# in_spec P(axis, ...) therefore comes out 1/axis_size too small and
# must rescale dparams itself; >= 0.6 transposes symmetrically and needs
# no correction (autodiff-through-shard_map is symmetric on both).
_LEGACY_SHARD_MAP_TRANSPOSE = not hasattr(jax.lax, "pcast")


def _legacy_dparams_fix(dparams, axis_name):
    if not _LEGACY_SHARD_MAP_TRANSPOSE:
        return dparams
    s = jax.lax.psum(1, axis_name)
    return jax.tree_util.tree_map(lambda v: v * s, dparams)


def _make_varying(axis_name):
    def _varying(z):
        try:
            return jax.lax.pcast(z, (axis_name,), to="varying")
        except ValueError:       # already varying over axis_name
            return z
        except AttributeError:   # jax 0.4.x: no VMA system — nothing to cast
            return z
    return _varying


def _make_fwd_scan(stage_fn, n_micro, n_stages, axis_name):
    """Shared forward schedule. Returns (out, per-tick stage inputs)."""
    M, S = n_micro, n_stages
    T = M + S - 1
    perm = [(i, i + 1) for i in range(S - 1)]
    _varying = _make_varying(axis_name)

    def fwd_scan(params_local, xv):
        idx = jax.lax.axis_index(axis_name)
        B = xv.shape[0]
        mb = xv.reshape((M, B // M) + xv.shape[1:])
        out_buf0 = _varying(jnp.zeros_like(mb))
        recv0 = _varying(jnp.zeros_like(mb[0]))

        def tick(carry, t):
            out_buf, recv = carry
            mb_idx = jnp.clip(t, 0, M - 1)
            x_t = jax.lax.dynamic_index_in_dim(mb, mb_idx, 0, keepdims=False)
            x_in = jnp.where(idx == 0, x_t, recv)
            y = stage_fn(params_local, x_in)
            widx = jnp.clip(t - (S - 1), 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(out_buf, widx, 0, keepdims=False)
            write = jnp.where(t >= S - 1, y, cur)
            out_buf = jax.lax.dynamic_update_index_in_dim(out_buf, write, widx, 0)
            recv = jax.lax.ppermute(y, axis_name, perm)
            return (out_buf, recv), x_in

        (out_buf, _), xs = jax.lax.scan(tick, (out_buf0, recv0), jnp.arange(T))
        # only the LAST stage's buffer holds the model output; psum-broadcast
        out_buf = jnp.where(idx == S - 1, out_buf, jnp.zeros_like(out_buf))
        out_buf = jax.lax.psum(out_buf, axis_name)
        return out_buf.reshape(xv.shape[:1] + out_buf.shape[2:]), xs

    return fwd_scan, _varying


def _gpipe_local(stage_fn, n_micro, n_stages, axis_name):
    fwd_scan, _ = _make_fwd_scan(stage_fn, n_micro, n_stages, axis_name)
    return lambda params_local, xv: fwd_scan(params_local, xv)[0]


def _1f1b_local(stage_fn, n_micro, n_stages, axis_name):
    """1F1B-liveness schedule as a custom_vjp over the local (per-stage)
    computation. Same tick count as GPipe (the pipeline bubble is
    fundamental); the difference is what the backward reads: saved stage
    inputs + recompute, never the full per-tick residual stash."""
    M, S = n_micro, n_stages
    T = M + S - 1
    rev_perm = [(i + 1, i) for i in range(S - 1)]
    fwd_scan, _varying = _make_fwd_scan(stage_fn, M, S, axis_name)

    @jax.custom_vjp
    def run(params_local, xv):
        out, _ = fwd_scan(params_local, xv)
        return out

    def run_fwd(params_local, xv):
        out, xs = fwd_scan(params_local, xv)
        return out, (params_local, xs)

    def run_bwd(res, g):
        params_local, xs = res
        idx = jax.lax.axis_index(axis_name)
        mb_shape = xs.shape[1:]          # one microbatch of activations
        gmb = g.reshape((M,) + mb_shape[:1] + g.shape[1:])
        zero_mb = _varying(jnp.zeros_like(xs[0]))
        dparams0 = jax.tree_util.tree_map(
            lambda v: _varying(jnp.zeros_like(v)), params_local)
        dmb0 = _varying(jnp.zeros((M,) + mb_shape, xs.dtype))

        def btick(carry, r):
            dparams, dmb, dsend = carry
            t = T - 1 - r
            grad_recv = jax.lax.ppermute(dsend, axis_name, rev_perm)
            # cotangent of this stage's tick-t output
            widx = jnp.clip(t - (S - 1), 0, M - 1)
            g_t = jax.lax.dynamic_index_in_dim(gmb, widx, 0, keepdims=False)
            dy_last = jnp.where(t >= S - 1, g_t.astype(xs.dtype),
                                jnp.zeros_like(g_t, xs.dtype))
            dy = jnp.where(idx == S - 1, dy_last, grad_recv)
            # ticks where this stage processed garbage contribute nothing
            valid = jnp.logical_and(t - idx >= 0, t - idx <= M - 1)
            dy = jnp.where(valid, dy, jnp.zeros_like(dy))
            x_in = jax.lax.dynamic_index_in_dim(xs, t, 0, keepdims=False)
            _, vjp_fn = jax.vjp(stage_fn, params_local, x_in)
            dp_t, dx_t = vjp_fn(dy)
            dparams = jax.tree_util.tree_map(jnp.add, dparams, dp_t)
            # stage 0's input grad is the pipeline input's microbatch grad
            mb_idx = jnp.clip(t, 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(dmb, mb_idx, 0, keepdims=False)
            upd = jnp.where(jnp.logical_and(idx == 0, valid), dx_t, cur)
            dmb = jax.lax.dynamic_update_index_in_dim(dmb, upd, mb_idx, 0)
            return (dparams, dmb, dx_t), None

        (dparams, dmb, _), _ = jax.lax.scan(
            btick, (dparams0, dmb0, zero_mb), jnp.arange(T))
        dxv = dmb.reshape((M * mb_shape[0],) + mb_shape[1:])
        # only stage 0 holds the true input grad; psum the masked value so
        # the cotangent is pp-invariant, matching the replicated in_spec
        dxv = jnp.where(idx == 0, dxv, jnp.zeros_like(dxv))
        return (_legacy_dparams_fix(dparams, axis_name),
                jax.lax.psum(dxv, axis_name))

    run.defvjp(run_fwd, run_bwd)
    return run


def _simulate_interleaved(n_micro, n_stages, virtual):
    """Greedy earliest-ready simulation of the interleaved schedule.

    Work item (m, k): microbatch m at global virtual stage k = c*S + d
    (chunk c of device d). Item input is ready one tick after the previous
    virtual stage computed it; each device runs at most one chunk per tick;
    ties broken breadth-first (lowest chunk, then lowest microbatch), which
    keeps the wrap link busy and realizes the ~(S-1)-chunk-tick fill bubble.

    Returns (T, compute) with compute = [(t, d, m, c), ...].
    """
    M, S, V = n_micro, n_stages, virtual
    SV = S * V
    avail = {(m, 0): 0 for m in range(M)}       # (m, k) -> ready tick
    done = set()
    compute = []                                # (t, d, m, c)
    t = 0
    while len(done) < M * SV:
        for d in range(S):
            ready = [(c, m)
                     for c in range(V) for m in range(M)
                     if (m, c * S + d) not in done
                     and avail.get((m, c * S + d), None) is not None
                     and avail[(m, c * S + d)] <= t]
            if not ready:
                continue
            c, m = min(ready)
            k = c * S + d
            done.add((m, k))
            compute.append((t, d, m, c))
            if k + 1 < SV:
                avail[(m, k + 1)] = t + 1
        t += 1
    return t, compute


def interleaved_schedule_table(n_micro, n_stages, virtual):
    """Forward tables, dict of numpy [T, S]:
      work/mb/ch    — does device d compute at tick t, and which (m, c)
      stv/stm/stc   — should device d STORE the value received at tick t,
                      and into which buffer slot (m, c)
      out           — is this tick's computed y a final-stage output
    """
    M, S, V = n_micro, n_stages, virtual
    SV = S * V
    T, compute = _simulate_interleaved(M, S, V)
    tbl = {key: np.zeros((T, S), np.int32)
           for key in ("work", "mb", "ch", "stv", "stm", "stc", "out")}
    for (tc, d, m, c) in compute:
        k = c * S + d
        tbl["work"][tc, d] = 1
        tbl["mb"][tc, d] = m
        tbl["ch"][tc, d] = c
        if k == SV - 1:
            tbl["out"][tc, d] = 1
        elif tc + 1 < T:
            d2 = (k + 1) % S
            tbl["stv"][tc + 1, d2] = 1
            tbl["stm"][tc + 1, d2] = m
            tbl["stc"][tc + 1, d2] = (k + 1) // S
    return T, tbl


def interleaved_backward_tables(n_micro, n_stages, virtual):
    """Mirror tables for the 1F1B recompute backward: device d re-runs the
    VJP of exactly the items it computed forward, at mirrored ticks
    r = T-1-t.  The consumer of item (m,k)'s output is item (m,k+1) on
    device (k+1)%S at forward tick t2 > t; its input-cotangent dx hops the
    REVERSE ring at backward tick r2 = T-1-t2 and is stored by d one tick
    later (r2+1 <= r, so it is always buffered before use).
    """
    M, S, V = n_micro, n_stages, virtual
    SV = S * V
    T, compute = _simulate_interleaved(M, S, V)
    item_tick = {(m, c * S + d): t for (t, d, m, c) in compute}
    tbl = {key: np.zeros((T, S), np.int32)
           for key in ("work", "mb", "ch", "stv", "stm", "stc", "out")}
    for (tc, d, m, c) in compute:
        k = c * S + d
        r = T - 1 - tc
        tbl["work"][r, d] = 1
        tbl["mb"][r, d] = m
        tbl["ch"][r, d] = c
        if k == SV - 1:
            tbl["out"][r, d] = 1        # dy comes straight from g[m]
        else:
            r2 = T - 1 - item_tick[(m, k + 1)]
            tbl["stv"][r2 + 1, d] = 1
            tbl["stm"][r2 + 1, d] = m
            tbl["stc"][r2 + 1, d] = c
    return T, tbl


def _make_interleaved_fwd(stage_fn, n_micro, n_stages, virtual, axis_name):
    """Shared interleaved forward scan. Returns (out, per-tick chunk
    inputs xs [T, ...]) — xs is the only residual the 1F1B backward
    needs. params_local leaves are [V*cl, ...]: chunk c of THIS device =
    rows [c*cl, (c+1)*cl) after the interleave permutation applied in
    pipeline_apply."""
    M, S, V = n_micro, n_stages, virtual
    T, tbl = interleaved_schedule_table(M, S, V)
    jt = {k: jnp.asarray(v) for k, v in tbl.items()}
    # one full-ring hop per tick: d -> d+1, plus the S-1 -> 0 wrap that
    # carries chunk c outputs into chunk c+1 on device 0
    perm_ring = [(i, (i + 1) % S) for i in range(S)]
    _varying = _make_varying(axis_name)

    def fwd_scan(params_local, xv):
        idx = jax.lax.axis_index(axis_name)
        B = xv.shape[0]
        mb = xv.reshape((M, B // M) + xv.shape[1:])
        mb_shape = mb.shape[1:]
        cl = jax.tree_util.tree_leaves(params_local)[0].shape[0] // V
        buf0 = _varying(jnp.zeros((V, M) + mb_shape, xv.dtype))
        out0 = _varying(jnp.zeros_like(mb))
        ysend0 = _varying(jnp.zeros(mb_shape, xv.dtype))
        zero_nd = (0,) * len(mb_shape)

        def tick(carry, t):
            buf, out_buf, ysend = carry
            # 1) receive last tick's hop on the ring
            recv = jax.lax.ppermute(ysend, axis_name, perm_ring)
            stv, stm, stc = jt["stv"][t, idx], jt["stm"][t, idx], jt["stc"][t, idx]
            cur = jax.lax.dynamic_slice(buf, (stc, stm) + zero_nd,
                                        (1, 1) + mb_shape)[0, 0]
            buf = jax.lax.dynamic_update_slice(
                buf, jnp.where(stv == 1, recv, cur)[None, None],
                (stc, stm) + zero_nd)
            # 2) compute this tick's chunk (idle devices run on garbage;
            #    consumers are gated by the tables so it never escapes)
            w, m, c = jt["work"][t, idx], jt["mb"][t, idx], jt["ch"][t, idx]
            x_direct = jax.lax.dynamic_index_in_dim(mb, m, 0, keepdims=False)
            x_buf = jax.lax.dynamic_slice(buf, (c, m) + zero_nd,
                                          (1, 1) + mb_shape)[0, 0]
            x_in = jnp.where(jnp.logical_and(idx == 0, c == 0), x_direct, x_buf)
            p_c = jax.tree_util.tree_map(
                lambda v: jax.lax.dynamic_slice_in_dim(v, c * cl, cl, 0),
                params_local)
            y = stage_fn(p_c, x_in)
            # 3) final-virtual-stage outputs land in the output buffer
            out_cur = jax.lax.dynamic_index_in_dim(out_buf, m, 0, keepdims=False)
            is_out = jnp.logical_and(w == 1, jt["out"][t, idx] == 1)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(is_out, y, out_cur), m, 0)
            return (buf, out_buf, y), x_in

        (_, out_buf, _), xs = jax.lax.scan(tick, (buf0, out0, ysend0),
                                           jnp.arange(T))
        # final virtual stage SV-1 lives on device S-1
        out_buf = jnp.where(idx == S - 1, out_buf, jnp.zeros_like(out_buf))
        out_buf = jax.lax.psum(out_buf, axis_name)
        return out_buf.reshape(xv.shape[:1] + out_buf.shape[2:]), xs

    return fwd_scan, _varying


def _interleaved_local(stage_fn, n_micro, n_stages, virtual, axis_name):
    """Interleaved forward, backward by XLA autodiff of the scan (GPipe
    liveness: the autodiff saves every tick's internal stage residuals)."""
    fwd_scan, _ = _make_interleaved_fwd(stage_fn, n_micro, n_stages,
                                        virtual, axis_name)
    return lambda params_local, xv: fwd_scan(params_local, xv)[0]


def _interleaved_1f1b_local(stage_fn, n_micro, n_stages, virtual, axis_name):
    """Interleaved schedule WITH the 1F1B recompute backward (reference
    fleet/meta_parallel/pipeline_parallel.py:171 — interleaved 1F1B):
    forward saves only each tick's chunk input; the backward replays the
    mirrored schedule, recomputing each chunk forward and applying its
    VJP, with input-cotangents hopping the reverse ring and buffering in
    a [V, M] grad buffer until their producer's backward tick."""
    M, S, V = n_micro, n_stages, virtual
    SV = S * V
    T, btbl = interleaved_backward_tables(M, S, V)
    jb = {k: jnp.asarray(v) for k, v in btbl.items()}
    rev_ring = [((i + 1) % S, i) for i in range(S)]
    fwd_scan, _varying = _make_interleaved_fwd(stage_fn, M, S, V, axis_name)

    @jax.custom_vjp
    def run(params_local, xv):
        return fwd_scan(params_local, xv)[0]

    def run_fwd(params_local, xv):
        out, xs = fwd_scan(params_local, xv)
        return out, (params_local, xs)

    def run_bwd(res, g):
        params_local, xs = res
        idx = jax.lax.axis_index(axis_name)
        mb_shape = xs.shape[1:]
        cl = jax.tree_util.tree_leaves(params_local)[0].shape[0] // V
        gmb = g.reshape((M,) + mb_shape[:1] + g.shape[1:]).astype(xs.dtype)
        zero_nd = (0,) * len(mb_shape)
        dbuf0 = _varying(jnp.zeros((V, M) + mb_shape, xs.dtype))
        dmb0 = _varying(jnp.zeros((M,) + mb_shape, xs.dtype))
        dsend0 = _varying(jnp.zeros(mb_shape, xs.dtype))
        dparams0 = jax.tree_util.tree_map(
            lambda v: _varying(jnp.zeros_like(v)), params_local)

        def btick(carry, r):
            dbuf, dmb, dparams, dsend = carry
            # 1) receive the reverse-ring hop, store per mirror tables
            drecv = jax.lax.ppermute(dsend, axis_name, rev_ring)
            stv, stm, stc = jb["stv"][r, idx], jb["stm"][r, idx], jb["stc"][r, idx]
            cur = jax.lax.dynamic_slice(dbuf, (stc, stm) + zero_nd,
                                        (1, 1) + mb_shape)[0, 0]
            dbuf = jax.lax.dynamic_update_slice(
                dbuf, jnp.where(stv == 1, drecv, cur)[None, None],
                (stc, stm) + zero_nd)
            # 2) backward-compute this tick's mirrored item
            w, m, c = jb["work"][r, idx], jb["mb"][r, idx], jb["ch"][r, idx]
            is_out = jb["out"][r, idx]
            g_t = jax.lax.dynamic_index_in_dim(gmb, m, 0, keepdims=False)
            d_buf = jax.lax.dynamic_slice(dbuf, (c, m) + zero_nd,
                                          (1, 1) + mb_shape)[0, 0]
            dy = jnp.where(is_out == 1, g_t, d_buf)
            dy = jnp.where(w == 1, dy, jnp.zeros_like(dy))
            t = T - 1 - r
            x_in = jax.lax.dynamic_index_in_dim(xs, t, 0, keepdims=False)
            p_c = jax.tree_util.tree_map(
                lambda v: jax.lax.dynamic_slice_in_dim(v, c * cl, cl, 0),
                params_local)
            _, vjp_fn = jax.vjp(stage_fn, p_c, x_in)
            dp_t, dx_t = vjp_fn(dy)
            dparams = jax.tree_util.tree_map(
                lambda acc, dpc: jax.lax.dynamic_update_slice_in_dim(
                    acc,
                    jax.lax.dynamic_slice_in_dim(acc, c * cl, cl, 0) + dpc,
                    c * cl, 0),
                dparams, dp_t)
            # 3) global-first-stage items feed the input cotangent
            is_first = jnp.logical_and(jnp.logical_and(idx == 0, c == 0),
                                       w == 1)
            cur_dmb = jax.lax.dynamic_index_in_dim(dmb, m, 0, keepdims=False)
            dmb = jax.lax.dynamic_update_index_in_dim(
                dmb, jnp.where(is_first, dx_t, cur_dmb), m, 0)
            return (dbuf, dmb, dparams, dx_t), None

        (_, dmb, dparams, _), _ = jax.lax.scan(
            btick, (dbuf0, dmb0, dparams0, dsend0), jnp.arange(T))
        dxv = dmb.reshape((M * mb_shape[0],) + mb_shape[1:])
        dxv = jnp.where(idx == 0, dxv, jnp.zeros_like(dxv))
        return (_legacy_dparams_fix(dparams, axis_name),
                jax.lax.psum(dxv, axis_name))

    run.defvjp(run_fwd, run_bwd)
    return run


def _interleave_perm(n_layers, n_stages, virtual):
    """Permutation mapping contiguous [L] layers to the interleaved
    device-major layout: device d holds (in order) the layers of virtual
    stages d, S+d, 2S+d, … so a plain 'pp'-sharding of dim 0 gives each
    device its V chunks contiguously."""
    cl = n_layers // (n_stages * virtual)
    perm = []
    for d in range(n_stages):
        for c in range(virtual):
            v = c * n_stages + d
            perm.extend(range(v * cl, (v + 1) * cl))
    return np.asarray(perm, np.int32)


def pipeline_apply(stage_fn, stacked_params, x, n_microbatch, mesh=None,
                   axis_name="pp", param_specs=None, schedule="gpipe",
                   virtual=2, pre_permuted=False):
    """Run layers stacked on leading dim through a pipeline schedule.

    stage_fn(local_params, x) -> y   applies this stage's layer slice
    stacked_params: pytree, leaves [L_total, ...], sharded over 'pp' on dim 0
    x: [B, ...] activations (replicated w.r.t. 'pp')
    schedule: "gpipe" (autodiff backward), "1f1b" (recompute backward
              with 1F1B activation liveness), or "interleaved" (virtual
              pipeline stages — `virtual` chunks per device)
    virtual: chunks per device for schedule="interleaved"
    pre_permuted: the caller already stores stacked_params in the
              interleaved device-major layout (_interleave_perm), so the
              compiled step does zero layer resharding. When False the
              permutation happens here via jnp.take — correct, but it
              costs an all-to-all of the whole layer stack every step;
              long-lived models should permute their storage once instead
              (see GPTStacked).
    """
    from .mesh import get_mesh

    mesh = mesh or get_mesh()
    n_stages = mesh.shape.get(axis_name, 1)
    if n_stages == 1:
        return stage_fn(stacked_params, x)

    n_micro = n_microbatch
    assert x.shape[0] % n_micro == 0, "batch must divide microbatches"

    if schedule == "1f1b":
        local_fn = _1f1b_local(stage_fn, n_micro, n_stages, axis_name)
    elif schedule == "gpipe":
        local_fn = _gpipe_local(stage_fn, n_micro, n_stages, axis_name)
    elif schedule in ("interleaved", "interleaved_1f1b"):
        L_total = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        if virtual <= 1 or L_total % (n_stages * virtual):
            raise ValueError(
                f"interleaved schedule needs layers ({L_total}) divisible by "
                f"pp*virtual ({n_stages}*{virtual}) and virtual>1")
        if not pre_permuted:
            perm = jnp.asarray(_interleave_perm(L_total, n_stages, virtual))
            stacked_params = jax.tree_util.tree_map(
                lambda v: jnp.take(v, perm, axis=0), stacked_params)
        make = (_interleaved_1f1b_local if schedule == "interleaved_1f1b"
                else _interleaved_local)
        local_fn = make(stage_fn, n_micro, n_stages, virtual, axis_name)
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r} (want "
                         "'gpipe', '1f1b', 'interleaved' or "
                         "'interleaved_1f1b')")

    if param_specs is None:
        param_specs = jax.tree_util.tree_map(
            lambda v: P(axis_name, *([None] * (v.ndim - 1))), stacked_params)
    from .mesh import compat_shard_map
    return compat_shard_map(
        local_fn, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        axis_names={axis_name},
    )(stacked_params, x)
