"""Pipeline parallelism: SPMD schedules over the 'pp' mesh axis.

Replaces reference fleet pipeline_parallel.py (P2P send/recv between rank
processes, GPipe/1F1B schedulers in python —
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:82,171)
with the TPU-native formulation: ONE compiled program in which every stage
runs the same code, activations hop stages via ppermute on ICI, and the
microbatch schedule is a lax.scan over ticks. shard_map is manual ONLY over
'pp' (axis_names={'pp'}) so tensor/data parallel dims inside each stage stay
GSPMD-managed — pp×tp×dp×sp compose.

Two schedules:

- "gpipe": forward scan, backward by XLA autodiff of the scan. Simple, but
  the autodiff saves EVERY tick's stage residuals (all internal
  activations × (M+S-1) ticks) for the backward — the GPipe liveness
  profile.
- "1f1b": custom_vjp. Forward saves only each tick's stage INPUT (one
  microbatch activation per tick); backward is an explicit reverse scan
  that recomputes the stage forward and runs its VJP, with activation
  gradients hopping backward over the reverse ppermute ring. This is the
  1F1B memory discipline (peak extra liveness = per-tick inputs, not full
  residuals) expressed as a single XLA program. Measured on GPTStacked
  pp=4×dp=2, 8 microbatches (examples/bench_pipeline.py): 1.56× faster
  and 5.7× less temp memory than "gpipe".
"""
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_apply"]


def _make_fwd_scan(stage_fn, n_micro, n_stages, axis_name):
    """Shared forward schedule. Returns (out, per-tick stage inputs)."""
    M, S = n_micro, n_stages
    T = M + S - 1
    perm = [(i, i + 1) for i in range(S - 1)]

    def _varying(z):
        try:
            return jax.lax.pcast(z, (axis_name,), to="varying")
        except ValueError:  # already varying over axis_name
            return z

    def fwd_scan(params_local, xv):
        idx = jax.lax.axis_index(axis_name)
        B = xv.shape[0]
        mb = xv.reshape((M, B // M) + xv.shape[1:])
        out_buf0 = _varying(jnp.zeros_like(mb))
        recv0 = _varying(jnp.zeros_like(mb[0]))

        def tick(carry, t):
            out_buf, recv = carry
            mb_idx = jnp.clip(t, 0, M - 1)
            x_t = jax.lax.dynamic_index_in_dim(mb, mb_idx, 0, keepdims=False)
            x_in = jnp.where(idx == 0, x_t, recv)
            y = stage_fn(params_local, x_in)
            widx = jnp.clip(t - (S - 1), 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(out_buf, widx, 0, keepdims=False)
            write = jnp.where(t >= S - 1, y, cur)
            out_buf = jax.lax.dynamic_update_index_in_dim(out_buf, write, widx, 0)
            recv = jax.lax.ppermute(y, axis_name, perm)
            return (out_buf, recv), x_in

        (out_buf, _), xs = jax.lax.scan(tick, (out_buf0, recv0), jnp.arange(T))
        # only the LAST stage's buffer holds the model output; psum-broadcast
        out_buf = jnp.where(idx == S - 1, out_buf, jnp.zeros_like(out_buf))
        out_buf = jax.lax.psum(out_buf, axis_name)
        return out_buf.reshape(xv.shape[:1] + out_buf.shape[2:]), xs

    return fwd_scan, _varying


def _gpipe_local(stage_fn, n_micro, n_stages, axis_name):
    fwd_scan, _ = _make_fwd_scan(stage_fn, n_micro, n_stages, axis_name)
    return lambda params_local, xv: fwd_scan(params_local, xv)[0]


def _1f1b_local(stage_fn, n_micro, n_stages, axis_name):
    """1F1B-liveness schedule as a custom_vjp over the local (per-stage)
    computation. Same tick count as GPipe (the pipeline bubble is
    fundamental); the difference is what the backward reads: saved stage
    inputs + recompute, never the full per-tick residual stash."""
    M, S = n_micro, n_stages
    T = M + S - 1
    rev_perm = [(i + 1, i) for i in range(S - 1)]
    fwd_scan, _varying = _make_fwd_scan(stage_fn, M, S, axis_name)

    @jax.custom_vjp
    def run(params_local, xv):
        out, _ = fwd_scan(params_local, xv)
        return out

    def run_fwd(params_local, xv):
        out, xs = fwd_scan(params_local, xv)
        return out, (params_local, xs)

    def run_bwd(res, g):
        params_local, xs = res
        idx = jax.lax.axis_index(axis_name)
        mb_shape = xs.shape[1:]          # one microbatch of activations
        gmb = g.reshape((M,) + mb_shape[:1] + g.shape[1:])
        zero_mb = _varying(jnp.zeros_like(xs[0]))
        dparams0 = jax.tree_util.tree_map(
            lambda v: _varying(jnp.zeros_like(v)), params_local)
        dmb0 = _varying(jnp.zeros((M,) + mb_shape, xs.dtype))

        def btick(carry, r):
            dparams, dmb, dsend = carry
            t = T - 1 - r
            grad_recv = jax.lax.ppermute(dsend, axis_name, rev_perm)
            # cotangent of this stage's tick-t output
            widx = jnp.clip(t - (S - 1), 0, M - 1)
            g_t = jax.lax.dynamic_index_in_dim(gmb, widx, 0, keepdims=False)
            dy_last = jnp.where(t >= S - 1, g_t.astype(xs.dtype),
                                jnp.zeros_like(g_t, xs.dtype))
            dy = jnp.where(idx == S - 1, dy_last, grad_recv)
            # ticks where this stage processed garbage contribute nothing
            valid = jnp.logical_and(t - idx >= 0, t - idx <= M - 1)
            dy = jnp.where(valid, dy, jnp.zeros_like(dy))
            x_in = jax.lax.dynamic_index_in_dim(xs, t, 0, keepdims=False)
            _, vjp_fn = jax.vjp(stage_fn, params_local, x_in)
            dp_t, dx_t = vjp_fn(dy)
            dparams = jax.tree_util.tree_map(jnp.add, dparams, dp_t)
            # stage 0's input grad is the pipeline input's microbatch grad
            mb_idx = jnp.clip(t, 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(dmb, mb_idx, 0, keepdims=False)
            upd = jnp.where(jnp.logical_and(idx == 0, valid), dx_t, cur)
            dmb = jax.lax.dynamic_update_index_in_dim(dmb, upd, mb_idx, 0)
            return (dparams, dmb, dx_t), None

        (dparams, dmb, _), _ = jax.lax.scan(
            btick, (dparams0, dmb0, zero_mb), jnp.arange(T))
        dxv = dmb.reshape((M * mb_shape[0],) + mb_shape[1:])
        # only stage 0 holds the true input grad; psum the masked value so
        # the cotangent is pp-invariant, matching the replicated in_spec
        dxv = jnp.where(idx == 0, dxv, jnp.zeros_like(dxv))
        return dparams, jax.lax.psum(dxv, axis_name)

    run.defvjp(run_fwd, run_bwd)
    return run


def pipeline_apply(stage_fn, stacked_params, x, n_microbatch, mesh=None,
                   axis_name="pp", param_specs=None, schedule="gpipe"):
    """Run layers stacked on leading dim through a pipeline schedule.

    stage_fn(local_params, x) -> y   applies this stage's layer slice
    stacked_params: pytree, leaves [L_total, ...], sharded over 'pp' on dim 0
    x: [B, ...] activations (replicated w.r.t. 'pp')
    schedule: "gpipe" (autodiff backward) or "1f1b" (recompute backward
              with 1F1B activation liveness)
    """
    from .mesh import get_mesh

    mesh = mesh or get_mesh()
    n_stages = mesh.shape.get(axis_name, 1)
    if n_stages == 1:
        return stage_fn(stacked_params, x)

    n_micro = n_microbatch
    assert x.shape[0] % n_micro == 0, "batch must divide microbatches"

    if schedule == "1f1b":
        local_fn = _1f1b_local(stage_fn, n_micro, n_stages, axis_name)
    elif schedule == "gpipe":
        local_fn = _gpipe_local(stage_fn, n_micro, n_stages, axis_name)
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         "(want 'gpipe' or '1f1b')")

    if param_specs is None:
        param_specs = jax.tree_util.tree_map(
            lambda v: P(axis_name, *([None] * (v.ndim - 1))), stacked_params)
    return jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        axis_names={axis_name},
    )(stacked_params, x)
