"""Pipeline parallelism: SPMD GPipe over the 'pp' mesh axis.

Replaces reference fleet pipeline_parallel.py (P2P send/recv between rank
processes, 1F1B scheduler in python) with the TPU-native formulation: ONE
compiled program in which every stage runs the same code, activations hop
stages via ppermute on ICI, and the microbatch schedule is a lax.scan over
ticks. shard_map is manual ONLY over 'pp' (axis_names={'pp'}) so tensor/data
parallel dims inside each stage stay GSPMD-managed — pp×tp×dp×sp compose.
"""
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_apply"]


def pipeline_apply(stage_fn, stacked_params, x, n_microbatch, mesh=None,
                   axis_name="pp", param_specs=None):
    """Run layers stacked on leading dim through a GPipe schedule.

    stage_fn(local_params, x) -> y   applies this stage's layer slice
    stacked_params: pytree, leaves [L_total, ...], sharded over 'pp' on dim 0
    x: [B, ...] activations (replicated w.r.t. 'pp')
    """
    from .mesh import get_mesh

    mesh = mesh or get_mesh()
    n_stages = mesh.shape.get(axis_name, 1)
    if n_stages == 1:
        return stage_fn(stacked_params, x)

    n_micro = n_microbatch
    assert x.shape[0] % n_micro == 0, "batch must divide microbatches"

    def local_fn(params_local, xv):
        idx = jax.lax.axis_index(axis_name)
        B = xv.shape[0]
        mb = xv.reshape((n_micro, B // n_micro) + xv.shape[1:])
        T = n_micro + n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]
        out_buf0 = jax.lax.pcast(jnp.zeros_like(mb), (axis_name,), to="varying")
        recv0 = jax.lax.pcast(jnp.zeros_like(mb[0]), (axis_name,), to="varying")

        def tick(carry, t):
            out_buf, recv = carry
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            x_t = jax.lax.dynamic_index_in_dim(mb, mb_idx, 0, keepdims=False)
            x_in = jnp.where(idx == 0, x_t, recv)
            y = stage_fn(params_local, x_in)
            widx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            cur = jax.lax.dynamic_index_in_dim(out_buf, widx, 0, keepdims=False)
            write = jnp.where(t >= n_stages - 1, y, cur)
            out_buf = jax.lax.dynamic_update_index_in_dim(out_buf, write, widx, 0)
            recv = jax.lax.ppermute(y, axis_name, perm)
            return (out_buf, recv), None

        (out_buf, _), _ = jax.lax.scan(tick, (out_buf0, recv0), jnp.arange(T))
        # only the LAST stage's buffer holds the model output; psum-broadcast
        out_buf = jnp.where(idx == n_stages - 1, out_buf, jnp.zeros_like(out_buf))
        out_buf = jax.lax.psum(out_buf, axis_name)
        return out_buf.reshape(xv.shape[:1] + out_buf.shape[2:])

    if param_specs is None:
        param_specs = jax.tree_util.tree_map(
            lambda v: P(axis_name, *([None] * (v.ndim - 1))), stacked_params)
    return jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        axis_names={axis_name},
    )(stacked_params, x)
