"""Compiled distributed training step.

This is the TPU replacement for the reference's fleet training loop
(dygraph forward → eager allreduce → optimizer): ONE jit-compiled XLA
program per step containing forward, backward, grad reduction, clipping and
the optimizer update, with params/optimizer state donated (updated in-place
in HBM) and every tensor sharded per the GSPMD plan. XLA overlaps the
collectives with compute on ICI.
"""
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.core import Tensor
from ..nn.layer_base import functional_call, load_state_pytree
from .mesh import get_mesh
from .sharding_utils import feasible_spec, plan_shardings

__all__ = ["Trainer", "shard_batch", "make_compute_loss", "batch_to_arrays"]

# consts key carrying the step counter that salts in-step RNG draws
_RNG_STEP = "__rng_step__"


def make_compute_loss(model, loss_fn):
    """Pure (params, consts, batch) -> (fp32 loss, buffer_updates) via
    functional_call. Shared by Trainer and LocalSGDTrainer so loss/dtype
    handling can't drift.

    buffer_updates is {name: traced_value} for buffers whose ops attempted a
    state write during the trace (BatchNorm running stats): the caller folds
    them back into its consts so stats keep accumulating under jit."""
    from ..nn.layer_base import collect_buffer_updates

    def compute_loss(p, consts, batch):
        with collect_buffer_updates() as sink:
            with functional_call(model, {**p, **consts}):
                loss = loss_fn(model, batch)
        updates = {}
        if sink:
            by_id = {id(b): name for name, b in model.named_buffers()}
            for tid, (_, val) in sink.items():
                name = by_id.get(tid)
                if name is not None:
                    updates[name] = val
        lv = loss._value if isinstance(loss, Tensor) else loss
        return lv.astype(jnp.float32), updates
    return compute_loss


def batch_to_arrays(batch):
    """Tensor leaves -> raw arrays, for any pytree-shaped batch."""
    return jax.tree_util.tree_map(
        lambda v: v._value if isinstance(v, Tensor) else jnp.asarray(v),
        batch, is_leaf=lambda x: isinstance(x, Tensor))


def shard_batch(batch, mesh=None, spec=("dp", "fsdp")):
    """device_put a batch pytree with its leading dim sharded over data axes.

    Axes that don't divide the batch dim are dropped (replicated) so user
    batches of any size are accepted, mirroring `sharding_utils.constraint`."""
    mesh = mesh or get_mesh()

    def put(x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        fspec = feasible_spec(v.shape, (tuple(spec),) + (None,) * (v.ndim - 1), mesh)
        sh = NamedSharding(mesh, PartitionSpec(*fspec))
        return jax.device_put(v, sh)
    return jax.tree_util.tree_map(put, batch)


class Trainer:
    """Owns the sharded params/opt-state and the compiled step.

        trainer = Trainer(model, optimizer, loss_fn)   # loss_fn(model, batch)
        loss = trainer.step(batch)                      # batch: dict of arrays
    """

    def __init__(self, model, optimizer, loss_fn, mesh=None, donate=True,
                 grad_accum_steps=1, grad_transform=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or get_mesh()
        self.grad_accum_steps = grad_accum_steps
        # grad_transform(grads, state) -> (grads, state): gradient
        # compression/filtering between backward and the optimizer (DGC
        # error-feedback sparsification, bf16 cast, custom clipping) —
        # reference fleet meta_optimizers dgc/fp16_allreduce. State (e.g.
        # DGC residuals) is carried inside the compiled step, donated like
        # optimizer slots.
        self.grad_transform = grad_transform
        self._plan = plan_shardings(model, self.mesh)

        trainable, consts = {}, {}
        for name, p in model.named_parameters():
            v = jax.device_put(p._value, self._plan[name])
            (consts if p.stop_gradient else trainable)[name] = v
        for name, b in model.named_buffers():
            consts[name] = jax.device_put(b._value, self._plan[name])
        # per-step RNG salt rides consts so stochastic layers (dropout,
        # noisy MoE gates) draw FRESH randomness every compiled step
        # (framework.random.traced_salt); load_state_pytree ignores it
        consts[_RNG_STEP] = jnp.zeros((), jnp.uint32)
        self.params = trainable
        self.consts = consts
        # slots inherit param shardings: zeros_like under jit keeps sharding
        self.opt_state = jax.jit(optimizer.init_state_pytree)(self.params)
        if self.grad_transform is not None and \
                hasattr(self.grad_transform, "init_state"):
            self.gt_state = jax.jit(self.grad_transform.init_state)(self.params)
        else:
            self.gt_state = None
        self._step_fn = self._build(donate)
        self._host_step = 0

    def _build(self, donate):
        model, optimizer, loss_fn = self.model, self.optimizer, self.loss_fn
        accum = self.grad_accum_steps

        compute_loss = make_compute_loss(model, loss_fn)

        grad_transform = self.grad_transform

        def step(params, opt_state, gt_state, consts, lr, batch):
            from ..framework.random import traced_salt
            with traced_salt(consts.get(_RNG_STEP)):
                return _inner(params, opt_state, gt_state, consts, lr, batch)

        def _inner(params, opt_state, gt_state, consts, lr, batch):
            if accum <= 1:
                (loss_v, buf_updates), grads = jax.value_and_grad(
                    compute_loss, has_aux=True)(params, consts, batch)
            else:
                # gradient merge (reference DistributedStrategy.gradient_merge):
                # microbatch scan accumulating mean grads before ONE update
                micro = jax.tree_util.tree_map(
                    lambda v: v.reshape((accum, v.shape[0] // accum) + v.shape[1:]),
                    batch)

                def body(carry, mb):
                    loss_acc, grad_acc = carry
                    (lv, bu), g = jax.value_and_grad(
                        compute_loss, has_aux=True)(params, consts, mb)
                    grad_acc = jax.tree_util.tree_map(jnp.add, grad_acc, g)
                    return (loss_acc + lv, grad_acc), bu

                zeros = jax.tree_util.tree_map(
                    lambda v: jnp.zeros(v.shape, jnp.float32), params)
                (loss_sum, grad_sum), bus = jax.lax.scan(
                    body, (jnp.zeros((), jnp.float32), zeros), micro)
                loss_v = loss_sum / accum
                grads = jax.tree_util.tree_map(lambda g: g / accum, grad_sum)
                # per-microbatch stat updates all start from the same consts;
                # carry the last microbatch's
                buf_updates = jax.tree_util.tree_map(lambda v: v[-1], bus)
            if grad_transform is not None:
                grads, gt_state = grad_transform(grads, gt_state)
            new_params, new_state = optimizer.apply_gradients_pytree(
                params, grads, opt_state, lr)
            new_consts = {**consts, **buf_updates}
            if _RNG_STEP in consts:
                new_consts[_RNG_STEP] = consts[_RNG_STEP] + 1
            return new_params, new_state, gt_state, new_consts, loss_v

        return jax.jit(step, donate_argnums=(0, 1, 2, 3) if donate else ())

    def step(self, batch, lr=None):
        lr = self.optimizer.get_lr() if lr is None else lr
        batch = batch_to_arrays(batch)
        (self.params, self.opt_state, self.gt_state, self.consts,
         loss) = self._step_fn(
            self.params, self.opt_state, self.gt_state, self.consts, lr, batch)
        sched = self.optimizer._lr_scheduler
        if sched is not None:
            sched.step()
        self._host_step += 1
        return loss

    def sync_to_model(self):
        """Copy trained params AND accumulated buffers (BN running stats)
        back into the Layer tree (for save/eval)."""
        load_state_pytree(self.model, {**self.consts, **self.params})

    def state(self):
        """Host-side snapshot (numpy leaves). Device buffers are donated
        into the next step(), so a live-array snapshot would be invalidated
        the moment training continues."""
        s = {"params": self.params, "opt_state": self.opt_state,
             "step": self._host_step}
        if self.gt_state is not None:   # grad-transform residuals (DGC u/v)
            s["gt_state"] = self.gt_state
        return jax.tree_util.tree_map(
            lambda v: jax.device_get(v) if hasattr(v, "dtype") else v, s)

    def load_state(self, state):
        # EVERY restored leaf is device_put onto the current trainer's
        # template sharding — params AND opt/grad-transform state. The
        # old code handed opt_state to the compiled step as raw numpy:
        # wrong placement semantics under a resharded mesh, and feeding
        # numpy into a DONATED argument of a deserialized (persistent-
        # cache-hit) executable mis-executes outright — silently wrong
        # resume losses, then heap corruption (the
        # tests/test_cross_mesh_resume.py crash that killed whole suite
        # runs).
        def put(t, v):
            if not hasattr(v, "dtype"):
                return v
            sh = getattr(t, "sharding", None)
            if sh is not None and getattr(sh, "num_devices", 1) > 1:
                return jax.device_put(v, sh)
            # template leaf is default-placed (eager opt-state init):
            # an uncommitted device array lets dispatch place it, while
            # still never handing raw HOST memory to a donated argument
            return jnp.asarray(v)

        def put_tree(template, tree):
            return jax.tree_util.tree_map(put, template, tree)

        self.params = put_tree(self.params, state["params"])
        self.opt_state = put_tree(self.opt_state, state["opt_state"])
        if "gt_state" in state:
            self.gt_state = put_tree(self.gt_state, state["gt_state"])
        self._host_step = int(state.get("step", 0))
