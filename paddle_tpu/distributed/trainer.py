"""Compiled distributed training step.

This is the TPU replacement for the reference's fleet training loop
(dygraph forward → eager allreduce → optimizer): ONE jit-compiled XLA
program per step containing forward, backward, grad reduction, clipping and
the optimizer update, with params/optimizer state donated (updated in-place
in HBM) and every tensor sharded per the GSPMD plan. XLA overlaps the
collectives with compute on ICI.
"""
import time
import weakref
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.core import Tensor
from ..nn.layer_base import functional_call, load_state_pytree
from .mesh import get_mesh
from .sharding_utils import plan_shardings

__all__ = ["Trainer", "LossBuffer", "shard_batch", "make_compute_loss",
           "batch_to_arrays"]

# consts key carrying the step counter that salts in-step RNG draws
_RNG_STEP = "__rng_step__"

# every live Trainer, so long-running harnesses (the tier-1 conftest's
# module-boundary GC hook) can trim per-signature compiled-step memos
# without plumbing handles — the ServeStats/_ENGINES registry pattern
_LIVE_TRAINERS = weakref.WeakSet()


def clear_compiled_step_memos():
    """Drop every live Trainer's per-signature compiled-program memos
    (`_placed_steps`/`_placed_multis`/`_batch_shardings`). The memos
    pin compiled executables (megabytes each, plus their jaxpr/HLO
    object graphs); a test-suite module that finished with its
    trainers no longer needs them, and anything still live simply
    recompiles on its next step. Returns the number of entries
    dropped. Used by tests/conftest.py at module boundaries (ROADMAP
    'tier-1 wall-clock health')."""
    n = 0
    for tr in list(_LIVE_TRAINERS):
        for memo in (tr._placed_steps, tr._placed_multis,
                     tr._batch_shardings):
            n += len(memo)
            memo.clear()
    return n


def make_compute_loss(model, loss_fn):
    """Pure (params, consts, batch) -> (fp32 loss, buffer_updates) via
    functional_call. Shared by Trainer and LocalSGDTrainer so loss/dtype
    handling can't drift.

    buffer_updates is {name: traced_value} for buffers whose ops attempted a
    state write during the trace (BatchNorm running stats): the caller folds
    them back into its consts so stats keep accumulating under jit."""
    from ..nn.layer_base import collect_buffer_updates

    def compute_loss(p, consts, batch):
        with collect_buffer_updates() as sink:
            with functional_call(model, {**p, **consts}):
                loss = loss_fn(model, batch)
        updates = {}
        if sink:
            by_id = {id(b): name for name, b in model.named_buffers()}
            for tid, (_, val) in sink.items():
                name = by_id.get(tid)
                if name is not None:
                    updates[name] = val
        lv = loss._value if isinstance(loss, Tensor) else loss
        return lv.astype(jnp.float32), updates
    return compute_loss


def batch_to_arrays(batch):
    """Tensor leaves -> raw arrays, for any pytree-shaped batch."""
    return jax.tree_util.tree_map(
        lambda v: v._value if isinstance(v, Tensor) else jnp.asarray(v),
        batch, is_leaf=lambda x: isinstance(x, Tensor))


def shard_batch(batch, mesh=None, spec=("dp", "fsdp")):
    """device_put a batch pytree with its leading dim sharded over data axes.

    Axes that don't divide the batch dim are dropped (replicated) so user
    batches of any size are accepted, mirroring `sharding_utils.constraint`."""
    from ..io.prefetch import _leaf_arrays, batch_shardings
    mesh = mesh or get_mesh()
    arrays = _leaf_arrays(batch)
    return jax.device_put(arrays, batch_shardings(arrays, mesh, spec))


class LossBuffer:
    """Async metrics drain: `Trainer.step` returns an UNFETCHED device
    loss — calling `float(loss)` every step blocks the host on step N and
    stalls dispatch of N+1 (the dispatch-queue bubble docs/performance.md
    rule 4 warns about). A LossBuffer holds the unfetched losses and
    syncs ONCE per `drain_every` appended STEPS, so the host keeps
    running ahead of the device.

        buf = LossBuffer(drain_every=10)
        for batch in loader:
            buf.append(trainer.step(batch))   # no host sync here
        print(buf.drain())                    # final sync + last loss

    Appends accept both a scalar device loss (`Trainer.step`) and a
    length-N horizon loss vector (`Trainer.step_multi`) — a vector
    counts as N steps toward `drain_every` and drains in step order, so
    mixed per-step / multi-step loops share one buffer. `maxlen` bounds
    the drained-history list; `fetches` counts REAL host syncs
    (observability: it must stay ~steps/drain_every)."""

    def __init__(self, drain_every=16, maxlen=65536):
        self.drain_every = max(1, int(drain_every))
        self.maxlen = maxlen
        self._pending = []
        self._pending_steps = 0
        self.losses = []     # drained python floats, oldest first
        self.fetches = 0     # number of host syncs issued

    @staticmethod
    def _steps_of(loss):
        """1 for a scalar loss, N for a [N] horizon vector — read from
        shape metadata only (never fetches)."""
        shape = getattr(loss, "shape", ())
        return int(shape[0]) if shape else 1

    def append(self, loss):
        self._pending.append(loss)
        self._pending_steps += self._steps_of(loss)
        if self._pending_steps >= self.drain_every:
            self.drain()
        return self

    @property
    def pending(self):
        """Dispatched-but-unfetched loss (step) count."""
        return self._pending_steps

    @property
    def last(self):
        """Most recently DRAINED loss (no sync), or None."""
        return self.losses[-1] if self.losses else None

    def drain(self):
        """Fetch every pending loss in one host sync; returns the latest
        loss value. Horizon vectors flatten in append order, so the
        drained stream is the per-step loss sequence regardless of how
        the steps were dispatched."""
        if self._pending:
            vals = jax.device_get(self._pending)
            self.fetches += 1
            for v in vals:
                arr = np.asarray(v)
                if arr.ndim:
                    self.losses.extend(float(x) for x in arr)
                else:
                    self.losses.append(float(arr))
            self._pending = []
            self._pending_steps = 0
            if self.maxlen and len(self.losses) > self.maxlen:
                del self.losses[:len(self.losses) - self.maxlen]
        return self.last

    def __len__(self):
        return len(self.losses) + self._pending_steps


class Trainer:
    """Owns the sharded params/opt-state and the compiled step.

        trainer = Trainer(model, optimizer, loss_fn)   # loss_fn(model, batch)
        loss = trainer.step(batch)                      # batch: dict of arrays
    """

    def __init__(self, model, optimizer, loss_fn, mesh=None, donate=True,
                 grad_accum_steps=1, grad_transform=None,
                 batch_spec=("dp", "fsdp"), dp_overlap="off",
                 dp_overlap_buckets=2):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or get_mesh()
        self.grad_accum_steps = grad_accum_steps
        # dp grad-reduction dispatch: 'off' leaves the reduction to
        # GSPMD (one bulk all-reduce after the whole backward), 'bulk'
        # issues an explicit per-parameter-BUCKET shard_map psum, 'ring'
        # the chunked ascending ring (ops/overlap.py) — each bucket's
        # wire overlaps the optimizer update consuming the previous
        # bucket, and 'ring' is bit-identical to 'bulk' by the twin
        # pin. Targets dp meshes (other axes stay size 1 on jax 0.4.x,
        # where manual shard_map axes cannot be subset).
        if dp_overlap not in ("off", "bulk", "ring"):
            raise ValueError(f"dp_overlap must be 'off', 'bulk' or "
                             f"'ring', got {dp_overlap!r}")
        if dp_overlap != "off" and grad_transform is not None:
            raise ValueError("dp_overlap decomposes the grad reduction "
                             "per bucket; grad_transform expects the "
                             "whole tree — use one or the other")
        self.dp_overlap = dp_overlap
        self.dp_overlap_buckets = int(dp_overlap_buckets)
        # grad_transform(grads, state) -> (grads, state): gradient
        # compression/filtering between backward and the optimizer (DGC
        # error-feedback sparsification, bf16 cast, custom clipping) —
        # reference fleet meta_optimizers dgc/fp16_allreduce. State (e.g.
        # DGC residuals) is carried inside the compiled step, donated like
        # optimizer slots.
        self.grad_transform = grad_transform
        self._plan = plan_shardings(model, self.mesh)

        trainable, consts = {}, {}
        for name, p in model.named_parameters():
            v = jax.device_put(p._value, self._plan[name])
            (consts if p.stop_gradient else trainable)[name] = v
        for name, b in model.named_buffers():
            consts[name] = jax.device_put(b._value, self._plan[name])
        # per-step RNG salt rides consts so stochastic layers (dropout,
        # noisy MoE gates) draw FRESH randomness every compiled step
        # (framework.random.traced_salt); load_state_pytree ignores it.
        # Mesh-placed like every other const so the whole consts tree has
        # one device assignment (required for the in_shardings step below)
        consts[_RNG_STEP] = jax.device_put(
            jnp.zeros((), jnp.uint32),
            NamedSharding(self.mesh, PartitionSpec()))
        self.params = trainable
        self.consts = consts
        # slots inherit param shardings: zeros_like under jit keeps sharding
        self.opt_state = self._mesh_place(
            jax.jit(optimizer.init_state_pytree)(self.params))
        if self.grad_transform is not None and \
                hasattr(self.grad_transform, "init_state"):
            self.gt_state = self._mesh_place(
                jax.jit(self.grad_transform.init_state)(self.params))
        else:
            self.gt_state = None
        self._donate = donate
        self._step_fn = self._build(donate)
        self._host_step = 0
        # batch placement: precomputed NamedSharding pytrees + specialized
        # compiled steps, keyed by the batch's (structure, shapes, dtypes)
        # signature. The specialized step pins the batch argument's
        # in_shardings, so the compiled program expects the batch already
        # laid out over the data axes — no replicate-then-reshard inside
        # jit, and host-numpy vs device-resident feeds share ONE program.
        self._batch_spec = tuple(batch_spec)
        self._batch_shardings = {}
        self._placed_steps = {}
        # fused multi-step programs, keyed by the STACKED batch signature
        # (which encodes the horizon length N in the leading dim)
        self._placed_multis = {}
        # FLIGHT RECORDER (serving.trace.FlightRecorder, shared schema
        # with the serving engines): off by default — attach_recorder
        # turns step_multi horizons into tick records with predicted
        # vs measured drift accounting. Every hook is a dead
        # `if self.recorder is not None` branch.
        self.recorder = None
        self._rec_predicted_step_s = None
        self._rec_predicted_serial_s = None
        self._rec_last_t = None
        _LIVE_TRAINERS.add(self)

    def attach_recorder(self, recorder, predicted_step_s=None,
                        predicted_serial_step_s=None):
        """Attach a `serving.trace.FlightRecorder` (or True for a
        default one): every `step_multi` horizon records a "train"
        tick — N steps, measured dispatch-to-dispatch wall seconds,
        and (when `predicted_step_s` is given, normally
        `cost_model.roofline_step_time(...).step_s` or the schedule
        pass's overlap-aware `overlap_step_s`) the roofline-predicted
        horizon cost, feeding the same drift ledger the serving
        engines use (`ROOFLINE-DRIFT` / `debug.serving_report`).
        `predicted_serial_step_s` (normally the schedule pass's
        `serial_step_s` — the compute+wire sum with nothing
        overlapped) stamps the serial band next to it, so an
        over-drifting shape gets the serialized-vs-mispriced verdict
        instead of a blanket "re-fit the legs". Returns the
        recorder."""
        if recorder is True:
            from ..serving.trace import FlightRecorder
            recorder = FlightRecorder()
        self.recorder = recorder
        self._rec_predicted_step_s = predicted_step_s
        self._rec_predicted_serial_s = predicted_serial_step_s
        self._rec_last_t = None
        if recorder is not None:
            recorder.meta.update(engine="Trainer",
                                 donate=bool(self._donate))
        return recorder

    def mark_recorder_idle(self):
        """Tell the recorder the loop is about to do non-training host
        work (eval pass, checkpoint save, data stall): the next
        horizon's dispatch-to-dispatch gap would book that pause as
        horizon time, so it is measured from the dispatch call instead
        and kept OUT of the drift ledger — the trainer's rendering of
        the serving engines' polluted-window exclusion."""
        self._rec_last_t = None

    def _mesh_place(self, tree):
        """Replicate any single-device leaf onto the full mesh. A state
        leaf that depends on NO parameter (e.g. a stateless optimizer's
        bare step counter) gets its params pruned from the init jit, which
        then executes on one device — mixing that with mesh-committed
        params in a single step program is an invalid device assignment."""
        if self.mesh.devices.size <= 1:
            return tree
        rep = NamedSharding(self.mesh, PartitionSpec())

        def fix(v):
            sh = getattr(v, "sharding", None)
            if sh is not None and getattr(sh, "num_devices", 1) == 1:
                return jax.device_put(v, rep)
            return v
        return jax.tree_util.tree_map(fix, tree)

    def _build_body(self):
        """The ONE single-step body: (params, opt_state, gt_state,
        consts, lr, batch) -> (params, opt_state, gt_state, consts,
        fp32 loss). `step()`'s jit wraps it directly and every tick of
        `step_multi`'s fused scan runs it under the scan carry — the
        same closure, so the two paths cannot drift (the serving
        `_forward_tokens` pattern). Callers apply the per-step RNG salt
        (`traced_salt`) themselves: the jit wrapper once, the scan once
        per tick with the carried counter."""
        model, optimizer, loss_fn = self.model, self.optimizer, self.loss_fn
        accum = self.grad_accum_steps

        compute_loss = make_compute_loss(model, loss_fn)

        grad_transform = self.grad_transform

        def _local_grads(params, consts, batch):
            if accum <= 1:
                (loss_v, buf_updates), grads = jax.value_and_grad(
                    compute_loss, has_aux=True)(params, consts, batch)
            else:
                # gradient merge (reference DistributedStrategy.gradient_merge):
                # microbatch scan accumulating mean grads before ONE update
                micro = jax.tree_util.tree_map(
                    lambda v: v.reshape((accum, v.shape[0] // accum) + v.shape[1:]),
                    batch)

                def body(carry, mb):
                    loss_acc, grad_acc = carry
                    (lv, bu), g = jax.value_and_grad(
                        compute_loss, has_aux=True)(params, consts, mb)
                    grad_acc = jax.tree_util.tree_map(jnp.add, grad_acc, g)
                    return (loss_acc + lv, grad_acc), bu

                zeros = jax.tree_util.tree_map(
                    lambda v: jnp.zeros(v.shape, jnp.float32), params)
                (loss_sum, grad_sum), bus = jax.lax.scan(
                    body, (jnp.zeros((), jnp.float32), zeros), micro)
                loss_v = loss_sum / accum
                grads = jax.tree_util.tree_map(lambda g: g / accum, grad_sum)
                # per-microbatch stat updates all start from the same consts;
                # carry the last microbatch's
                buf_updates = jax.tree_util.tree_map(lambda v: v[-1], bus)
            return loss_v, grads, buf_updates

        def _inner(params, opt_state, gt_state, consts, lr, batch):
            loss_v, grads, buf_updates = _local_grads(params, consts, batch)
            if grad_transform is not None:
                grads, gt_state = grad_transform(grads, gt_state)
            new_params, new_state = optimizer.apply_gradients_pytree(
                params, grads, opt_state, lr)
            new_consts = {**consts, **buf_updates}
            if _RNG_STEP in consts:
                new_consts[_RNG_STEP] = consts[_RNG_STEP] + 1
            return new_params, new_state, gt_state, new_consts, loss_v

        dp = int(self.mesh.shape.get("dp", 1))
        if self.dp_overlap == "off" or dp <= 1:
            return _inner

        # dp-overlap path: per-shard grads under an explicit shard_map
        # over 'dp', the grad reduction decomposed per parameter BUCKET
        # and interleaved with the optimizer update consuming each
        # bucket — bucket b's ring steps share no data edge with bucket
        # b-1's update dots, so the two-stream schedule (and the chip)
        # overlap them. The local loss/grads are per-shard MEANS, so the
        # global ones are sum/dp — reduced with the same ascending fold
        # ('ring') or bulk psum ('bulk'), bit-identical by the twin pin.
        from jax.sharding import PartitionSpec as P
        from ..ops.overlap import chunked_all_reduce
        from .mesh import compat_shard_map
        impl = "ring" if self.dp_overlap == "ring" else "bulk"
        n_buckets = max(1, self.dp_overlap_buckets)
        mesh = self.mesh

        def _shard_body(params, opt_state, gt_state, consts, lr, batch):
            loss_v, grads, buf_updates = _local_grads(params, consts, batch)
            names = sorted(grads)
            nb = max(1, min(n_buckets, len(names)))
            bounds = [(i * len(names)) // nb for i in range(nb + 1)]
            new_params, new_slots = {}, {}
            new_step = opt_state["step"]
            for i in range(nb):
                bucket = names[bounds[i]:bounds[i + 1]]
                if not bucket:
                    continue
                gb = {n: chunked_all_reduce(grads[n], "dp", impl=impl) / dp
                      for n in bucket}
                up, us = optimizer.apply_gradients_pytree(
                    {n: params[n] for n in bucket}, gb,
                    {"slots": {n: opt_state["slots"][n] for n in bucket},
                     "step": opt_state["step"]}, lr)
                new_params.update(up)
                new_slots.update(us["slots"])
                new_step = us["step"]
            loss_v = chunked_all_reduce(loss_v, "dp", impl=impl) / dp
            buf_updates = jax.tree_util.tree_map(
                lambda v: (chunked_all_reduce(v, "dp", impl=impl) / dp
                           if jnp.issubdtype(jnp.asarray(v).dtype,
                                             jnp.floating) else v),
                buf_updates)
            new_consts = {**consts, **buf_updates}
            if _RNG_STEP in consts:
                new_consts[_RNG_STEP] = consts[_RNG_STEP] + 1
            new_state = {"slots": new_slots, "step": new_step}
            return new_params, new_state, gt_state, new_consts, loss_v

        def _inner_dp(params, opt_state, gt_state, consts, lr, batch):
            return compat_shard_map(
                _shard_body, mesh,
                in_specs=(P(), P(), P(), P(), P(), P("dp")),
                out_specs=(P(), P(), P(), P(), P()),
                axis_names={"dp"}, check=False)(
                params, opt_state, gt_state, consts, lr, batch)

        return _inner_dp

    def _build(self, donate, in_shardings=None):
        _inner = self._build_body()

        def step(params, opt_state, gt_state, consts, lr, batch):
            from ..framework.random import traced_salt
            with traced_salt(consts.get(_RNG_STEP)):
                return _inner(params, opt_state, gt_state, consts, lr, batch)

        kwargs = {}
        if in_shardings is not None:
            kwargs["in_shardings"] = in_shardings
            # pin outputs to the same layout: step N's outputs then carry
            # shardings EQUAL to step N+1's pinned inputs, so the dispatch
            # cache hits from the first step onward (without this, the
            # first step's GSPMD-typed outputs force one extra compile)
            state_sh = in_shardings[:4]
            kwargs["out_shardings"] = state_sh + (
                NamedSharding(self.mesh, PartitionSpec()),)   # fp32 loss
        return jax.jit(step, donate_argnums=(0, 1, 2, 3) if donate else (),
                       **kwargs)

    def _build_multi(self, donate, in_shardings=None):
        """N train steps fused into ONE jitted lax.scan over a
        leading-stacked batch pytree ([N, ...] leaves) and an [N] lr
        vector, params/opt-state/grad-transform-state/consts threaded
        through the donated carry. The scan body is `_build_body()` —
        the SAME closure `step()` compiles — so fused and per-step loops
        cannot drift. Returns the length-N loss vector UNFETCHED: host
        contact happens only when the caller drains it."""
        _inner = self._build_body()

        def multi_step(params, opt_state, gt_state, consts, lrs, batches):
            from ..framework.random import traced_salt

            def tick(carry, xs):
                params, opt_state, gt_state, consts = carry
                lr, batch = xs
                with traced_salt(consts.get(_RNG_STEP)):
                    p, o, g, c, loss = _inner(params, opt_state, gt_state,
                                              consts, lr, batch)
                return (p, o, g, c), loss

            carry = (params, opt_state, gt_state, consts)
            (params, opt_state, gt_state, consts), losses = jax.lax.scan(
                tick, carry, (lrs, batches))
            return params, opt_state, gt_state, consts, losses

        kwargs = {}
        if in_shardings is not None:
            kwargs["in_shardings"] = in_shardings
            state_sh = in_shardings[:4]
            kwargs["out_shardings"] = state_sh + (
                NamedSharding(self.mesh, PartitionSpec()),)  # [N] f32 losses
        return jax.jit(multi_step,
                       donate_argnums=(0, 1, 2, 3) if donate else (),
                       **kwargs)

    # -- batch placement ----------------------------------------------------

    def place_batch(self, batch):
        """Normalize a batch onto the mesh with the precomputed GSPMD batch
        sharding (leading dim over the data axes). Host numpy / Tensor
        leaves are device_put — sharded and committed; already-resident
        leaves (`io.DeviceLoader` / `shard_batch` output) pass through
        untouched, since device_put with a matching sharding is a no-op.
        Every feed path therefore reaches the compiled step with identical
        input shardings: ONE compilation, zero per-step reshards."""
        from ..io.prefetch import (_leaf_arrays, batch_shardings,
                                   batch_signature)
        arrays = _leaf_arrays(batch)
        sig = batch_signature(arrays)
        sh = self._batch_shardings.get(sig)
        if sh is None:
            sh = batch_shardings(arrays, self.mesh, self._batch_spec)
            self._batch_shardings[sig] = sh
        return jax.device_put(arrays, sh), sig, sh

    def _placed_step(self, sig, batch_sh):
        """Compiled step specialized to one batch signature, with every
        argument's sharding pinned via in_shardings (batch included — the
        program is compiled to CONSUME the sharded batch, not to reshard a
        replicated one). Falls back to the generic jit when a sharding
        can't be derived (exotic state pytrees)."""
        fn = self._placed_steps.get(sig)
        if fn is None:
            try:
                leaf_sh = lambda v: v.sharding  # noqa: E731
                in_sh = (
                    jax.tree_util.tree_map(leaf_sh, self.params),
                    jax.tree_util.tree_map(leaf_sh, self.opt_state),
                    (jax.tree_util.tree_map(leaf_sh, self.gt_state)
                     if self.gt_state is not None else None),
                    jax.tree_util.tree_map(leaf_sh, self.consts),
                    NamedSharding(self.mesh, PartitionSpec()),   # lr scalar
                    batch_sh)
                fn = self._build(self._donate, in_shardings=in_sh)
            except (AttributeError, TypeError) as e:
                # a state leaf with no .sharding (exotic pytree): fall
                # back to the unpinned jit — LOUDLY, because the fallback
                # re-introduces the in-jit batch reshard this class
                # exists to avoid
                import warnings
                warnings.warn(
                    "Trainer: could not derive in_shardings for the "
                    f"compiled step ({e!r}); falling back to the "
                    "unpinned jit (batch resharding inside the step)")
                fn = self._step_fn
            self._placed_steps[sig] = fn
        return fn

    def place_horizon(self, batches):
        """Normalize a training horizon onto the mesh: `batches` is
        either a list/tuple of N per-step batch pytrees (host numpy or
        device-resident — stacked here, `io.prefetch.stack_batches`) or
        an already leading-stacked pytree (`DeviceLoader.stack(n)`
        output). Leaves land as [N, B, ...] arrays with the scan dim
        replicated and the per-step batch dim sharded over the data axes
        — the layout the fused scan pins as its batch in_shardings, so
        every feed path hits ONE compiled program per (N, signature)."""
        from ..io.prefetch import (_leaf_arrays, batch_signature,
                                   horizon_shardings, stack_batches)
        if isinstance(batches, (list, tuple)):
            arrays = stack_batches(batches)
        else:
            arrays = _leaf_arrays(batches)
        sig = ("multi", batch_signature(arrays))
        sh = self._batch_shardings.get(sig)
        if sh is None:
            sh = horizon_shardings(arrays, self.mesh, self._batch_spec)
            self._batch_shardings[sig] = sh
        return jax.device_put(arrays, sh), sig, sh

    def _placed_multi(self, sig, horizon_sh):
        """Compiled fused-scan step specialized to one stacked-batch
        signature (the horizon length N rides in the signature's leading
        dim), shardings pinned like `_placed_step` (same fallback
        contract when a state leaf has no derivable sharding)."""
        fn = self._placed_multis.get(sig)
        if fn is None:
            try:
                leaf_sh = lambda v: v.sharding  # noqa: E731
                rep = NamedSharding(self.mesh, PartitionSpec())
                in_sh = (
                    jax.tree_util.tree_map(leaf_sh, self.params),
                    jax.tree_util.tree_map(leaf_sh, self.opt_state),
                    (jax.tree_util.tree_map(leaf_sh, self.gt_state)
                     if self.gt_state is not None else None),
                    jax.tree_util.tree_map(leaf_sh, self.consts),
                    rep,                                 # [N] lr vector
                    horizon_sh)
                fn = self._build_multi(self._donate, in_shardings=in_sh)
            except (AttributeError, TypeError) as e:
                import warnings
                warnings.warn(
                    "Trainer: could not derive in_shardings for the "
                    f"fused multi-step program ({e!r}); falling back to "
                    "the unpinned jit (batch resharding inside the scan)")
                fn = self._build_multi(self._donate)
            self._placed_multis[sig] = fn
        return fn

    def _horizon_lrs(self, n):
        """Precompute the next `n` per-step learning rates HOST-SIDE by
        advancing the real scheduler — `get_lr()` then `sched.step()`
        per tick, exactly what n calls of `step()` would do — so
        warmup/decay boundaries falling MID-horizon feed the scan the
        same lr sequence the per-step loop would see."""
        sched = self.optimizer._lr_scheduler
        lrs = []
        for _ in range(int(n)):
            lrs.append(float(self.optimizer.get_lr()))
            if sched is not None:
                sched.step()
        return np.asarray(lrs, np.float32)

    def step_multi(self, batches, lrs=None):
        """Dispatch N train steps as ONE compiled `lax.scan`
        (`_build_multi`): one host dispatch per horizon instead of per
        step, donated state threaded through the carry, per-step lrs
        precomputed host-side (default: the optimizer's scheduler,
        advanced exactly as N `step()` calls would). `batches` is a
        list of N batch pytrees or a leading-stacked pytree
        (`DeviceLoader.stack(n)`). NON-BLOCKING: returns the [N] fp32
        loss vector unfetched — drain it through a `LossBuffer` (vector
        appends are supported) so host contact stays at horizon
        boundaries."""
        arrays, sig, horizon_sh = self.place_horizon(batches)
        n = jax.tree_util.tree_leaves(arrays)[0].shape[0]
        if lrs is None:
            lrs = self._horizon_lrs(n)
        else:
            lrs = np.asarray(lrs, np.float32)
            if lrs.shape != (n,):
                raise ValueError(
                    f"step_multi: lrs shape {lrs.shape} != ({n},)")
            # parity with step(batch, lr=x), which advances the
            # scheduler even under an explicit lr: N explicit-lr steps
            # leave the scheduler N positions further along
            sched = self.optimizer._lr_scheduler
            if sched is not None:
                for _ in range(int(n)):
                    sched.step()
        t0 = time.perf_counter() if self.recorder is not None else None
        # a signature never dispatched before will compile inside this
        # window — a pollution source the drift ledger must skip, like
        # the first horizon (the memo is the compile's proxy: first
        # call per signature pays the XLA compile)
        warm_sig = sig in self._placed_multis
        fn = self._placed_multi(sig, horizon_sh)
        (self.params, self.opt_state, self.gt_state, self.consts,
         losses) = fn(
            self.params, self.opt_state, self.gt_state, self.consts,
            jnp.asarray(lrs), arrays)
        # horizon-aware step accounting: state()/load_state round-trip
        # the TRUE device step count, not the host dispatch count
        self._host_step += int(n)
        if self.recorder is not None:
            # dispatch is NON-blocking, so this call's own wall time is
            # not the horizon's: in a steady-state loop the dispatch-to-
            # dispatch gap is (the next dispatch blocks on the donated
            # carry), so measure that. The FIRST horizon after attach or
            # mark_recorder_idle() has no previous dispatch — its call
            # wall is recorded but kept out of the drift ledger (cold
            # compiles and host pauses are pollution, the same
            # exclusion the serving engines apply to prefill windows)
            now = time.perf_counter()
            steady = self._rec_last_t is not None and warm_sig
            # the tick's chrome slice must span the window it measured:
            # steady ticks start at the PREVIOUS dispatch, not this one
            start = self._rec_last_t if self._rec_last_t is not None \
                else t0
            measured = now - start
            self._rec_last_t = now
            pred = self._rec_predicted_step_s
            serial = self._rec_predicted_serial_s
            self.recorder.tick(
                "train", ("train", int(n)), measured, ts=start,
                predicted_s=(pred * int(n)) if pred else None,
                predicted_serial_s=(serial * int(n)) if serial else None,
                drift=steady, k=int(n), decode_rows=0, prefill_rows=0)
        return losses

    def lower_step(self, batch, lr=0.0):
        """Lower the SAME specialized program `step()` dispatches for this
        batch's signature (in/out shardings pinned) — the honest target
        for static analysis, HLO pins, and memory audits. `_step_fn` (the
        unspecialized jit) exists only as the fallback for state pytrees
        whose shardings can't be derived; don't analyze that one."""
        arrays, sig, batch_sh = self.place_batch(batch)
        fn = self._placed_step(sig, batch_sh)
        return fn.lower(self.params, self.opt_state, self.gt_state,
                        self.consts, lr, arrays)

    def analysis_program(self, batch, lr=0.0, n=None):
        """Graph Doctor view of the SAME specialized step `step()`
        dispatches: one trace yields the StableHLO text AND jaxpr, plus
        per-argument capture of role (param / opt_state / gt_state /
        const / lr / batch), sharding (shard count per leaf, from the
        pinned in_shardings), and donation — everything the memory and
        sharding passes need for per-device peak-HBM estimation and
        replication lint that the HLO text alone can't recover.

        With `n` the FUSED multi-step program (`step_multi`, N ticks in
        one lax.scan over `batch` stacked N deep) is traced instead —
        the HOST-SYNC-TRAIN rule checks it for host transfers, donated
        carry, and a real device loop."""
        from ..analysis.lowering import LoweredProgram, tree_arg_infos
        if n:
            stacked = [batch] * int(n)
            arrays, sig, batch_sh = self.place_horizon(stacked)
            fn = self._placed_multi(sig, batch_sh)
            lrs = jnp.full((int(n),), float(lr), jnp.float32)
            traced = fn.trace(self.params, self.opt_state, self.gt_state,
                              self.consts, lrs, arrays)
            lr_arg, name = lrs, f"train_multi_n{int(n)}"
        else:
            arrays, sig, batch_sh = self.place_batch(batch)
            fn = self._placed_step(sig, batch_sh)
            traced = fn.trace(self.params, self.opt_state, self.gt_state,
                              self.consts, lr, arrays)
            lr_arg, name = lr, "train_step"
        donate = bool(self._donate)
        infos = tree_arg_infos(self.params, "param", donated=donate)
        infos += tree_arg_infos(self.opt_state, "opt_state",
                                donated=donate)
        if self.gt_state is not None:
            infos += tree_arg_infos(self.gt_state, "gt_state",
                                    donated=donate)
        infos += tree_arg_infos(self.consts, "const", donated=donate)
        infos += tree_arg_infos(lr_arg, "lr")
        infos += tree_arg_infos(arrays, "batch", shardings=batch_sh)
        return LoweredProgram(traced.lower().as_text(),
                              jaxpr=traced.jaxpr, name=name,
                              arg_infos=infos)

    def suggest_config(self, batch, hbm_budget=None, **kw):
        """Static config advice for THIS trainer: candidate microbatch
        sizes x remat policies ranked by roofline-predicted throughput,
        HBM-infeasible points pruned — one CPU trace per batch size, a
        what-if liveness replay per policy, zero compiles, zero device
        work (analysis/autotune.py). Returns an AutotuneReport whose
        `.best` names the config to measure first and whose `.advice`
        lines read "remat=dots: peak X → Y per device, +Z% recompute
        FLOPs"."""
        from ..analysis.autotune import autotune
        return autotune(self, batch, hbm_budget=hbm_budget, **kw)

    def step(self, batch, lr=None):
        """Dispatch one compiled step. NON-BLOCKING: the returned loss is
        an unfetched device array — `float()` it only when you must (or
        batch the syncs through a `LossBuffer`), so dispatch of step N+1
        overlaps step N's compute."""
        lr = self.optimizer.get_lr() if lr is None else lr
        batch, sig, batch_sh = self.place_batch(batch)
        step_fn = self._placed_step(sig, batch_sh)
        (self.params, self.opt_state, self.gt_state, self.consts,
         loss) = step_fn(
            self.params, self.opt_state, self.gt_state, self.consts, lr, batch)
        sched = self.optimizer._lr_scheduler
        if sched is not None:
            sched.step()
        self._host_step += 1
        return loss

    def sync_to_model(self):
        """Copy trained params AND accumulated buffers (BN running stats)
        back into the Layer tree (for save/eval)."""
        load_state_pytree(self.model, {**self.consts, **self.params})

    def state(self):
        """Host-side snapshot (numpy leaves). Device buffers are donated
        into the next step(), so a live-array snapshot would be invalidated
        the moment training continues."""
        s = {"params": self.params, "opt_state": self.opt_state,
             "step": self._host_step}
        if self.gt_state is not None:   # grad-transform residuals (DGC u/v)
            s["gt_state"] = self.gt_state
        return jax.tree_util.tree_map(
            lambda v: jax.device_get(v) if hasattr(v, "dtype") else v, s)

    def load_state(self, state):
        # EVERY restored leaf is device_put onto the current trainer's
        # template sharding — params AND opt/grad-transform state. The
        # old code handed opt_state to the compiled step as raw numpy:
        # wrong placement semantics under a resharded mesh, and feeding
        # numpy into a DONATED argument of a deserialized (persistent-
        # cache-hit) executable mis-executes outright — silently wrong
        # resume losses, then heap corruption (the
        # tests/test_cross_mesh_resume.py crash that killed whole suite
        # runs).
        def put(t, v):
            if not hasattr(v, "dtype"):
                return v
            sh = getattr(t, "sharding", None)
            if sh is not None and getattr(sh, "num_devices", 1) > 1:
                return jax.device_put(v, sh)
            # template leaf is default-placed (eager opt-state init):
            # an uncommitted device array lets dispatch place it, while
            # still never handing raw HOST memory to a donated argument
            return jnp.asarray(v)

        def put_tree(template, tree):
            return jax.tree_util.tree_map(put, template, tree)

        self.params = put_tree(self.params, state["params"])
        self.opt_state = put_tree(self.opt_state, state["opt_state"])
        if "gt_state" in state:
            self.gt_state = put_tree(self.gt_state, state["gt_state"])
        self._host_step = int(state.get("step", 0))
        # restored leaves may carry different shardings (resharded mesh,
        # default-placed opt state): drop the specialized steps so the next
        # step()/step_multi() re-derives in_shardings from the actual arrays
        self._placed_steps = {}
        self._placed_multis = {}
