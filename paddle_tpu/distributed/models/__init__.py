"""Reference python/paddle/distributed/models/ — model-specific
distributed helpers (currently MoE routing utilities)."""
from . import moe  # noqa: F401

__all__ = ["moe"]
