"""MoE routing utilities — reference
python/paddle/distributed/models/moe/utils.py:22-230.

The reference binds five CUDA ops (number_count, assign_pos,
random_routing, limit_by_capacity, prune_gate_by_capacity); here each
is a vectorized jnp computation — bincount / stable-argsort / cumsum /
one-hot-cumsum shapes that XLA lowers to a handful of fused kernels, no
scalar loops — so they jit cleanly on TPU. Semantics (including the
within-expert token ordering of assign_pos and the worker-greedy
capacity split of limit_by_capacity) match the reference docstring
examples bit-for-bit; each is pinned by tests/test_moe_routing_utils.py.
"""
import jax.numpy as jnp

from ....framework.core import apply_op

__all__ = []


def _number_count(numbers, upper_range):
    """Per-expert token count from gate indices (reference utils.py:22):
    _number_count([[0,2],[0,2]], 6) == [2,0,2,0,0,0]. Entries outside
    [0, upper_range) (e.g. -1 pruned tokens) are not counted."""
    def f(n):
        flat = n.reshape(-1)
        valid = (flat >= 0) & (flat < upper_range)
        counts = jnp.bincount(jnp.where(valid, flat, 0),
                              weights=valid.astype(jnp.float32),
                              length=upper_range)
        return counts.astype(n.dtype)
    return apply_op(f, numbers)


def _assign_pos(x, cum_count):
    """Token order for expert-contiguous dispatch (reference utils.py:62):
    out[slot] is the token index occupying that slot when tokens are
    grouped by expert. The reference CUDA kernel fills each expert's
    slots back-to-front while scanning tokens forward, so later tokens
    take earlier slots within an expert — reproduced here with a single
    stable argsort on (expert, -token) keys:
    _assign_pos([[0,2],[0,2]], cumsum([2,0,2,0])) == [2,0,3,1]."""
    import numpy as np
    cc_host = cum_count._value if hasattr(cum_count, "_value") else cum_count
    total = int(np.asarray(cc_host).reshape(-1)[-1])

    def f(xv, cc):
        flat = xv.reshape(-1).astype(jnp.int32)
        n = flat.shape[0]
        tok = jnp.arange(n, dtype=jnp.int32)
        # int32-safe keys (x64 is disabled on TPU): requires
        # n_tokens * (n_experts+1) < 2^31, true for any per-step dispatch.
        # Invalid (negative) gates get the largest representable expert id
        # so they sort past every real one.
        big = (jnp.iinfo(jnp.int32).max - n) // n
        expert = jnp.where(flat >= 0, flat, big)
        order = jnp.argsort(expert * n + (n - 1 - tok))
        return order[:total].astype(cc.dtype)
    return apply_op(f, x, cum_count)


def _random_routing(topk_idx, topk_value, prob, topk=2):
    """Stochastically drop the 2nd expert (reference utils.py:113):
    out[i][topk-1] = -1 where topk * value[i][topk-1] < prob[i]."""
    if topk != 2:
        raise RuntimeError("only topk=2 is supported now")

    def f(idx, val, p):
        drop = topk * val[:, topk - 1] < p
        col = jnp.where(drop, jnp.asarray(-1, idx.dtype), idx[:, topk - 1])
        return idx.at[:, topk - 1].set(col)
    return apply_op(f, topk_idx, topk_value, prob)


def _limit_by_capacity(expert_count, capacity, n_worker):
    """Clamp per-(worker, expert) counts so each expert's total across
    workers fits its capacity, granted to workers in rank order
    (reference utils.py:138): _limit_by_capacity([1,2,2,8,3,6], [5,5,5],
    2) == [1,2,2,4,3,3]."""
    def f(ec, cap):
        # int32 math: counts are token counts, far below 2^31, and x64
        # is disabled on TPU (int64 would warn + truncate anyway)
        n_expert = ec.size // n_worker
        grid = ec.reshape(n_worker, n_expert).astype(jnp.int32)
        cum = jnp.cumsum(grid, axis=0)
        capped = jnp.minimum(cum, cap.astype(jnp.int32)[None, :])
        prev = jnp.concatenate(
            [jnp.zeros((1, n_expert), jnp.int32), capped[:-1]], axis=0)
        return (capped - prev).reshape(-1).astype(ec.dtype)
    return apply_op(f, expert_count, capacity)


def _prune_gate_by_capacity(gate_idx, expert_count, n_expert, n_worker):
    """Replace over-capacity gate assignments with -1, keeping each
    expert's first expert_count[e] tokens in order (reference
    utils.py:181): _prune_gate_by_capacity([1,3,3,3,3,2,1,1],
    [0,3,1,3,0,0,0,0], 4, 2) == [1,3,3,3,-1,2,1,1]."""
    def f(g, ec):
        total_experts = n_expert * n_worker
        flat = g.reshape(-1)   # [T, k] topk indices prune in row-major order
        oh = (flat[:, None] == jnp.arange(total_experts)[None, :])
        occ = (jnp.cumsum(oh, axis=0) * oh).sum(-1) - 1  # 0-based occurrence
        keep = occ < ec[jnp.clip(flat, 0, total_experts - 1)]
        return jnp.where(keep & (flat >= 0), flat, -1).astype(g.dtype) \
                  .reshape(g.shape)
    return apply_op(f, gate_idx, expert_count)
