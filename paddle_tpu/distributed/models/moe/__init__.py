"""Reference python/paddle/distributed/models/moe/__init__.py — the
routing-utility namespace a migrating Paddle user imports from. The MoE
model family itself lives in paddle_tpu.models.moe / incubate.moe."""
from . import utils  # noqa: F401

__all__ = ["utils"]
