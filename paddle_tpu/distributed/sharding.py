"""group_sharded (ZeRO) API — reference python/paddle/distributed/sharding/
group_sharded.py (stage 1/2/3 optimizer-state/grad/param sharding).

GSPMD equivalence: sharding the params over 'fsdp' gives stage-3 semantics
(params gathered on use, grads reduce-scattered); optimizer slots inherit the
param sharding, which covers stages 1/2 automatically. This wrapper annotates
+ places params and returns the (model, optimizer, scaler) triple like the
reference API.
"""
from .mesh import build_mesh, get_mesh
from .sharding_utils import shard_params

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def group_sharded_parallel(model, optimizer, level="p_g_os", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False):
    mesh = get_mesh(create_default=False)
    if mesh is None:
        import jax
        build_mesh(fsdp=len(jax.devices()))
    elif mesh.shape.get("fsdp", 1) == 1:
        # An app-built mesh exists but has no fsdp axis: replacing it would
        # invalidate placements already made against it, so keep it and warn.
        import warnings
        warnings.warn(
            "group_sharded_parallel: current mesh has fsdp=1; parameters "
            "stay replicated. Call build_mesh(fsdp=N) before "
            "group_sharded_parallel to shard over N devices.")
    shard_params(model)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework.io import save
    save(model.state_dict(), output + ".pdparams")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
