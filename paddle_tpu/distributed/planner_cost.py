"""Cost-model mesh planner — reference
python/paddle/distributed/auto_parallel/cost_model.py + planner.py
(MCMC search over partitions with per-op cost estimates) and
tuner/parallel_tuner.py.

TPU-first rendering: XLA owns per-op placement, so what's worth searching
is the MESH SHAPE — how many chips go to dp / fsdp / tp / pp. This module
scores every factorization of the chip count with a roofline model in the
"How to Scale Your Model" style:

  step_time = max(compute, memory_bw) + collective time on each axis
  compute   = model FLOPs / (chips * peak_flops * mfu_ceiling)
  dp        - grad all-reduce:    2 * P * (dp-1)/dp bytes over ICI
  fsdp      - param all-gather + grad reduce-scatter: 3 * P * (f-1)/f
  tp        - per-layer activation all-reduces: ~4 * B * S * H * (tp-1)/tp
  pp        - bubble factor (S-1)/(M+S-1) stretches compute

plus an HBM feasibility check (params + optimizer state + activations must
fit per chip, with fsdp/tp dividing the static bytes and remat shrinking
activations). Returns ranked PlanCandidates; `Planner.search` is the
public entry.

The model constants are deliberately explicit and overridable — the point
is transparent arithmetic you can check against a profile, not a learned
black box.
"""
import dataclasses
import itertools

__all__ = ["ClusterSpec", "ModelStats", "PlanCandidate", "search_mesh",
           "gpt_stats"]


@dataclasses.dataclass
class ClusterSpec:
    """Hardware description (defaults: one v5e pod slice)."""
    n_devices: int = 8
    peak_flops: float = 197e12          # bf16 / chip
    hbm_bytes: float = 16e9             # / chip
    ici_bw: float = 45e9                # bytes/s per link direction (v5e)
    dcn_bw: float = 6.25e9              # bytes/s per host NIC
    devices_per_host: int = 8
    mfu_ceiling: float = 0.65           # best-case single-chip efficiency


@dataclasses.dataclass
class ModelStats:
    """What the cost model needs to know about one training step."""
    flops_per_step: float               # fwd+bwd total
    param_bytes: float                  # model weights (one copy)
    optim_bytes: float                  # optimizer slots (adam: 2x params)
    act_bytes_per_layer: float          # activations, full batch, one layer
    n_layers: int
    batch: int                          # global batch (samples)
    seq_len: int = 1
    hidden: int = 1
    dtype_bytes: int = 2

    def act_bytes(self, remat=True):
        # with per-layer remat only layer BOUNDARIES stay live
        keep = 1.0 if remat else 8.0
        return self.act_bytes_per_layer * self.n_layers * keep


def gpt_stats(n_params, n_layers, hidden, batch, seq_len, dtype_bytes=2,
              adam=True):
    """ModelStats for a GPT-family decoder via the 6·N·T heuristic."""
    tokens = batch * seq_len
    return ModelStats(
        flops_per_step=6.0 * n_params * tokens,
        param_bytes=float(n_params) * dtype_bytes,
        optim_bytes=float(n_params) * dtype_bytes * (2 if adam else 1),
        act_bytes_per_layer=float(batch) * seq_len * hidden * dtype_bytes,
        n_layers=n_layers, batch=batch, seq_len=seq_len, hidden=hidden,
        dtype_bytes=dtype_bytes)


@dataclasses.dataclass
class PlanCandidate:
    axes: dict                          # {"dp": d, "fsdp": f, "tp": t, "pp": p}
    step_time: float                    # seconds (estimated)
    compute_time: float
    comm_time: float
    hbm_per_chip: float
    feasible: bool
    why: str = ""

    @property
    def mfu(self):
        return 0.0 if self.step_time == 0 else \
            self.compute_time / self.step_time


def _factorizations(n, axes=("dp", "fsdp", "tp", "pp")):
    """All ways to write n as a product over the axes (powers of the prime
    factorization; n_devices is 2^k on TPU slices, so this is small)."""
    def splits(n, k):
        if k == 1:
            yield (n,)
            return
        d = 1
        while d <= n:
            if n % d == 0:
                for rest in splits(n // d, k - 1):
                    yield (d,) + rest
            d += 1
    for combo in splits(n, len(axes)):
        yield dict(zip(axes, combo))


def _estimate(ax, stats, cluster, remat=True, microbatches=8):
    dp, f, tp, pp = ax["dp"], ax["fsdp"], ax["tp"], ax["pp"]
    n = dp * f * tp * pp
    P = stats.param_bytes

    # --- feasibility -----------------------------------------------------
    inf = float("inf")
    if (dp * f) > 1 and stats.batch % (dp * f):
        return PlanCandidate(dict(ax), inf, inf, 0.0, 0.0, False,
                             "batch not divisible by dp*fsdp")
    if stats.n_layers % pp:
        return PlanCandidate(dict(ax), inf, inf, 0.0, 0.0, False,
                             "layers not divisible by pp")
    shard = f * tp                       # static bytes divided by fsdp*tp
    static = (P + stats.optim_bytes) / shard / pp
    acts = stats.act_bytes(remat) / max(dp * f, 1) / tp / pp
    if pp > 1:                           # in-flight microbatch activations
        acts *= min(pp, microbatches)
    hbm = static + acts
    feasible = hbm <= cluster.hbm_bytes * 0.9   # runtime/jitter headroom

    # --- compute ---------------------------------------------------------
    compute = stats.flops_per_step / (n * cluster.peak_flops
                                      * cluster.mfu_ceiling)
    if pp > 1:                           # pipeline fill/drain bubble
        M = microbatches
        compute *= 1.0 + (pp - 1) / M

    # --- collectives -----------------------------------------------------
    # Axis-to-host mapping follows the mesh nesting convention (tp
    # innermost, then fsdp, dp, pp): an axis rides ICI only if its whole
    # span fits inside one host given everything nested inside it; the
    # first axis to straddle the host boundary (and everything outside
    # it) pays DCN bandwidth.
    span = {}
    cum = 1
    for a in ("tp", "fsdp", "dp", "pp"):
        cum *= ax[a]
        span[a] = cum

    def bw(axis):
        intra = span[axis] <= cluster.devices_per_host
        return cluster.ici_bw if intra else cluster.dcn_bw

    comm = 0.0
    if dp > 1:                           # grad all-reduce per step
        comm += 2.0 * (P / (f * tp * pp)) * (dp - 1) / dp / bw("dp")
    if f > 1:                            # ZeRO-3: all-gather + reduce-scatter
        comm += 3.0 * (P / (tp * pp)) * (f - 1) / f / bw("fsdp")
    if tp > 1:                           # 2 all-reduces of activations/layer
        act_layer = (stats.batch / max(dp * f, 1)) * stats.seq_len \
            * stats.hidden * stats.dtype_bytes
        comm += 4.0 * act_layer * stats.n_layers / pp * (tp - 1) / tp / bw("tp")
    if pp > 1:                           # boundary activation hops
        act_mb = (stats.batch / max(dp * f, 1)) / microbatches \
            * stats.seq_len * stats.hidden * stats.dtype_bytes
        comm += 2.0 * act_mb * microbatches * (pp - 1) / pp / bw("pp")

    return PlanCandidate(dict(ax), compute + comm, compute, comm, hbm,
                         feasible,
                         "" if feasible else "exceeds HBM headroom")


def search_mesh(stats, cluster=None, remat=True, microbatches=8, top_k=5):
    """Rank mesh factorizations by estimated step time. Infeasible
    candidates (HBM overflow, divisibility) sink to the bottom with
    `.why` explaining the rejection. Returns top_k PlanCandidates."""
    cluster = cluster or ClusterSpec()
    out = [_estimate(ax, stats, cluster, remat, microbatches)
           for ax in _factorizations(cluster.n_devices)]
    out.sort(key=lambda c: (not c.feasible, c.step_time))
    return out[:top_k]
