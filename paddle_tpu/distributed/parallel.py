"""init_parallel_env / DataParallel — reference python/paddle/distributed/parallel.py."""
import os

import jax

from ..nn.layer_base import Layer
from .mesh import build_mesh, get_mesh

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "DataParallel", "ParallelEnv"]


def _dist_client_active():
    try:
        from jax._src import distributed
        return distributed.global_state.client is not None
    except Exception:
        return False


def init_parallel_env():
    """Join the multi-host job if launched by paddle_tpu.distributed.launch
    (PADDLE_MASTER/PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM env), then create
    the default dp mesh over all (global) devices.

    After jax.distributed.initialize, jax.devices() is the job-wide device
    list, so every mesh built afterwards spans all hosts and XLA lowers
    cross-host collectives onto ICI/DCN per the mesh layout."""
    master = os.environ.get("PADDLE_MASTER")
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if master and world > 1 and not _dist_client_active():
        # multi-PROCESS computations on the CPU backend need a CPU
        # collectives implementation or XLA refuses with "Multiprocess
        # computations aren't implemented on the CPU backend". The
        # launcher's force_cpu_devices exports the choice (gloo on this
        # jaxlib); jax's enum flag never reads env vars, so it must be
        # applied here, before the backend initializes.
        impl = os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION")
        if impl and os.environ.get("JAX_PLATFORMS") == "cpu":
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  impl)
            except Exception:
                pass   # older jax: flag absent, collectives unavailable
        # The coordinator barrier defaults to 300 s. Under an elastic
        # supervisor that is FAR too patient: a group relaunched while
        # its peer host is still tearing down (epoch race) sits the full
        # barrier out — twice, if both sides miss — before failing and
        # triggering the restart that actually fixes things (observed as
        # a 10-minute test_multihost_kill_restarts_both_groups). The
        # supervisor sets a short timeout; a timed-out init exits
        # nonzero, bumps the epoch, and the next launch pairs up.
        timeout = int(os.environ.get("PADDLE_TPU_DIST_INIT_TIMEOUT", "300"))
        jax.distributed.initialize(
            coordinator_address=master,
            num_processes=world,
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")),
            initialization_timeout=timeout)
    get_mesh(create_default=True)
    return ParallelEnv()


def get_rank(group=None):
    return jax.process_index()


def get_world_size(group=None):
    return max(jax.process_count(), 1)


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    local_rank = rank
    nranks = world_size


class DataParallel(Layer):
    """reference DataParallel wraps NCCL allreduce of grads; here batches are
    globally sharded over 'dp' and grad reduction happens inside the compiled
    step, so this wrapper only marks intent + shards params."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        from .sharding_utils import shard_params
        shard_params(layers)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    @property
    def _sub_layers_passthrough(self):
        return self._layers

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass
