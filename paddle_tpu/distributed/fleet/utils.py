"""Fleet utils — reference python/paddle/distributed/fleet/utils/
(fs.py LocalFS/HDFSClient, recompute, DistributedInfer)."""
import os
import shutil
import subprocess

__all__ = ["LocalFS", "HDFSClient", "recompute", "DistributedInfer"]


class LocalFS:
    """Local filesystem client (reference fleet/utils/fs.py:LocalFS)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for f in os.listdir(fs_path):
            (dirs if os.path.isdir(os.path.join(fs_path, f))
             else files).append(f)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src_path):
            raise FileNotFoundError(src_path)
        if self.is_exist(dst_path):
            if not overwrite:
                # POSIX rename would clobber silently; honor the guard
                raise FileExistsError(dst_path)
            self.delete(dst_path)
        os.rename(src_path, dst_path)

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path) and not exist_ok:
            raise FileExistsError(fs_path)
        open(fs_path, "a").close()

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """`hadoop fs` subprocess client (reference fleet/utils/fs.py:
    HDFSClient) — requires a hadoop binary on PATH."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300000,
                 sleep_inter=1000):
        self._base = [os.path.join(hadoop_home, "bin", "hadoop")
                      if hadoop_home else "hadoop", "fs"]
        for k, v in (configs or {}).items():
            self._base += [f"-D{k}={v}"]

    def _run(self, *args):
        try:
            out = subprocess.run(self._base + list(args),
                                 capture_output=True, text=True, check=True)
        except FileNotFoundError as e:
            raise RuntimeError(
                "HDFSClient needs a hadoop binary on PATH (or "
                "hadoop_home); none found in this environment") from e
        return out.stdout

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except subprocess.CalledProcessError:
            return False

    def ls_dir(self, fs_path):
        try:
            lines = self._run("-ls", fs_path).splitlines()
        except subprocess.CalledProcessError:
            return [], []        # missing path: match LocalFS.ls_dir
        dirs, files = [], []
        for ln in lines:
            parts = ln.split()
            if len(parts) < 8:
                continue
            name = parts[-1].rsplit("/", 1)[-1]
            (dirs if ln.startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)


def recompute(function, *args, **kwargs):
    """Activation recomputation (reference fleet/utils/recompute):
    TPU-native it IS jax.checkpoint — the backward re-runs `function`
    instead of storing its internals. Non-tensor kwargs pass through to
    `function` (they are static w.r.t. the checkpoint).

    Eager (untraced) calls run `function` directly: rematerialization is
    a compiled-program memory tradeoff, and the direct call keeps the
    eager tape recording the block's PARAMETER ops (a checkpoint wrapper
    would orphan closure-captured params from Tensor.backward())."""
    import jax

    from ...framework.core import Tensor, apply_op
    kwargs.pop("preserve_rng_state", True)

    traced = any(isinstance(a._value if isinstance(a, Tensor) else a,
                            jax.core.Tracer) for a in args)
    if not traced:
        return function(*args, **kwargs)

    def fn(*raw):
        out = function(*[Tensor(r) for r in raw], **kwargs)
        return out._value if isinstance(out, Tensor) else out

    return apply_op(jax.checkpoint(fn), *args)


class DistributedInfer:
    """Thin parity shim (reference fleet/utils/ps_util.DistributedInfer is
    parameter-server specific; collective mode just runs the model)."""

    def __init__(self, main_program=None, startup_program=None):
        pass

    def init_distributed_infer_env(self, exe, loss, role_maker=None,
                                   dirname=None):
        return None

    def get_dist_infer_program(self):
        return None
