"""Tensor-parallel layers — reference python/paddle/distributed/fleet/
meta_parallel/parallel_layers/mp_layers.py.

GSPMD twist: instead of manually splitting weights per rank + NCCL allreduce,
each layer stores the FULL logical weight annotated with a partition_spec over
the 'tp' mesh axis. Under jit with NamedSharding'd params, XLA partitions the
matmuls and inserts the exact same collectives (allreduce for row-parallel,
allgather when gather_output) — but fused and overlapped.
"""
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply_op
from ...framework.random import next_key
from ...nn import functional as F
from ...nn.initializer import Normal, XavierUniform
from ...nn.layer_base import Layer
from ..mesh import in_shard_map, mesh_axis_size
from ..sharding_utils import constraint

__all__ = ["ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
           "ParallelCrossEntropy", "get_rng_state_tracker", "RNGStatesTracker"]


class RNGStatesTracker:
    """reference mp RNG tracker: distinct dropout streams for replicated vs
    tensor-parallel regions."""

    def __init__(self):
        self.states = {}

    def add(self, name, seed):
        self.states[name] = jax.random.PRNGKey(seed)

    def rng_state(self, name="model_parallel_rng"):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            yield
        return ctx()


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


class ColumnParallelLinear(Layer):
    """W:[in, out] sharded on out ('tp'); y = x @ W is tp-local, optional
    gather re-replicates the output."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.partition_spec = (None, "tp")
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter([out_features], attr=None, is_bias=True)
            self.bias.partition_spec = ("tp",)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = constraint(out, *((None,) * (out.ndim - 1)), None)
        else:
            out = constraint(out, *((None,) * (out.ndim - 1)), "tp")
        return out


class RowParallelLinear(Layer):
    """W:[in, out] sharded on in ('tp'); partial products psum via GSPMD."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.partition_spec = ("tp", None)
        self.weight.is_distributed = True
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None

    def forward(self, x):
        if self.input_is_parallel:
            x = constraint(x, *((None,) * (x.ndim - 1)), "tp")
        out = F.linear(x, self.weight, None)
        out = constraint(out, *((None,) * (out.ndim - 1)), None)
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(Layer):
    """Embedding table sharded over vocab ('tp'); GSPMD turns the gather into
    per-shard lookup + psum (the reference's masked-lookup + allreduce)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 0.02))
        self.weight.partition_spec = ("tp", None)
        self.weight.is_distributed = True

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Cross entropy over tp-sharded logits (reference parallel_cross_entropy).
    Computed from local shards without materializing gathered logits when the
    last dim is sharded; GSPMD handles the reduction."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
