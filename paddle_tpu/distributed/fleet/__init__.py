"""Fleet — reference python/paddle/distributed/fleet/__init__.py.

fleet.init(strategy) builds the global mesh from hybrid_configs;
distributed_model/distributed_optimizer return GSPMD-aware wrappers whose
jitted train step shards params per plan_shardings and batches over
('dp','fsdp'). No NCCL process groups: XLA emits the collectives.
"""
import jax

from ...framework.core import Tensor
from ..mesh import build_mesh, get_mesh, mesh_axis_size
from ..sharding_utils import plan_shardings, shard_params
from .base import (  # noqa: F401
    CommunicateTopology,
    DataGenerator,
    Fleet,
    MultiSlotDataGenerator,
    MultiSlotStringDataGenerator,
    PaddleCloudRoleMaker,
    Role,
    UserDefinedRoleMaker,
    UtilBase,
)
from .meta_parallel import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)
from . import metrics  # noqa: F401  (distributed metric aggregation)
from . import utils  # noqa: F401  (LocalFS/HDFSClient/recompute)
from .utils import DistributedInfer, HDFSClient, LocalFS, recompute  # noqa: F401

__all__ = [
    "init", "DistributedStrategy", "distributed_model", "distributed_optimizer",
    "get_hybrid_communicate_group", "worker_index", "worker_num", "is_first_worker",
    "HybridCommunicateGroup", "ColumnParallelLinear", "RowParallelLinear",
    "VocabParallelEmbedding", "ParallelCrossEntropy", "get_rng_state_tracker",
    "Fleet", "UtilBase", "Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
    "CommunicateTopology", "DataGenerator", "MultiSlotDataGenerator",
    "MultiSlotStringDataGenerator",
]


class DistributedStrategy:
    """reference python/paddle/distributed/fleet/base/distributed_strategy.py"""

    def __init__(self):
        self.hybrid_configs = {}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.dgc = False
        self.dgc_configs = {}
        self.fp16_allreduce = False
        self.lamb = False
        self.lars = False
        self.lars_configs = {}
        self.localsgd = False
        self.localsgd_configs = {}
        self.find_unused_parameters = False

    def _degrees(self):
        cfg = self.hybrid_configs or {}
        return dict(
            dp=int(cfg.get("dp_degree", 1)),
            tp=int(cfg.get("mp_degree", 1)),
            pp=int(cfg.get("pp_degree", 1)),
            fsdp=int(cfg.get("sharding_degree", 1)),
            sp=int(cfg.get("sep_degree", cfg.get("sp_degree", 1))),
            ep=int(cfg.get("ep_degree", 1)),
        )

    def pipeline_schedule(self):
        """Schedule for distributed.pipeline.pipeline_apply, from
        pipeline_configs (reference pipeline_configs schedule_mode /
        accumulate_steps / virtual_pp_degree):
        returns (schedule, n_microbatch, virtual)."""
        cfg = self.pipeline_configs or {}
        mode = str(cfg.get("schedule_mode", "1F1B")).lower()
        virtual = int(cfg.get("virtual_pp_degree", 1))
        if virtual > 1:
            mode = "interleaved"
        elif mode not in ("gpipe", "1f1b", "interleaved"):
            mode = "1f1b"
        if mode == "interleaved":
            virtual = max(virtual, 2)
        return mode, int(cfg.get("accumulate_steps", 4)), virtual


class HybridCommunicateGroup:
    def __init__(self, strategy):
        d = strategy._degrees()
        self._d = d

    def get_data_parallel_world_size(self):
        return self._d["dp"] * self._d["fsdp"]

    def get_model_parallel_world_size(self):
        return self._d["tp"]

    def get_pipe_parallel_world_size(self):
        return self._d["pp"]

    def get_sharding_parallel_world_size(self):
        return self._d["fsdp"]

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_model_parallel_group(self):
        from ..collective import Group
        return Group(0, self._d["tp"], axis="tp")

    def get_data_parallel_group(self):
        from ..collective import Group
        return Group(0, self._d["dp"], axis="dp")

    def get_sharding_parallel_group(self):
        from ..collective import Group
        return Group(0, self._d["fsdp"], axis="fsdp")

    def get_pipe_parallel_group(self):
        from ..collective import Group
        return Group(0, self._d["pp"], axis="pp")


_state = {"strategy": None, "hcg": None, "initialized": False}


def init(role_maker=None, is_collective=True, strategy=None):
    strategy = strategy or DistributedStrategy()
    d = strategy._degrees()
    n_dev = len(jax.devices())
    import numpy as np
    need = int(np.prod(list(d.values())))
    if need == 1 and n_dev > 1:
        d["dp"] = n_dev
    build_mesh(**d)
    _state.update(strategy=strategy, hcg=HybridCommunicateGroup(strategy), initialized=True)
    return None


def get_hybrid_communicate_group():
    return _state["hcg"]


def worker_index():
    return jax.process_index()


def worker_num():
    return max(jax.process_count(), 1)


def is_first_worker():
    return worker_index() == 0


class DistributedModel:
    """Wraps a Layer: params physically sharded over the mesh; calls pass
    through (GSPMD handles comms). reference meta_parallel model wrappers."""

    def __init__(self, layer):
        self._layers = layer
        self.sharding_plan = shard_params(layer)

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)


def distributed_model(model):
    if not _state["initialized"]:
        init()
    return DistributedModel(model)


def distributed_optimizer(optimizer, strategy=None):
    # optimizer state inherits parameter shardings automatically in the
    # functional path; eager path updates sharded arrays in place
    return optimizer
