"""Fleet base infrastructure — reference
python/paddle/distributed/fleet/base/{topology,role_maker,util_factory}.py
and fleet/data_generator/data_generator.py.

TPU-native notes: role information comes from the launcher's env
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM, set by
paddle_tpu.distributed.launch) instead of gloo/etcd; there is no
parameter-server mode, so every role is WORKER and the data generators
exist for their text-protocol (they are host-side utilities usable for
any slot-style ingestion).
"""
import collections
import os
import sys
from functools import reduce
from itertools import product

__all__ = ["CommunicateTopology", "Role", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker", "UtilBase", "DataGenerator",
           "MultiSlotDataGenerator", "MultiSlotStringDataGenerator",
           "Fleet"]


class CommunicateTopology:
    """Rank <-> hybrid-coordinate bookkeeping (reference
    fleet/base/topology.py:52)."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections.namedtuple("Coordinate",
                                                 self._parallel_names)
        self._world_size = reduce(lambda x, y: x * y, self._dims)
        ranges = [range(d) for d in self._dims]
        all_coord = [self.coordinate(*x) for x in product(*ranges)]
        self._coord2rank = dict(zip(all_coord, range(len(all_coord))))
        self._rank2coord = dict(zip(self._coord2rank.values(),
                                    self._coord2rank.keys()))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **args):
        assert len(args) == len(self._dims), args
        key = self.coordinate(**args)
        return self._coord2rank[key]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on `axis_name` equals `index`."""
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord2rank.items()
                      if c[axis] == index)

    def get_comm_list(self, axis_name):
        """Rank groups that communicate along `axis_name` (all other
        coordinates fixed)."""
        axis = self._parallel_names.index(axis_name)
        other = [self._parallel_names[i]
                 for i in range(len(self._dims)) if i != axis]
        groups = {}
        for coord, rank in self._coord2rank.items():
            key = tuple(getattr(coord, n) for n in other)
            groups.setdefault(key, []).append(rank)
        return [sorted(v) for _, v in sorted(groups.items())]

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        tf = coord._asdict()
        tf.update(kwargs)
        return self.get_rank(**tf)


class Role:
    WORKER = 1
    SERVER = 2


class PaddleCloudRoleMaker:
    """Role info from the launcher env (reference role_maker.py; gloo and
    the parameter-server paths don't exist here — everyone is a WORKER)."""

    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective

    def _worker_index(self):
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))

    def _worker_num(self):
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def _role(self):
        return Role.WORKER

    def _is_first_worker(self):
        return self._worker_index() == 0

    worker_index = _worker_index
    worker_num = _worker_num
    is_first_worker = _is_first_worker

    def _is_worker(self):
        return True

    def _is_server(self):
        return False


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=True, init_gloo=False, current_id=0,
                 role=Role.WORKER, worker_endpoints=None, server_endpoints=None,
                 **kwargs):
        super().__init__(is_collective=is_collective)
        self._current_id = current_id
        self._user_role = role
        self._worker_endpoints = worker_endpoints or []

    def _worker_index(self):
        return self._current_id

    def _worker_num(self):
        return max(len(self._worker_endpoints), 1)

    def _role(self):
        return self._user_role

    worker_index = _worker_index
    worker_num = _worker_num


class UtilBase:
    """Host-side helpers (reference fleet/utils/fleet_util.py surface)."""

    def get_file_shard(self, files):
        """This worker's slice of a file list (contiguous split with the
        remainder spread over the first workers)."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file paths")
        n = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        i = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        blocks = len(files) // n
        remain = len(files) % n
        begin = blocks * i + min(i, remain)
        end = begin + blocks + (1 if i < remain else 0)
        return files[begin:end]

    def print_on_rank(self, message, rank_id=0):
        if int(os.environ.get("PADDLE_TRAINER_ID", 0)) == rank_id:
            print(message, flush=True)

    def all_reduce(self, input, mode="sum"):
        """Cross-process reduction of host values; single-controller JAX
        jobs reduce over jax processes when initialized, else identity."""
        import numpy as np
        import jax
        arr = np.asarray(input)
        if jax.process_count() <= 1:
            return arr
        from jax.experimental import multihost_utils
        out = multihost_utils.process_allgather(arr)
        if mode == "sum":
            return out.sum(axis=0)
        if mode == "max":
            return out.max(axis=0)
        if mode == "min":
            return out.min(axis=0)
        raise ValueError(f"unsupported mode {mode!r}")

    def barrier(self, comm_world="worker"):
        import jax
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("fleet_util_barrier")


class DataGenerator:
    """Slot-format streaming data generator (reference
    fleet/data_generator): subclass and override generate_sample(line);
    run_from_stdin() turns stdin lines into the MultiSlotDataFeed text
    protocol on stdout."""

    def __init__(self):
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        raise NotImplementedError(
            "override generate_sample(line) -> callable yielding "
            "[(slot_name, [values...]), ...]")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    def run_from_stdin(self):
        self._run(sys.stdin, sys.stdout)

    def run_from_memory(self, lines):
        """Same pipeline over in-memory lines; returns the encoded
        strings (testable without process plumbing)."""
        out = []

        class _Sink:
            def write(self, s):
                out.append(s)
        self._run(lines, _Sink())
        return out

    def _run(self, line_iter, sink):
        batch = []
        for line in line_iter:
            for sample in self.generate_sample(line)():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    for s in self.generate_batch(batch)():
                        sink.write(self._gen_str(s))
                    batch = []
        if batch:
            for s in self.generate_batch(batch)():
                sink.write(self._gen_str(s))


def _check_slots(line):
    if isinstance(line, zip):
        line = list(line)
    if not isinstance(line, (list, tuple)):
        raise ValueError(
            "the output of generate_sample() must be list or tuple, e.g. "
            "[('words', [1926, 8, 17]), ('label', [1])]")
    return line


class MultiSlotStringDataGenerator(DataGenerator):
    def _gen_str(self, line):
        parts = []
        for name, elements in _check_slots(line):
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    def _gen_str(self, line):
        parts = []
        for name, elements in _check_slots(line):
            if not elements:
                raise ValueError(f"slot {name!r} has no values")
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"


class Fleet:
    """The Fleet object API (reference fleet/base/fleet_base.py:Fleet);
    the module-level paddle.distributed.fleet functions are the singleton
    form of this class."""

    def __init__(self):
        self._util = UtilBase()
        self._role_maker = None

    def init(self, role_maker=None, is_collective=True, strategy=None):
        from . import init as _init
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        return _init(role_maker=role_maker, is_collective=is_collective,
                     strategy=strategy)

    @property
    def util(self):
        return self._util

    def worker_index(self):
        if self._role_maker is not None:
            return self._role_maker.worker_index()
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))

    def worker_num(self):
        if self._role_maker is not None:
            return self._role_maker.worker_num()
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    def is_first_worker(self):
        return self.worker_index() == 0

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def barrier_worker(self):
        self._util.barrier()

    def distributed_model(self, model):
        from . import distributed_model as _dm
        return _dm(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        from . import distributed_optimizer as _do
        return _do(optimizer, strategy)

    def stop_worker(self):
        pass
