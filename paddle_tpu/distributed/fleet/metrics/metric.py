"""Fleet distributed metrics — reference
python/paddle/distributed/fleet/metrics/metric.py:24-373.

Each function aggregates shard-local metric state to the global value.
Two aggregation paths, both faithful to the reference's "all-reduce the
stat arrays, then finish the scalar math on the host" shape:

  * cross-process (SPMD multi-controller): `util.all_reduce` — the
    reference reduces over fleet workers via gloo/NCCL; here UtilBase
    reduces over jax processes (identity when single-process).
  * device-sharded (single-controller): a stat array whose LEADING axis
    is partitioned over mesh devices (one slice per data shard — the
    natural single-controller spelling of "each worker's local stats")
    is first reduced over that axis ON DEVICE, so XLA inserts the
    cross-device collective, then pulled to host.

The scalar epilogues (auc bucket walk, mae/rmse/mse/acc ratios) match
the reference formulas exactly — including auc's 0.5 on degenerate
input — but are vectorized instead of per-bucket Python loops.
"""
import math

import numpy as np

__all__ = []


def _default_util():
    from ..base import UtilBase
    return UtilBase()


def _resolve(value, scope):
    """Accept numpy / Tensor / jax.Array / scope variable name, return a
    host-or-device array. The reference resolves Variables through the
    static scope (metric.py:52-56); our static mode keeps values host-side
    under the same name."""
    from ....framework.core import Tensor
    if isinstance(value, str):
        if scope is None:
            from ....static import global_scope
            scope = global_scope()
        var = scope.find_var(value)
        if var is None:
            raise KeyError(f"variable {value!r} not found in scope")
        value = var
    if isinstance(value, Tensor):
        return value._value
    return value


def _device_partitioned(arr):
    """True when arr is a jax.Array whose leading axis is partitioned
    across devices — the shard-per-worker layout."""
    import jax
    if not isinstance(arr, jax.Array) or arr.ndim == 0:
        return False
    try:
        shard0 = arr.sharding.shard_shape(arr.shape)[0]
    except Exception:
        return False
    return shard0 != arr.shape[0]


def _all_reduce(value, mode, scope, util):
    import jax.numpy as jnp
    arr = _resolve(value, scope)
    if _device_partitioned(arr):
        # eager jnp reduction: runs on device (XLA inserts the
        # cross-device collective) and hits the op-by-op compile cache,
        # unlike a fresh jax.jit(lambda) per call which never would
        red = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[mode]
        arr = red(arr, axis=0)
    arr = np.asarray(arr)
    if util is None:
        util = _default_util()
    old_shape = arr.shape
    out = util.all_reduce(arr.reshape(-1), mode)
    return np.asarray(out).reshape(old_shape)


def sum(input, scope=None, util=None):  # noqa: A001 — reference name
    """Distributed elementwise sum of `input` across workers
    (reference metric.py:24)."""
    return _all_reduce(input, "sum", scope, util)


def max(input, scope=None, util=None):  # noqa: A001 — reference name
    """Distributed elementwise max across workers (reference metric.py:64)."""
    return _all_reduce(input, "max", scope, util)


def min(input, scope=None, util=None):  # noqa: A001 — reference name
    """Distributed elementwise min across workers (reference metric.py:103)."""
    return _all_reduce(input, "min", scope, util)


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Distributed AUC from per-worker threshold-bucket stat arrays
    (reference metric.py:143-218): sum-reduce the pos/neg histograms,
    then walk buckets from the highest threshold accumulating trapezoid
    area; 0.5 on degenerate input. The inputs are exactly what
    paddle_tpu.metric.Auc accumulates in _stat_pos/_stat_neg."""
    global_pos = _all_reduce(stat_pos, "sum", scope, util).reshape(-1)
    global_neg = _all_reduce(stat_neg, "sum", scope, util).reshape(-1)
    # descending threshold: reference iterates index = num_bucket-1-i
    pos_c = np.cumsum(global_pos[::-1]).astype(np.float64)
    neg_c = np.cumsum(global_neg[::-1]).astype(np.float64)
    tot_pos, tot_neg = pos_c[-1], neg_c[-1]
    if tot_pos * tot_neg == 0:
        return 0.5
    prev_pos = np.concatenate([[0.0], pos_c[:-1]])
    prev_neg = np.concatenate([[0.0], neg_c[:-1]])
    area = np.sum((neg_c - prev_neg) * (prev_pos + pos_c) / 2.0)
    return float(area / (tot_pos * tot_neg))


def mae(abserr, total_ins_num, scope=None, util=None):
    """Distributed MAE: sum of absolute errors over sum of instance
    counts (reference metric.py:221)."""
    global_err = _all_reduce(abserr, "sum", scope, util).reshape(-1)
    global_cnt = _all_reduce(total_ins_num, "sum", scope, util).reshape(-1)
    return float(global_err[0]) / float(global_cnt[0])


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    """Distributed RMSE (reference metric.py:268)."""
    return math.sqrt(mse(sqrerr, total_ins_num, scope, util))


def mse(sqrerr, total_ins_num, scope=None, util=None):
    """Distributed MSE (reference metric.py:316)."""
    global_err = _all_reduce(sqrerr, "sum", scope, util).reshape(-1)
    global_cnt = _all_reduce(total_ins_num, "sum", scope, util).reshape(-1)
    return float(global_err[0]) / float(global_cnt[0])


def acc(correct, total, scope=None, util=None):
    """Distributed accuracy: global correct count over global total
    (reference metric.py:373)."""
    global_correct = _all_reduce(correct, "sum", scope, util).reshape(-1)
    global_total = _all_reduce(total, "sum", scope, util).reshape(-1)
    return float(global_correct[0]) / float(global_total[0])
