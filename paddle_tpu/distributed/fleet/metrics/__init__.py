"""Reference python/paddle/distributed/fleet/metrics/__init__.py."""
from .metric import acc  # noqa: F401
from .metric import auc  # noqa: F401
from .metric import mae  # noqa: F401
from .metric import max  # noqa: F401
from .metric import min  # noqa: F401
from .metric import mse  # noqa: F401
from .metric import rmse  # noqa: F401
from .metric import sum  # noqa: F401

__all__ = []
