"""Launcher plumbing — reference python/paddle/distributed/utils.py
(Cluster/Pod/Trainer topology records + local trainer process control,
used by user launch scripts)."""
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["get_host_name_ip", "Trainer", "TrainerProc", "get_cluster",
           "start_local_trainers", "watch_local_trainers",
           "find_free_ports", "JobServer", "Cluster", "Pod", "Hdfs",
           "add_arguments", "terminate_local_procs", "get_logger",
           "pull_worker_log", "global_scatter", "global_gather"]


def get_host_name_ip():
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(host)
    except OSError:
        return None, None


def find_free_ports(num):
    ports = set()
    socks = []
    try:
        while len(ports) < num:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.add(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return list(ports)


class Hdfs:
    def __init__(self):
        self.hdfs_ugi = None
        self.hdfs_name = None
        self.hdfs_path = None

    def is_valid(self):
        return bool(self.hdfs_ugi and self.hdfs_name and self.hdfs_path)


class JobServer:
    def __init__(self):
        self.endpoint = None


class Trainer:
    def __init__(self):
        self.gpus = []
        self.endpoint = None
        self.rank = None

    def __str__(self):
        return f"Trainer(rank={self.rank}, endpoint={self.endpoint})"


class Pod:
    def __init__(self):
        self.rank = None
        self.id = None
        self.addr = None
        self.port = None
        self.trainers = []

    def __str__(self):
        return f"Pod(rank={self.rank}, addr={self.addr}, " \
               f"trainers={len(self.trainers)})"


class Cluster:
    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods = []
        self.hdfs = hdfs

    def trainers_nranks(self):
        return len(self.trainers_endpoints())

    def trainers_endpoints(self):
        return [t.endpoint for p in self.pods for t in p.trainers]

    def pods_endpoints(self):
        return [f"{p.addr}:{p.port}" for p in self.pods]

    def world_device_ids(self):
        return [t.gpus for p in self.pods for t in p.trainers]


def get_cluster(node_ips, node_ip, trainer_endpoints, devices_per_proc):
    """Build a Cluster record: one pod per node, one trainer per device
    group (reference get_cluster)."""
    cluster = Cluster(hdfs=None)
    rank = 0
    for node_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = node_rank
        pod.addr = ip
        pod.id = node_rank
        eps = trainer_endpoints[node_rank]
        for i, dev in enumerate(devices_per_proc):
            t = Trainer()
            t.gpus = dev if isinstance(dev, list) else [dev]
            t.endpoint = eps[i]
            t.rank = rank
            rank += 1
            pod.trainers.append(t)
        cluster.pods.append(pod)
    return cluster


class TrainerProc:
    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.rank = None
        self.cmd = None


def start_local_trainers(cluster, pod, training_script,
                         training_script_args, log_dir=None, envs=None):
    """Spawn this pod's trainer processes with the PADDLE_* env the
    runtime expects (init_parallel_env reads them)."""
    procs = []
    world = cluster.trainers_nranks()
    endpoints = ",".join(cluster.trainers_endpoints())
    for t in pod.trainers:
        env = dict(os.environ)
        env.update(envs or {})
        env.update(
            PADDLE_TRAINER_ID=str(t.rank),
            PADDLE_TRAINERS_NUM=str(world),
            PADDLE_MASTER=cluster.trainers_endpoints()[0],
            PADDLE_CURRENT_ENDPOINT=t.endpoint or "",
            PADDLE_TRAINER_ENDPOINTS=endpoints,
        )
        out = None
        tp = TrainerProc()
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            tp.log_fn = open(os.path.join(log_dir,
                                          f"workerlog.{t.rank}"), "w")
            out = tp.log_fn
        cmd = [sys.executable, "-u", training_script,
               *training_script_args]
        tp.proc = subprocess.Popen(cmd, env=env, stdout=out,
                                   stderr=subprocess.STDOUT if out else None)
        tp.rank = t.rank
        tp.cmd = cmd
        procs.append(tp)
    return procs


def watch_local_trainers(procs, nranks):
    """Poll trainer processes; returns the still-alive list, terminates
    the group on any failure (reference watch_local_trainers)."""
    alive = []
    for tp in procs:
        ret = tp.proc.poll()
        if ret is None:
            alive.append(tp)
        elif ret != 0:
            terminate_local_procs(procs)
            raise RuntimeError(
                f"trainer rank {tp.rank} failed with exit code {ret}")
    return alive


def terminate_local_procs(procs):
    for tp in procs:
        if tp.proc is not None and tp.proc.poll() is None:
            tp.proc.terminate()
    deadline = time.time() + 10
    for tp in procs:
        if tp.proc is None:
            continue
        try:
            tp.proc.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            tp.proc.kill()
        if tp.log_fn:
            tp.log_fn.close()


def get_logger(log_level=20, name="root"):
    import logging
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(levelname)s %(asctime)s %(filename)s:%(lineno)d] %(message)s"))
        logger.addHandler(h)
    return logger


def pull_worker_log(tp):
    if tp.log_fn:
        with open(tp.log_fn.name) as f:
            sys.stdout.write(f.read())


def add_arguments(argname, type, default, help, argparser, **kwargs):
    """Reference arg-helper used by launch scripts."""
    argparser.add_argument(
        "--" + argname, default=default, type=type,
        help=help + f" Default: %(default)s.", **kwargs)


def _uniform_tokens_per_peer(count, what):
    import numpy as np
    try:
        c = np.asarray(count.numpy() if hasattr(count, "numpy") else count)
    except Exception:
        # traced counts (inside jit): uniformity can't be verified and
        # ragged exchange can't compile — same guidance either way
        raise NotImplementedError(
            f"{what}: per-expert counts are traced; XLA needs static "
            "shapes — use the capacity-bounded dense dispatch "
            "(paddle_tpu.models.moe), the TPU-native MoE exchange")
    if c.ndim != 1 or not (c == c[0]).all():
        raise NotImplementedError(
            f"{what}: ragged per-expert counts need dynamic shapes, which "
            "XLA does not compile; use the capacity-bounded dense dispatch "
            "(paddle_tpu.models.moe) — the TPU-native MoE exchange")
    return int(c[0])


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True):
    """MoE raw token exchange (reference distributed/utils.global_scatter
    over NCCL alltoall). TPU-native MoE routes through capacity-bounded
    dense dispatch (models/moe.py) so shapes stay static; this wrapper
    supports the shape-static subset — uniform counts per peer — via
    all_to_all over the 'ep' axis."""
    from .collective import alltoall
    _uniform_tokens_per_peer(local_count, "global_scatter")
    return alltoall(x, group=group)


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True):
    """Inverse of global_scatter (same static-shape contract)."""
    from .collective import alltoall
    _uniform_tokens_per_peer(global_count, "global_gather")
    return alltoall(x, group=group)
