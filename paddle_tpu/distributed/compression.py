"""Gradient compression transforms for Trainer(grad_transform=...).

Reference: python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py
(Deep Gradient Compression: momentum correction + error-feedback top-k
sparsification) and fp16_allreduce_optimizer.py (cast grads to fp16 for the
allreduce). On TPU the collectives are XLA-inserted over ICI, so these are
expressed as pure gradient transforms inside the one compiled train step:
DGC keeps its *statistical* contract (only the top-k gradient mass reaches
the optimizer each step, the rest accumulates locally), and the bf16 cast
bounds the bytes any dp/fsdp reduction moves.
"""
import jax
import jax.numpy as jnp

__all__ = ["DGCCompressor", "bf16_compress", "from_strategy"]


def bf16_compress(grads, state):
    """fp16_allreduce analogue (bf16 on TPU: same byte width, no overflow
    cliffs). Cast grads to bf16 and back so every cross-device reduction
    of them moves half the bytes; stateless."""
    grads = jax.tree_util.tree_map(
        lambda g: g.astype(jnp.bfloat16).astype(g.dtype), grads)
    return grads, state


class DGCCompressor:
    """Error-feedback top-k gradient sparsification with momentum correction.

        trainer = Trainer(model, opt, loss_fn,
                          grad_transform=DGCCompressor(sparsity=0.99))

    Per leaf g:  u = m*u + g            (momentum correction)
                 v = v + u              (error accumulation)
                 send = top-k(|v|)      (k = (1-sparsity) fraction)
                 v -= send              (error feedback)
                 u = where(sent, 0, u)  (momentum factor masking)
    The optimizer sees `send`; everything else stays in v and drains over
    later steps, so no gradient mass is lost. Momentum factor masking
    clears u at sent coordinates (DGC paper §3.2 / reference dgc op) so a
    frequently-sent coordinate's velocity doesn't compound into an
    over-weighted update.
    """

    def __init__(self, sparsity=0.99, momentum=0.9, min_k=1):
        assert 0.0 <= sparsity < 1.0
        self.sparsity = float(sparsity)
        self.momentum = float(momentum)
        self.min_k = min_k

    def init_state(self, params):
        # zeros_like (not zeros(shape)): under jit the data dependence on
        # the param propagates its GSPMD sharding into the residual slots
        # (same idiom as Trainer's optimizer-state init)
        zeros = lambda v: jnp.zeros_like(v, dtype=jnp.float32)
        return {
            "u": jax.tree_util.tree_map(zeros, params),
            "v": jax.tree_util.tree_map(zeros, params),
        }

    def __call__(self, grads, state):
        m = self.momentum

        def leaf(g, u, v):
            g32 = g.astype(jnp.float32)
            u = m * u + g32
            v = v + u
            flat = v.reshape(-1)
            n = flat.shape[0]
            k = max(self.min_k, int(n * (1.0 - self.sparsity)))
            if k >= n:
                send = v
                sent = jnp.ones_like(v, jnp.bool_)
            else:
                thresh = jax.lax.top_k(jnp.abs(flat), k)[0][-1]
                sent = jnp.abs(v) >= thresh
                send = jnp.where(sent, v, 0.0)
            v = v - send
            u = jnp.where(sent, 0.0, u)     # momentum factor masking
            return send.astype(g.dtype), u, v

        # flatten by the grads treedef so tuples used as structure nodes in
        # the params pytree are never mistaken for per-leaf results
        leaves_g, treedef = jax.tree_util.tree_flatten(grads)
        leaves_u = treedef.flatten_up_to(state["u"])
        leaves_v = treedef.flatten_up_to(state["v"])
        outs = [leaf(g, u, v)
                for g, u, v in zip(leaves_g, leaves_u, leaves_v)]
        sends = treedef.unflatten([o[0] for o in outs])
        new_u = treedef.unflatten([o[1] for o in outs])
        new_v = treedef.unflatten([o[2] for o in outs])
        return sends, {"u": new_u, "v": new_v}


def from_strategy(strategy):
    """Build the grad_transform a fleet DistributedStrategy asks for
    (strategy.dgc / strategy.fp16_allreduce), or None."""
    if getattr(strategy, "dgc", False):
        cfg = getattr(strategy, "dgc_configs", {}) or {}
        return DGCCompressor(
            sparsity=float(cfg.get("sparsity", 0.99)),
            momentum=float(cfg.get("momentum", 0.9)))
    if getattr(strategy, "fp16_allreduce", False):
        return bf16_compress
    return None
