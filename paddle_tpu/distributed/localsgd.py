"""LocalSGD: local updates + periodic parameter averaging.

Reference: python/paddle/distributed/fleet/meta_optimizers/localsgd_optimizer.py:26
(LocalSGDOptimizer) and :197 (AdaptiveLocalSGDOptimizer). Workers run
`k_steps` optimizer updates on their own shard without gradient
synchronization, then average parameters across the data-parallel group —
trading a little statistical efficiency for k× fewer synchronizations when
interconnect is the bottleneck (DCN-connected pods, preemptible fleets).

TPU-native formulation: instead of per-process divergent copies + allreduce
(the reference's NCCL program), parameters live as [dp, ...]-stacked arrays
sharded over the 'dp' mesh axis. One jitted step runs the per-rank update
inside shard_map (no collectives), and every k-th step a `lax.cond`-gated
psum averages the stack — XLA schedules the collective on ICI only when the
sync flag fires.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn.layer_base import load_state_pytree
from .mesh import get_mesh
from .trainer import batch_to_arrays, make_compute_loss

__all__ = ["LocalSGDTrainer"]


class LocalSGDTrainer:
    """Data-parallel trainer with LocalSGD synchronization.

        trainer = LocalSGDTrainer(model, opt, loss_fn, k_steps=4)
        loss = trainer.step(batch)       # batch leading dim divisible by dp

    `adaptive=True` approximates AdaptiveLocalSGDOptimizer: the sync period
    grows as the loss plateaus (begin_step semantics simplified to host-side
    control, since the schedule is host-driven in the reference too).
    """

    def __init__(self, model, optimizer, loss_fn, mesh=None, k_steps=4,
                 axis_name="dp", adaptive=False, max_k_steps=16):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or get_mesh()
        self.axis = axis_name
        self.k_steps = k_steps
        self.adaptive = adaptive
        self.max_k_steps = max_k_steps
        self.dp = self.mesh.shape[axis_name]
        self._host_step = 0
        self._loss_hist = []

        trainable, consts = {}, {}
        for name, p in model.named_parameters():
            (consts if p.stop_gradient else trainable)[name] = p._value
        for name, b in model.named_buffers():
            consts[name] = b._value
        stack_sh = lambda v: jax.device_put(
            jnp.broadcast_to(v[None], (self.dp,) + v.shape),
            NamedSharding(self.mesh, P(self.axis)))
        # every rank starts from identical params; they diverge between syncs
        self.params = {k: stack_sh(v) for k, v in trainable.items()}
        self.consts = consts
        self.opt_state = jax.jit(jax.vmap(optimizer.init_state_pytree))(self.params)
        self._step_fn = self._build()

    def _build(self):
        model, optimizer, loss_fn = self.model, self.optimizer, self.loss_fn
        axis, dp = self.axis, self.dp

        compute_loss = make_compute_loss(model, loss_fn)

        def local_step(params, opt_state, consts, lr, batch, do_sync):
            # per dp rank: the stacked leading axis arrives as a size-1 shard
            # (shard_map shards dims, it does not strip them) — squeeze it
            # for the model and restore it on the way out
            params = jax.tree_util.tree_map(lambda v: v[0], params)
            opt_state = jax.tree_util.tree_map(lambda v: v[0], opt_state)
            (loss_v, buf_updates), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(params, consts, batch)
            new_params, new_state = optimizer.apply_gradients_pytree(
                params, grads, opt_state, lr)
            new_params = jax.lax.cond(
                do_sync,
                lambda t: jax.tree_util.tree_map(
                    lambda v: jax.lax.pmean(v, axis), t),
                lambda t: t,
                new_params)
            # buffer stats (BN running mean/var) are consts: average the
            # per-rank updates so the replicated copy stays consistent
            new_consts = {**consts, **jax.tree_util.tree_map(
                lambda v: jax.lax.pmean(v, axis), buf_updates)}
            unsq = lambda tree: jax.tree_util.tree_map(lambda v: v[None], tree)
            return (unsq(new_params), unsq(new_state), new_consts,
                    jax.lax.pmean(loss_v, axis))

        strip = lambda tree: jax.tree_util.tree_map(lambda _: P(axis), tree)

        def step(params, opt_state, consts, lr, batch, do_sync):
            # version/kwarg portability lives in mesh.compat_shard_map
            from .mesh import compat_shard_map
            return compat_shard_map(
                local_step, mesh=self.mesh,
                in_specs=(strip(params), strip(opt_state), P(), P(),
                          jax.tree_util.tree_map(lambda _: P(axis), batch), P()),
                out_specs=(strip(params), strip(opt_state), P(), P()),
                check=False,
            )(params, opt_state, consts, lr, batch, do_sync)

        return jax.jit(step, donate_argnums=(0, 1))

    def _maybe_grow_k(self):
        # loss plateauing -> sync less often; growth PERSISTS (doubling up to
        # max_k_steps, AdaptiveLocalSGD semantics)
        if not self.adaptive or len(self._loss_hist) < 4:
            return
        recent = self._loss_hist[-4:]
        rel_improve = (recent[0] - recent[-1]) / max(abs(recent[0]), 1e-8)
        if rel_improve < 0.01:
            self.k_steps = min(self.max_k_steps, self.k_steps * 2)
            self._loss_hist.clear()   # re-evaluate at the new cadence

    def step(self, batch, lr=None):
        lr = self.optimizer.get_lr() if lr is None else lr
        batch = batch_to_arrays(batch)
        self._host_step += 1
        do_sync = (self._host_step % self.k_steps) == 0
        self.params, self.opt_state, self.consts, loss = self._step_fn(
            self.params, self.opt_state, self.consts, lr, batch,
            jnp.asarray(do_sync))
        sched = self.optimizer._lr_scheduler
        if sched is not None:
            sched.step()
        if self.adaptive:
            # only the adaptive controller needs the value (host sync); keep
            # the async-dispatch property otherwise
            self._loss_hist.append(float(loss))
            self._loss_hist = self._loss_hist[-8:]
            self._maybe_grow_k()
        return loss

    def sync_to_model(self):
        """Average the per-rank stacks and write back into the Layer tree
        (consts carry the pmean'd BN running stats)."""
        avg = {k: jnp.mean(v, axis=0) for k, v in self.params.items()}
        load_state_pytree(self.model, {**self.consts, **avg})
