"""Collective communication — reference python/paddle/distributed/collective.py.

The reference binds NCCL; here every collective is an XLA collective over the
mesh ('dp' by default), usable in two contexts:

  * inside shard_map (axis_scope active): lax.psum / all_gather / ppermute …
    compiled onto ICI — the performance path
  * eager / outside shard_map: single-controller semantics. Arrays are global,
    so sum-like collectives are identities for replicated values; world size 1
    is always an identity. This keeps reference scripts runnable unchanged.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op
from .mesh import current_axis_context, get_mesh, in_shard_map, mesh_axis_size

__all__ = [
    "ReduceOp", "all_reduce", "all_gather", "reduce", "broadcast", "scatter",
    "reduce_scatter", "alltoall", "send", "recv", "barrier", "get_group",
    "new_group", "wait", "Group",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    def __init__(self, rank, nranks, id=0, ranks=None, axis="dp"):
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.ranks = ranks or list(range(nranks))
        self.axis = axis

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank)


_default_group = None


def _group_axis(group):
    return group.axis if isinstance(group, Group) else "dp"


def get_group(id=0):
    global _default_group
    if _default_group is None:
        import jax
        _default_group = Group(jax.process_index(), max(jax.process_count(), 1))
    return _default_group


def new_group(ranks=None, backend=None, axis="dp"):
    return Group(0, len(ranks) if ranks else mesh_axis_size(axis), axis=axis)


def _live_axis(axis):
    """The axis name to reduce over, or None for identity semantics."""
    ctx = current_axis_context()
    if axis in ctx:
        return axis
    return None


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _live_axis(_group_axis(group))
    if axis is None:
        return tensor  # replicated global array: sum across ranks is itself

    def _f(v):
        if op in (ReduceOp.SUM, "sum"):
            return jax.lax.psum(v, axis)
        if op in (ReduceOp.MAX, "max"):
            return jax.lax.pmax(v, axis)
        if op in (ReduceOp.MIN, "min"):
            return jax.lax.pmin(v, axis)
        if op in (ReduceOp.AVG, "avg"):
            return jax.lax.pmean(v, axis)
        return jax.lax.psum(v, axis)  # prod unsupported by ICI; sum fallback
    if isinstance(tensor, Tensor):
        out = apply_op(_f, tensor)
        tensor._value = out._value
        return tensor
    return _f(tensor)


def all_gather(tensor_list, tensor=None, group=None, sync_op=True, axis=0):
    """paddle signature: all_gather(list, tensor). Inside shard_map returns
    the concatenated array as well."""
    if tensor is None:  # functional form: all_gather(x) -> gathered
        tensor, tensor_list = tensor_list, None
    ax = _live_axis(_group_axis(group))
    if ax is None:
        out = tensor
        if tensor_list is not None:
            tensor_list.append(tensor)
        return out

    def _f(v):
        return jax.lax.all_gather(v, ax, tiled=True)
    out = apply_op(_f, tensor) if isinstance(tensor, Tensor) else _f(tensor)
    if tensor_list is not None:
        n = mesh_axis_size(ax)
        from ..tensor.manipulation import split
        tensor_list.extend(split(out, n, axis=0))
    return out


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def broadcast(tensor, src=0, group=None, sync_op=True):
    axis = _live_axis(_group_axis(group))
    if axis is None:
        return tensor

    def _f(v):
        # take src's value: gather then index (XLA folds this into a broadcast)
        g = jax.lax.all_gather(v, axis)
        return g[src]
    if isinstance(tensor, Tensor):
        out = apply_op(_f, tensor)
        tensor._value = out._value
        return tensor
    return _f(tensor)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    axis = _live_axis(_group_axis(group))
    if axis is None:
        return tensor

    def _f(v):
        idx = jax.lax.axis_index(axis)
        n = mesh_axis_size(axis)
        chunk = v.shape[0] // n
        return jax.lax.dynamic_slice_in_dim(v, idx * chunk, chunk, axis=0)
    if isinstance(tensor, Tensor):
        return apply_op(_f, tensor)
    return _f(tensor)


def reduce_scatter(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _live_axis(_group_axis(group))
    if axis is None:
        return tensor

    def _f(v):
        return jax.lax.psum_scatter(v, axis, scatter_dimension=0, tiled=True)
    if isinstance(tensor, Tensor):
        return apply_op(_f, tensor)
    return _f(tensor)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    axis = _live_axis(_group_axis(group))
    x = in_tensor_list
    stacked = None
    if isinstance(x, (list, tuple)):
        from ..tensor.manipulation import stack
        stacked = stack(list(x), axis=0)
    else:
        stacked = x
    if axis is None:
        out = stacked
    else:
        def _f(v):
            return jax.lax.all_to_all(v, axis, split_axis=0, concat_axis=0, tiled=False)
        out = apply_op(_f, stacked) if isinstance(stacked, Tensor) else _f(stacked)
    if out_tensor_list is not None:
        n = mesh_axis_size(axis) if axis else 1
        from ..tensor.manipulation import unstack
        out_tensor_list.extend(unstack(out, axis=0))
        return None
    return out


def send(tensor, dst=0, group=None, sync_op=True):
    axis = _live_axis(_group_axis(group))
    if axis is None:
        return tensor
    n = mesh_axis_size(axis)

    def _f(v):
        # point-to-point on ICI = ppermute ring hop
        perm = [(i, dst) for i in range(n)]
        return jax.lax.ppermute(v, axis, perm)
    return apply_op(_f, tensor) if isinstance(tensor, Tensor) else _f(tensor)


def recv(tensor, src=0, group=None, sync_op=True):
    axis = _live_axis(_group_axis(group))
    if axis is None:
        return tensor
    n = mesh_axis_size(axis)

    def _f(v):
        perm = [(src, i) for i in range(n)]
        return jax.lax.ppermute(v, axis, perm)
    out = apply_op(_f, tensor) if isinstance(tensor, Tensor) else _f(tensor)
    if isinstance(tensor, Tensor):
        tensor._value = out._value
        return tensor
    return out


def barrier(group=None):
    jax.effects_barrier()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and hasattr(tensor._value, "block_until_ready"):
        tensor._value.block_until_ready()
