"""Reference python/paddle/distributed/metric/metrics.py — yaml-driven
metric tables for the parameter-server runtime (init_metric wires C++
metric instances into the PS trainer; print_metric/print_auc read them
back).

The PS runtime is deflected on TPU (docs/distributed.md): embedding
tables shard over the mesh and metric aggregation is
distributed.fleet.metrics over collectives.  These entry points exist
so migrating imports resolve, and fail with that mapping instead of an
AttributeError."""

__all__ = ["init_metric", "print_metric", "print_auc"]

_MSG = ("the parameter-server metric runtime is replaced on TPU: compute "
        "shard-local stats with paddle_tpu.metric.Auc/Accuracy and "
        "aggregate with paddle_tpu.distributed.fleet.metrics "
        "(sum/max/min/auc/mae/rmse/mse/acc over mesh collectives)")


def init_metric(metric_ptr, metric_yaml_path, cmatch_rank_var="",
                mask_var="", uid_var="", phase=-1, cmatch_rank_group="",
                ignore_rank=False, bucket_size=1000000):
    raise NotImplementedError(_MSG)


def print_metric(metric_ptr, name):
    raise NotImplementedError(_MSG)


def print_auc(metric_ptr, is_day, phase="all"):
    raise NotImplementedError(_MSG)
