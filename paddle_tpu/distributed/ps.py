"""Reference python/paddle/distributed/ps/ — the parameter-server
runtime.  Deliberately deflected on TPU (accepted design, see
docs/distributed.md): recsys-scale embedding tables shard over the
device mesh via distributed.ShardedEmbedding, the data path keeps
InMemoryDataset/QueueDataset shims, and metric aggregation is
fleet.metrics.  Importing resolves; instantiating explains the
mapping."""

__all__ = ["TheOnePSRuntime"]

_MSG = ("the parameter-server runtime is replaced on TPU by mesh-sharded "
        "embedding tables: use distributed.ShardedEmbedding with a "
        "normal DataLoader (docs/distributed.md 'PS-mode mapping')")


class TheOnePSRuntime:
    def __init__(self, *a, **kw):
        raise NotImplementedError(_MSG)


def __getattr__(name):
    raise AttributeError(f"paddle_tpu.distributed.ps.{name}: {_MSG}")
