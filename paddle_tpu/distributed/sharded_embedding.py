"""Mesh-sharded embedding tables — the TPU answer to reference PS mode.

Reference counterpart: the parameter-server training stack for
recsys-scale sparse embeddings — python/paddle/distributed/ps/the_one_ps.py
(1,439 L: sparse tables on PS nodes, workers pull rows / push sparse
grads) and paddle.static.nn.sparse_embedding.

TPU-first mapping (no parameter servers exist here):

    PS concept                      → TPU-native equivalent
    ------------------------------------------------------------------
    sparse table sharded over       → ONE logical [V, D] array with its
    PS instances (by row hash)        vocab dim sharded over mesh axes
                                      (GSPMD row sharding)
    worker "pull" of touched rows   → jnp.take on the sharded table:
                                      XLA lowers the gather to an
                                      all-to-all/all-gather over ICI
    "push" of sparse row grads      → VJP of take = scatter-add, which
                                      GSPMD keeps row-sharded: each
                                      device only materializes and
                                      updates ITS rows' optimizer state
    distributed lookup table        → total HBM across the mesh; each
    capacity ≫ single host            device holds V/n rows

Inside a shard_map body (manual-collective contexts: pipeline stages,
custom kernels) the same layer switches to the explicit recipe: local
slice lookup, out-of-range rows masked to zero, psum over the shard
axis — byte-identical to what GSPMD emits for the annotated gather.
"""
import jax
import jax.numpy as jnp

from ..framework.core import apply_op
from ..nn.layer.common import Embedding
from .mesh import current_axis_context, in_shard_map, mesh_axis_size

__all__ = ["ShardedEmbedding"]


class ShardedEmbedding(Embedding):
    """nn.Embedding with the vocab (row) dim sharded over `shard_axes`.

    Drop-in replacement: same call signature and numerics as the dense
    layer (parity-tested), but the [V, D] table carries a row partition
    spec so plan_shardings/GSPMD place V/n rows per device — tables
    larger than one device's HBM train normally. Gradients stay
    row-sharded through the take-VJP scatter, so optimizer state for a
    row lives only where the row does (the PS "sparse push" economics).

    Args:
        shard_axes: mesh axis name (or tuple of names) to shard rows
            over. Defaults to ("dp", "tp") — recsys tables want the
            biggest product of axes available; axes that don't divide V
            are dropped by feasible_spec at plan time.
    """

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=True, weight_attr=None, shard_axes=("dp", "tp"),
                 name=None):
        super().__init__(num_embeddings, embedding_dim,
                         padding_idx=padding_idx, sparse=sparse,
                         weight_attr=weight_attr, name=name)
        if isinstance(shard_axes, str):
            shard_axes = (shard_axes,)
        # the REQUESTED axes: feasibility against the actual mesh is
        # resolved at plan time (param_partition_spec -> feasible_spec),
        # so building the layer before build_mesh() is safe
        self.shard_axes = tuple(shard_axes)
        self.weight.partition_spec = (self.shard_axes, None)

    def forward(self, x):
        axes = [a for a in self.shard_axes
                if a in (current_axis_context() or ())]
        if in_shard_map() and axes:
            # manual-collective path: the table arg is the LOCAL row
            # slice; mask foreign rows and psum the partial lookups
            pad = self._padding_idx

            def _local(ids, w_local):
                idx = jnp.zeros((), jnp.int32)
                for a in axes:
                    idx = idx * mesh_axis_size(a) + jax.lax.axis_index(a)
                rows = w_local.shape[0]
                offset = idx * rows
                local = ids - offset
                ok = (local >= 0) & (local < rows)
                if pad is not None:
                    ok = ok & (ids != pad)
                safe = jnp.clip(local, 0, rows - 1)
                out = jnp.take(w_local, safe, axis=0) \
                    * ok[..., None].astype(w_local.dtype)
                return jax.lax.psum(out, tuple(axes))
            return apply_op(_local, x, self.weight)
        # GSPMD path: annotated row sharding makes XLA insert the
        # gather collectives; numerics identical to dense Embedding
        return super().forward(x)

    def extra_repr(self):
        return (f"{self._num_embeddings}, {self._embedding_dim}, "
                f"shard_axes={self.shard_axes}")
