from . import launch

launch()
