"""Multi-host / multi-process launcher.

Reference: python/paddle/distributed/launch/ (main.py arg surface,
controllers/collective.py process management). The TPU-native rendering is
much smaller: there is no parameter-server mode and no per-GPU process
fan-out — JAX is single-controller-per-host, so the launcher's job is

  1. decide (master, world_size, rank) for every process,
  2. export them (PADDLE_MASTER / PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM),
  3. exec the training script once per local process and babysit it.

`init_parallel_env` (distributed/parallel.py) picks the env up and calls
`jax.distributed.initialize`, after which `jax.devices()` is the GLOBAL
device list and every GSPMD mesh spans all hosts — collectives ride
ICI/DCN exactly as laid out by the mesh axes.

Usage (2 hosts):
    host0$ python -m paddle_tpu.distributed.launch --nnodes 2 --rank 0 \
               --master 10.0.0.1:8476 train.py --lr 0.1
    host1$ python -m paddle_tpu.distributed.launch --nnodes 2 --rank 1 \
               --master 10.0.0.1:8476 train.py --lr 0.1

CPU emulation (2 processes x 4 virtual devices on one machine):
    $ python -m paddle_tpu.distributed.launch --nproc_per_node 2 \
          --cpu_devices_per_rank 4 train.py
"""
import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _free_port():
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu distributed launcher (jax.distributed)")
    p.add_argument("--master", default=None,
                   help="coordinator ip:port (default: local free port)")
    p.add_argument("--rank", type=int, default=0,
                   help="this node's rank in [0, nnodes)")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of nodes (hosts) in the job")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes to start on this node (TPU: 1 per host)")
    p.add_argument("--log_dir", default=None,
                   help="write per-rank stdout/stderr to this directory")
    p.add_argument("--job_id", default="default", help="job name for logs")
    p.add_argument("--devices", default=None,
                   help="restrict visible TPU devices (TPU_VISIBLE_DEVICES)")
    p.add_argument("--cpu_devices_per_rank", type=int, default=0,
                   help="emulate N virtual CPU devices per process "
                        "(JAX_PLATFORMS=cpu; for tests/dry-runs)")
    p.add_argument("training_script", help="script to run")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def force_cpu_devices(env, n):
    """Mutate an env dict so a fresh process comes up with `n` virtual CPU
    devices, even when the parent already initialized an accelerator PJRT
    plugin (plugins export discovery vars — PJRT_LIBRARY_PATH, TPU_*, … —
    that would otherwise make the child claim the accelerator again)."""
    for k in list(env):
        if k.startswith(("AXON_", "TPU_", "PALLAS_AXON_")) or k in (
                "PJRT_LIBRARY_PATH", "_AXON_REGISTERED"):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    # multi-PROCESS computations need a CPU collectives backend: without
    # one XLA refuses outright ("Multiprocess computations aren't
    # implemented on the CPU backend") — the root cause of the two-process
    # launch/elastic failures this repo carried since the seed. This
    # jaxlib ships gloo; respect an explicit override.
    env.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (flags +
                        f" --xla_force_host_platform_device_count={n}").strip()
    return env


def _child_env(args, master, world, rank):
    env = dict(os.environ)
    env.update(
        PADDLE_MASTER=master,
        PADDLE_TRAINER_ID=str(rank),
        PADDLE_TRAINERS_NUM=str(world),
        PADDLE_JOB_ID=args.job_id,
    )
    if args.devices:
        env["TPU_VISIBLE_DEVICES"] = args.devices
    if args.cpu_devices_per_rank:
        force_cpu_devices(env, args.cpu_devices_per_rank)
    return env


def main(argv=None):
    args = _parse(argv)
    nproc = args.nproc_per_node
    world = args.nnodes * nproc
    master = args.master or f"127.0.0.1:{_free_port()}"
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    procs, logs = [], []
    for p in range(nproc):
        rank = args.rank * nproc + p
        env = _child_env(args, master, world, rank)
        cmd = [sys.executable, args.training_script, *args.training_script_args]
        if args.log_dir:
            out = open(os.path.join(
                args.log_dir, f"{args.job_id}.rank{rank}.log"), "w")
            logs.append(out)
        else:
            out = None
        procs.append((rank, subprocess.Popen(
            cmd, env=env, stdout=out, stderr=subprocess.STDOUT if out else None)))

    rc = 0
    try:
        pending = dict(procs)
        termed_at = None
        while pending:
            for rank, proc in list(pending.items()):
                r = proc.poll()
                if r is None:
                    continue
                del pending[rank]
                if r != 0 and rc == 0:
                    # first failure wins; peers then die by SIGTERM (-15)
                    rc = r
                    print(f"[launch] rank {rank} exited rc={r}; "
                          "terminating peers", file=sys.stderr)
                    for _, q in procs:
                        if q.poll() is None:
                            q.terminate()
                    termed_at = time.time()
            if termed_at is not None and pending and \
                    time.time() - termed_at > 10:
                # SIGTERM can't land on a rank wedged inside a gloo
                # collective whose partner died: the C++ socket read
                # never returns, so a python-level signal handler (e.g.
                # ElasticManager's graceful-exit hook) never runs.
                # Escalate so the group always reaps and the elastic
                # supervisor can restart it.
                print("[launch] peers ignored SIGTERM for 10s; killing",
                      file=sys.stderr)
                for _, q in procs:
                    if q.poll() is None:
                        q.kill()
                termed_at = None
            time.sleep(0.2)
    except KeyboardInterrupt:
        for _, q in procs:
            if q.poll() is None:
                q.send_signal(signal.SIGINT)
        # a rank wedged in a gloo collective never sees SIGINT (same
        # C++-block story as the SIGTERM escalation above) — reap it
        # rather than orphan it past our own exit
        deadline = time.time() + 10
        while time.time() < deadline and any(
                q.poll() is None for _, q in procs):
            time.sleep(0.2)
        for _, q in procs:
            if q.poll() is None:
                q.kill()
                q.wait()
        rc = 130
    finally:
        for f in logs:
            f.close()
    return rc


def launch():
    """Entry point matching reference paddle.distributed.launch.launch()."""
    sys.exit(main())
