"""Reference python/paddle/distributed/passes/ (pass_base.py new_pass /
PassManager / PassContext + the auto_parallel_* program passes).

The pass FRAMEWORK is real here (registration, ordering, context); the
reference's program-rewriting passes themselves are compile-time
behaviors on TPU — XLA/GSPMD performs the rewrite the pass encoded, or
the framework exposes it as a first-class knob.  Applying one of those
passes therefore raises with its TPU-native replacement spelled out,
instead of silently no-op-ing on a Program that doesn't exist.

    new_pass("fuse_all_reduce")     -> XLA all-reduce combiner (automatic)
    new_pass("auto_parallel_amp")   -> amp.auto_cast / amp.decorate
    new_pass("auto_parallel_fp16")  -> amp O2 (dtype="float16")
    new_pass("auto_parallel_recompute") -> remat policies / fleet recompute
    new_pass("auto_parallel_sharding")  -> mesh axes + shard_params
    new_pass("auto_parallel_gradient_merge") -> Trainer(grad_accum_steps=N)
"""

__all__ = ["new_pass", "PassManager", "PassContext", "PassBase",
           "register_pass"]

_PASS_REGISTRY = {}


class PassContext:
    def __init__(self):
        self._attrs = {}

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)


class PassBase:
    name = None

    def __init__(self, attrs=None):
        self._attrs = dict(attrs or {})

    def set_attr(self, key, value):
        self._attrs[key] = value
        return self

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    def check_before(self):
        return True

    def apply(self, main_programs, startup_programs=None, context=None):
        return self._apply_impl(main_programs, startup_programs,
                                context or PassContext())

    def _apply_impl(self, mains, startups, context):
        raise NotImplementedError


def register_pass(name):
    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls
    return deco


class _DeflectedPass(PassBase):
    replacement = ""

    def _apply_impl(self, mains, startups, context):
        raise NotImplementedError(
            f"pass {self.name!r} is a fluid Program rewrite; on TPU use "
            f"{self.replacement} — XLA/GSPMD applies the equivalent "
            "transform at compile time")


def _deflect(name, replacement):
    cls = type(f"_Pass_{name}", (_DeflectedPass,),
               {"name": name, "replacement": replacement})
    _PASS_REGISTRY[name] = cls
    return cls


_deflect("fuse_all_reduce",
         "nothing: the XLA all-reduce combiner fuses collectives")
_deflect("fuse_optimizer", "nothing: XLA fuses the optimizer update")
_deflect("auto_parallel_amp", "paddle_tpu.amp.auto_cast / amp.decorate")
_deflect("auto_parallel_fp16",
         "paddle_tpu.amp.decorate(level='O2', dtype='float16')")
_deflect("auto_parallel_bf16",
         "paddle_tpu.amp.decorate(level='O2', dtype='bfloat16')")
_deflect("auto_parallel_recompute",
         "model remat policies / distributed.fleet.utils.recompute")
_deflect("auto_parallel_sharding",
         "distributed.build_mesh + shard_params (GSPMD)")
_deflect("auto_parallel_gradient_merge",
         "distributed.trainer.Trainer(grad_accum_steps=N)")
_deflect("ps_trainer_pass",
         "distributed.ShardedEmbedding (docs/distributed.md)")
_deflect("ps_server_pass",
         "distributed.ShardedEmbedding (docs/distributed.md)")


def new_pass(name, pass_attrs=None):
    if name not in _PASS_REGISTRY:
        raise ValueError(f"unknown pass {name!r}; registered: "
                         f"{sorted(_PASS_REGISTRY)}")
    return _PASS_REGISTRY[name](pass_attrs)


class PassManager:
    def __init__(self, passes=None):
        self._passes = list(passes or [])

    @property
    def names(self):
        return [p.name for p in self._passes]

    def append(self, p):
        self._passes.append(p)

    def apply(self, main_programs, startup_programs=None):
        ctx = PassContext()
        for p in self._passes:
            p.apply(main_programs, startup_programs, ctx)
        return ctx
