"""Parameter-sharding planning: maps a Layer tree onto the mesh.

Replaces reference fleet sharding/ZeRO (python/paddle/distributed/fleet/
meta_parallel/sharding) with GSPMD planning: each Parameter may carry a
`partition_spec` (set by meta_parallel layers); unannotated params are
FSDP-sharded along their largest divisible dim when the 'fsdp' axis is >1.
XLA then inserts all-gathers/reduce-scatters — ZeRO-3 semantics for free.
"""
import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.core import Parameter
from .mesh import get_mesh

__all__ = ["param_partition_spec", "plan_shardings", "shard_params", "constraint"]


def param_partition_spec(p, fsdp_size=1, min_fsdp_numel=2 ** 16, mesh=None):
    """Decide the PartitionSpec for one parameter value.

    With `mesh` given, requested axes that don't evenly divide their dim
    are dropped here at PLAN time (feasible_spec policy) — layers may
    annotate partition_spec before any mesh exists (e.g. ShardedEmbedding
    built before build_mesh) and still get a legal sharding."""
    spec = getattr(p, "partition_spec", None)
    shape = tuple(p.shape if hasattr(p, "shape") else np.shape(p))
    if spec is not None:
        spec = tuple(spec)
        if mesh is not None:
            spec = tuple(feasible_spec(shape, spec, mesh))
    else:
        spec = (None,) * len(shape)
    if fsdp_size > 1 and int(np.prod(shape)) >= min_fsdp_numel:
        # shard the largest dim not already taken, divisible by fsdp
        order = sorted(range(len(shape)), key=lambda i: -shape[i])
        for i in order:
            if spec[i] is None and shape[i] % fsdp_size == 0:
                spec = spec[:i] + ("fsdp",) + spec[i + 1:]
                break
    return PartitionSpec(*spec)


def plan_shardings(layer, mesh=None, fsdp_axis="fsdp"):
    """{param_name: NamedSharding} for every parameter + buffer of `layer`."""
    mesh = mesh or get_mesh()
    fsdp_size = mesh.shape.get(fsdp_axis, 1)
    plan = {}
    for name, p in layer.named_parameters():
        plan[name] = NamedSharding(mesh,
                                   param_partition_spec(p, fsdp_size,
                                                        mesh=mesh))
    for name, b in layer.named_buffers():
        plan[name] = NamedSharding(mesh, PartitionSpec())
    return plan


def shard_params(layer, mesh=None):
    """Physically device_put parameters according to the plan (eager path)."""
    mesh = mesh or get_mesh()
    plan = plan_shardings(layer, mesh)
    for name, p in list(layer.named_parameters()) + list(layer.named_buffers()):
        if name in plan and hasattr(p._value, "shape"):
            p._value = jax.device_put(p._value, plan[name])
    return plan


def feasible_spec(shape, spec, mesh):
    """Drop mesh axes from `spec` that do not evenly divide their dim.

    GSPMD rejects (or worse, silently pads) shardings whose axis-size
    product doesn't divide the dimension; eager constraints on user-sized
    batches (e.g. batch 2 on a dp=8 mesh) must degrade to replication
    instead of raising."""
    out = []
    for i, entry in enumerate(spec):
        if entry is None or i >= len(shape):
            out.append(None)
            continue
        axes = tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)
        kept, size = [], 1
        for a in axes:
            s = mesh.shape.get(a, 1)
            if s > 1 and shape[i] % (size * s) == 0:
                kept.append(a)
                size *= s
        out.append(tuple(kept) if len(kept) > 1 else (kept[0] if kept else None))
    return out


def constraint(x, *spec):
    """with_sharding_constraint on a Tensor/array with the global mesh.

    Axes that don't divide the tensor's dims are dropped (replicated)
    rather than raising, so model code can annotate unconditionally."""
    from ..framework.core import Tensor, apply_op

    mesh = get_mesh()
    v = x._value if isinstance(x, Tensor) else x
    shape = getattr(v, "shape", None)
    if shape is not None:
        spec = feasible_spec(shape, spec, mesh)
    sh = NamedSharding(mesh, PartitionSpec(*spec))
    if isinstance(x, Tensor):
        return apply_op(lambda u: jax.lax.with_sharding_constraint(u, sh), x)
    return jax.lax.with_sharding_constraint(v, sh)
